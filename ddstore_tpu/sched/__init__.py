"""Unified cost-model scheduler (ROADMAP item 5).

One measurement substrate (:mod:`.measure`, mirroring the native
``measure.h`` contract) feeds one planner (:mod:`.planner`) that models
delivered batch throughput as a joint function of route x lane width x
readahead depth x async admission width per traffic class, with every
pre-existing env knob acting as a user pin (:mod:`.knobs`)."""

from .knobs import PLANNED_KNOBS, REGISTRY, pinned_knobs
from .measure import (WARM_EWMA_ALPHA, WARM_MAX_COLD_SKIPS,
                      WARM_MIN_SAMPLES, ColdSkipBudget, Fold,
                      ProbeDiscard, SampleSet, WarmStat,
                      fold_warm_sample)
from .planner import (ASYNC_WIDTH_CAP, CostModel, Plan, Scheduler,
                      scheduler_enabled)

__all__ = [
    "ASYNC_WIDTH_CAP", "PLANNED_KNOBS", "REGISTRY", "WARM_EWMA_ALPHA",
    "WARM_MAX_COLD_SKIPS", "WARM_MIN_SAMPLES", "ColdSkipBudget",
    "CostModel", "Fold", "Plan", "ProbeDiscard", "SampleSet",
    "Scheduler", "WarmStat", "fold_warm_sample", "pinned_knobs",
    "scheduler_enabled",
]
