"""Warm-window measurement substrate — Python mirror of the native
contract (``native/measure.h``).

Three tuners used to carry private copies of the same sample hygiene
(the CMA/TCP router, the lane autotuner, the hand-tuned readahead
knobs); the rules now live in exactly two files that implement ONE
contract: ``native/measure.h`` for the in-transport tuners (they fold on
the read hot path and cannot call into Python) and this module for
host-side sample sources (the readahead engine's window-fetch timings,
the planner's delivered-throughput tracking). ``tests/test_sched.py``
pins the two implementations to each other: the EWMA-parity unit drives
this module with the router's historical fold traces and asserts
bit-equal estimates.

The contract, in fold order (see measure.h for the full rationale):

1. **Dial-taint discard** — a window that included a connection dial
   timed the handshake, not the transport; discarded while the cell has
   no clean sample, bounded by a per-tuner skip budget.
2. **First-window (warm-up) discard** — each cell's first surviving
   window timed the path waking, not running.
3. **Paired-probe discard** — a steady-state probe pair's first window
   only re-warms the idle path; the caller arms a one-shot discard the
   fold consumes.
4. **EWMA fold** — survivors fold at ``WARM_EWMA_ALPHA`` (the first
   sample seeds the estimate outright).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: Clean samples a cell needs before a verdict may be read off it
#: (mirrors ``kWarmMinSamples``).
WARM_MIN_SAMPLES = 2
#: Dial-taint discards allowed per tuner before tainted numbers are
#: accepted anyway (mirrors ``kWarmMaxColdSkips``).
WARM_MAX_COLD_SKIPS = 4
#: EWMA smoothing: new = alpha * old + (1 - alpha) * sample (mirrors
#: ``kWarmEwmaAlpha``).
WARM_EWMA_ALPHA = 0.5


class Fold(enum.Enum):
    """Outcome of one :func:`fold_warm_sample` (mirrors ``WarmFold``)."""

    FOLDED = 0
    DROP_COLD = 1
    DROP_WARMUP = 2
    DROP_PROBE = 3


@dataclass
class ColdSkipBudget:
    """Per-TUNER dial-taint discard budget (rule 1). Shared across a
    tuner's cells — not per-cell — so a flapping peer cannot spend the
    budget once per knob level."""

    skips: int = 0


@dataclass
class ProbeDiscard:
    """One-shot armed discard for the probe pair's warm-up window
    (rule 3). The caller arms it when dispatching the pair's first
    window; the fold consumes it."""

    armed: bool = False


@dataclass
class WarmStat:
    """One warm-window estimator cell: a (traffic class, knob value)
    pair's throughput estimate plus its hygiene state."""

    ewma: float = 0.0  # bytes/s estimate; 0 = no clean sample yet
    n: int = 0         # clean samples folded
    warmed: bool = False  # warm-up window consumed (rule 2)

    def reset(self) -> None:
        self.ewma = 0.0
        self.n = 0
        self.warmed = False


def fold_warm_sample(stat: WarmStat, value: float, cold: bool = False,
                     budget: Optional[ColdSkipBudget] = None,
                     discard: Optional[ProbeDiscard] = None) -> Fold:
    """Fold one measured window into ``stat`` under the shared hygiene
    contract. Keep in lockstep with ``FoldWarmSample`` in measure.h —
    rule ORDER included (cold, warm-up, probe, fold)."""
    if cold and stat.n == 0 and budget is not None \
            and budget.skips < WARM_MAX_COLD_SKIPS:
        budget.skips += 1
        return Fold.DROP_COLD
    if not stat.warmed:
        stat.warmed = True
        return Fold.DROP_WARMUP
    if discard is not None and discard.armed:
        discard.armed = False
        return Fold.DROP_PROBE
    stat.ewma = value if stat.ewma == 0.0 else \
        WARM_EWMA_ALPHA * stat.ewma + (1.0 - WARM_EWMA_ALPHA) * value
    stat.n += 1
    return Fold.FOLDED


@dataclass
class _TunerCells:
    budget: ColdSkipBudget = field(default_factory=ColdSkipBudget)
    cells: Dict[float, WarmStat] = field(default_factory=dict)


class SampleSet:
    """Host-side warm-window cells keyed by ``(source, cls, knob)``,
    with the dial-taint budget scoped per ``(source, cls)`` tuner —
    exactly the native tuners' budget scoping. Rows snapshot in the
    same layout as :meth:`NativeStore.sched_cells`, so the planner
    consumes native and host cells uniformly."""

    def __init__(self) -> None:
        self._tuners: Dict[Tuple[str, int], _TunerCells] = {}

    def fold(self, source: str, cls: int, knob: float, nbytes: int,
             secs: float, cold: bool = False) -> Fold:
        """Fold one ``nbytes``-over-``secs`` window into the cell.
        Non-positive measurements are rejected without touching hygiene
        state (same guard as the native record paths)."""
        if nbytes <= 0 or secs <= 0.0:
            return Fold.DROP_COLD
        tuner = self._tuners.setdefault((source, int(cls)), _TunerCells())
        stat = tuner.cells.setdefault(float(knob), WarmStat())
        return fold_warm_sample(stat, nbytes / secs, cold=cold,
                                budget=tuner.budget)

    def cell(self, source: str, cls: int,
             knob: float) -> Optional[WarmStat]:
        tuner = self._tuners.get((source, int(cls)))
        return tuner.cells.get(float(knob)) if tuner else None

    def cells(self) -> List[dict]:
        """Snapshot rows in :data:`ddstore_tpu.binding.SCHED_CELL_COLS`
        shape (``source`` kept as its string name)."""
        out: List[dict] = []
        for (source, cls), tuner in sorted(self._tuners.items()):
            for knob, stat in sorted(tuner.cells.items()):
                out.append({"source": source, "cls": cls, "knob": knob,
                            "ewma_bps": stat.ewma, "n": stat.n})
        return out

    def reset(self) -> None:
        self._tuners.clear()
