"""Cost-model scheduler: joint route x lanes x depth x width planning.

Before this module the port carried three INDEPENDENT warm-window
tuners — the CMA/TCP router, the per-class lane autotuner, and hand-set
readahead depth / async admission width — each optimizing its knob
blind to the others. The knobs are not independent: lane fan-out,
async admission and window depth all compete for the same cores (PR 5's
honest finding: on a 2-core box 1-lane fan-out alone oversubscribes the
CPU, and scatter forced to 4 lanes ran at 0.33x). This planner models
delivered batch throughput as one function of all four knobs per
traffic class and plans them together.

The model
---------

Per traffic class ``c`` (bulk / scatter), candidate route ``r`` and
lane width ``l``::

    T(c, r, l)      = B(c, r, l) * g(l)          predicted fetch bytes/s
    B(c, r, l)      = the substrate's measured EWMA for that cell when
                      it holds >= WARM_MIN_SAMPLES clean samples;
                      otherwise extrapolated from the nearest measured
                      width l0 of the same (c, r)
    g(l | l0)       = max(1, min(l / l0, cores / (l0 * peers)))
                      the CORE-BUDGET term: widening a stripe l0 -> l
                      scales linearly in the lane ratio only while idle
                      cores cover the extra streams; with cores <=
                      l0 * peers there is no headroom and the predicted
                      gain is exactly 1 — the no-headroom regime falls
                      out of the model, it is not special-cased.

Measured beats extrapolated: a width the substrate has really measured
uses its EWMA directly, which is how the PR 5 scatter result (4 lanes
measured at 0.33x of 1 lane) keeps scatter on 1 lane without any
special case. Ties break toward FEWER lanes (cheaper dispatch).

Depth and width close the loop on the same core budget::

    width = min(nvars * max(1, depth_req - 1),     reads the ring can
                max(1, cores // peers),            actually keep in
                ASYNC_WIDTH_CAP)                   flight vs. afford
    depth = min(depth_req, width + 1)

one window being consumed plus ``width`` concurrently fetching is the
most the admission gate lets the ring exploit; deeper rings only add
staging memory.

Pin semantics
-------------

Every pre-existing env knob is a PIN (:mod:`ddstore_tpu.sched.knobs`):
an explicitly-set ``DDSTORE_TCP_LANES`` / ``DDSTORE_CMA_*`` /
``DDSTORE_ASYNC_THREADS`` / ``DDSTORE_READAHEAD_DEPTH`` freezes that
knob at the user's value and the planner plans the rest. That is what
keeps every PR 1-5 contract byte-identical under the scheduler: the
lanes=1 identity tests, the chaos determinism runs and the forced-path
benches all pin the knobs they rely on.

Replanning
----------

The scheduler replans (and re-applies the unpinned knobs through the
native pin setters) on epoch boundaries, on degradation events
(``kErrPeerLost`` classification, a readahead/collective ladder
engagement) and on peer topology changes (``update_peer`` — which also
RESETS the native tuners and releases the planner pins, so the rebuilt
plan starts from fresh samples). Each replan's chosen knobs, predicted
throughput and trigger reason export through
``PipelineMetrics.summary()["sched"]``.
"""

from __future__ import annotations

import os
import threading
import warnings
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..binding import trace_emit, trace_enabled
from .knobs import pinned_knobs
from .measure import WARM_MIN_SAMPLES, SampleSet

#: Hard cap on the planned async admission width (mirrors the native
#: pool cap, kAsyncPoolCap).
ASYNC_WIDTH_CAP = 16

_ROUTE_SRC, _LANES_SRC = 0, 1
_CLS = {"bulk": 0, "scatter": 1}
#: Per-class route flip bands, mirroring the native router's
#: RouteClass.hysteresis: the planner's FIRST route verdict is a raw
#: argmax (the router's one-shot calibration), but overturning an
#: already-applied pin requires beating it by this factor — a raw
#: argmax re-applied every epoch would flap between near-equal paths,
#: exactly what the router's band exists to stop.
_ROUTE_HYSTERESIS = {"bulk": 1.25, "scatter": 1.10}


def scheduler_enabled(env: Optional[dict] = None) -> bool:
    """DDSTORE_SCHED gate: default on; \"0\" disables (independent
    tuners only — the PR 1-5 behavior)."""
    e = os.environ if env is None else env
    return e.get("DDSTORE_SCHED", "").strip() != "0"


@dataclass
class Plan:
    """One joint knob assignment. ``None`` = knob left to its adaptive
    tuner (insufficient samples) or frozen by a user pin (see
    ``pins``)."""

    route: Dict[str, Optional[str]] = field(
        default_factory=lambda: {"bulk": None, "scatter": None})
    lanes: Dict[str, Optional[int]] = field(
        default_factory=lambda: {"bulk": None, "scatter": None})
    depth: Optional[int] = None
    width: Optional[int] = None
    predicted_gbps: Dict[str, float] = field(default_factory=dict)
    pins: Dict[str, object] = field(default_factory=dict)
    #: Per-tenant QoS budgets ({tenant: {"width": w, "lanes": l}}),
    #: share-weighted splits of the planned width/lane cells — the
    #: tenancy layer rides the SAME plan, not a fourth tuner. Empty
    #: without configured shares.
    tenants: Dict[str, Dict[str, int]] = field(default_factory=dict)
    reason: str = ""
    #: True once apply() actually set at least one knob.
    engaged: bool = False


class CostModel:
    """The throughput model over the substrate's cells (module
    docstring). Pure and stateless beyond its geometry so the planner
    units can drive it with canned samples."""

    def __init__(self, cores: int, peers: int):
        self.cores = max(1, int(cores))
        self.peers = max(1, int(peers))

    def core_budget_gain(self, l0: int, l: int) -> float:
        """Extrapolated speedup of widening a stripe l0 -> l: linear in
        the lane ratio, capped by idle-core availability (and never a
        predicted LOSS — an unmeasured narrower width is not predicted
        to beat a measured one)."""
        if l <= l0:
            return 1.0
        want = l / l0
        have = self.cores / (l0 * self.peers)
        return max(1.0, min(want, have))

    def lane_throughput(self, cells: Dict[int, dict],
                        l: int) -> Optional[float]:
        """Predicted bytes/s at width ``l`` from the class's lane cells
        ({lane_count: row}). Measured widths (n >= WARM_MIN_SAMPLES)
        use their EWMA; unmeasured ones extrapolate from the nearest
        measured width below (or the nearest above, gain 1)."""
        measured = {k: c["ewma_bps"] for k, c in cells.items()
                    if c["n"] >= WARM_MIN_SAMPLES and c["ewma_bps"] > 0}
        if not measured:
            return None
        if l in measured:
            return measured[l]
        below = [k for k in measured if k < l]
        l0 = max(below) if below else min(measured)
        return measured[l0] * self.core_budget_gain(l0, l)

    def best_lanes(self, cells: Dict[int, dict]) -> Optional[int]:
        """argmax over the tuner's widths of the predicted throughput,
        ties toward fewer lanes. None without any measured cell."""
        if not cells:
            return None
        best, best_t = None, -1.0
        for l in sorted(cells):
            t = self.lane_throughput(cells, l)
            if t is None:
                return None
            if t > best_t * 1.0001:  # strict: ties keep fewer lanes
                best, best_t = l, t
        return best

    def plan_width(self, nvars: int, depth_req: int) -> int:
        useful = max(1, int(nvars)) * max(1, int(depth_req) - 1)
        affordable = max(1, self.cores // self.peers)
        return max(1, min(useful, affordable, ASYNC_WIDTH_CAP))

    def plan_depth(self, depth_req: int, width: int) -> int:
        return max(1, min(int(depth_req), int(width) + 1))


class Scheduler:
    """Owns the plan for one store + loader pairing. Thread-safe: the
    loader's workers report degradations concurrently with the consumer
    thread's epoch replans (replans serialize on an internal lock so
    the applied knobs always belong to ONE jointly computed plan).

    One ACTIVE scheduler per store is the supported shape — two
    enabled schedulers pinning the same store would overwrite each
    other's plans (last replan wins). The peer-change listener holds
    only a weak reference, so a scheduler (and its abandoned loader)
    is collectable and a dead one never replans.

    ``requested_depth`` is the readahead ring depth the owner budgets
    for; 0 means the owner runs NO readahead pipeline, and the
    scheduler then leaves the depth AND async-width knobs alone (a
    loader without readahead must not throttle the store's other
    async users)."""

    def __init__(self, store, nvars: int = 1,
                 requested_depth: int = 2,
                 enabled: Optional[bool] = None):
        self.store = store
        self.nvars = max(1, int(nvars))
        self.requested_depth = max(0, int(requested_depth))
        self.enabled = scheduler_enabled() if enabled is None \
            else bool(enabled)
        cores = os.cpu_count() or 1
        peers = max(1, store.world - 1) if store is not None else 1
        self.model = CostModel(cores, peers)
        # Host-side substrate cells: delivered window-fetch throughput
        # keyed by the depth it ran at (source "window"), plus the
        # per-tier cells (source "tier": hot-hit vs cold-miss fetch
        # legs) the prefetch planner reads.
        self.samples = SampleSet()
        self._tier_prefetch: Optional[int] = None
        self._mu = threading.Lock()
        self._replan_mu = threading.Lock()
        self._plan = Plan(pins=pinned_knobs())
        self.replans = 0
        self.reasons: List[str] = []
        # Same regime rule the lanes bench exports: client stripe legs
        # + serving threads of a 1-lane fan-out, + consumer + issuer.
        self.no_core_headroom = cores < 2 * peers + 2
        if store is not None and hasattr(store, "add_peer_listener"):
            wr = weakref.ref(self)

            def _on_peer_change():
                s = wr()
                if s is not None:
                    s.on_peer_change()

            # `alive` lets DDStore.update_peer prune the entry once the
            # scheduler is collected (listener lists on long-lived
            # stores must not grow one dead closure per discarded
            # loader).
            _on_peer_change.alive = lambda: wr() is not None
            store.add_peer_listener(_on_peer_change)

    # -- sample intake -----------------------------------------------------

    def observe_window(self, nbytes: int, secs: float,
                       cold: bool = False) -> None:
        """Fold one readahead window fetch (issue -> completion) into
        the host-side substrate, keyed by the depth it ran at. The
        engine's FIRST window of an epoch is `cold` (ring first-touch,
        lane dials) — the substrate's dial-taint rule discards it while
        the cell is unseeded, exactly like the native tuners."""
        depth = self._plan.depth or self.requested_depth or 1
        with self._mu:
            self.samples.fold("window", 0, depth, nbytes, secs, cold)

    def observe_tier(self, nbytes: int, secs: float, warmed: bool,
                     cold: bool = False) -> None:
        """Fold one window fetch into the PER-TIER read cells: knob 1 =
        hot-hit (the window was cache-warmed before issue, its fetch is
        an in-RAM gather), knob 0 = cold-miss (unwarmed — NVMe page
        faults / wire reads). Same warm-window hygiene as every other
        cell; ``planned_prefetch`` reads these to decide whether
        warming ahead is paying."""
        with self._mu:
            self.samples.fold("tier", 0, 1 if warmed else 0, nbytes,
                              secs, cold)

    def planned_prefetch(self, requested: int, window_bytes: int,
                         cache_bytes: int, depth: int) -> int:
        """The hot-cache warm-ahead depth (windows planned+prefetched
        beyond the one being issued) the readahead engine should run:
        the DDSTORE_TIER_PREFETCH_DEPTH pin wins outright; otherwise
        ``requested`` clamped to what the cache budget can actually
        hold (consumed-window entries evict as the pipeline advances,
        so ~``depth + prefetch`` windows are live at once), dropped to
        1 when the measured hot-hit cell shows no gain over cold-miss
        (warming that doesn't pay should not burn RAM and fill
        traffic)."""
        pins = pinned_knobs()
        if isinstance(pins.get("prefetch"), int):
            return max(0, int(pins["prefetch"]))
        if cache_bytes <= 0 or window_bytes <= 0:
            return 0
        fit = int(cache_bytes // window_bytes) - max(1, int(depth))
        p = max(0, min(int(requested), fit))
        if not self.enabled:
            return p
        with self._mu:
            hot = self.samples.cell("tier", 0, 1)
            cold = self.samples.cell("tier", 0, 0)
            if (hot is not None and cold is not None
                    and hot.n >= WARM_MIN_SAMPLES
                    and cold.n >= WARM_MIN_SAMPLES
                    and hot.ewma <= cold.ewma):
                p = min(p, 1)
            self._tier_prefetch = p
        return p

    # -- planning ----------------------------------------------------------

    def _native_cells(self) -> List[dict]:
        if self.store is None:
            return []
        try:
            return self.store.sched_cells()
        except Exception:
            return []

    def _wire_route(self) -> str:
        """The wire path's route label: "uring" when the store's
        io_uring wire loop is engaged, else "tcp". Both map to the
        same native route pin (knob 1)."""
        try:
            if self.store is not None and \
                    self.store.transport_facts().get("wire") == "uring":
                return "uring"
        except Exception:
            pass
        return "tcp"

    def compute(self, cells: Optional[List[dict]] = None) -> Plan:
        """Build (but do not apply) a joint plan from substrate cells.
        ``cells`` defaults to the live native snapshot; the planner
        units pass canned rows."""
        rows = self._native_cells() if cells is None else cells
        pins = pinned_knobs()
        plan = Plan(pins=pins)
        for name, cls in _CLS.items():
            route_cells = {int(r["knob"]): r for r in rows
                           if r["source"] == _ROUTE_SRC
                           and int(r["cls"]) == cls}
            lane_cells = {int(r["knob"]): r for r in rows
                          if r["source"] == _LANES_SRC
                          and int(r["cls"]) == cls}
            # Route: argmax over the two measured path cells. Left to
            # the adaptive router until both paths hold clean samples
            # (the router's own collection/calibration does that part).
            # The wire cell (knob 1) is one PATH with two possible
            # labels: "tcp", or "uring" when the io_uring wire loop is
            # engaged — the planner plans across {cma, tcp, uring}
            # with no fourth tuner (the ring batches the same wire
            # leg, so the same measurement cell covers it).
            wire = self._wire_route()
            if f"route_{name}" not in pins:
                cma = route_cells.get(0)
                wc = route_cells.get(1)
                if cma and wc and \
                        cma["n"] >= WARM_MIN_SAMPLES and \
                        wc["n"] >= WARM_MIN_SAMPLES:
                    cma_bw, wire_bw = cma["ewma_bps"], wc["ewma_bps"]
                    prev = self._plan.route.get(name)
                    h = _ROUTE_HYSTERESIS[name]
                    if prev is None:
                        pick = "wire" if wire_bw > cma_bw else "cma"
                    elif prev == "cma":
                        pick = "wire" if wire_bw > h * cma_bw else "cma"
                    else:  # previously on the wire path (tcp or uring)
                        pick = "cma" if cma_bw > h * wire_bw else "wire"
                    plan.route[name] = wire if pick == "wire" else "cma"
            # Lanes: model argmax (measured beats extrapolated; the
            # core-budget term caps unmeasured growth).
            if f"lanes_{name}" not in pins:
                plan.lanes[name] = self.model.best_lanes(lane_cells)
            best_l = plan.lanes[name] if plan.lanes[name] else 1
            t = self.model.lane_throughput(lane_cells, best_l) \
                if lane_cells else None
            if t is None and plan.route[name] is not None:
                rc = route_cells.get(
                    0 if plan.route[name] == "cma" else 1)
                t = rc["ewma_bps"] if rc else None
            if t:
                plan.predicted_gbps[name] = round(t / 1e9, 3)
        # Depth/width close over the same core budget — but ONLY for an
        # owner that actually runs a readahead pipeline
        # (requested_depth >= 1). A readahead-less loader has no
        # business setting the store's admission width: it would
        # silently throttle the store's other async users.
        if self.requested_depth >= 1:
            width = pins.get("width")
            if not isinstance(width, int):
                width = self.model.plan_width(self.nvars,
                                              self.requested_depth)
                plan.width = width
            depth = pins.get("depth")
            if not isinstance(depth, int):
                plan.depth = self.model.plan_depth(self.requested_depth,
                                                   width)
        # Per-tenant QoS budgets: share-weighted splits of the planned
        # (or pinned/live) width and the widest planned lane cell —
        # additional cells of the SAME joint plan. The async half is
        # enforced natively by the admission gate; the lane half is
        # applied through SetTenantLaneBudget in apply().
        shares = self._tenant_shares()
        if shares:
            from ..tenant import share_split

            width_base = plan.width if plan.width else \
                pins.get("width") if isinstance(pins.get("width"), int) \
                else self._live_width()
            lane_base = max([l for l in plan.lanes.values() if l] or
                            [self._live_lanes()])
            widths = share_split(max(1, int(width_base)), shares)
            lanes = share_split(max(1, int(lane_base)), shares)
            plan.tenants = {t: {"width": widths[t], "lanes": lanes[t]}
                            for t in shares}
        return plan

    def _tenant_shares(self) -> Dict[str, int]:
        """Configured QoS shares, read from the store's ledger (env or
        runtime setters). {} = tenancy not in play."""
        if self.store is None or not hasattr(self.store, "tenant_stats"):
            return {}
        try:
            stats = self.store.tenant_stats()
        except Exception:
            return {}
        # The share gauge is 0 for tenants that never ran
        # SetTenantShare (quota-only, snapshot-pin-only rows): only
        # EXPLICITLY configured tenants enter the split, so the
        # planner's denominator is the native gate's
        # async_share_total_ — sum of configured weights, even when
        # every configured weight is 1.
        shares = {t: int(row.get("share", 0)) for t, row in stats.items()}
        return {t: w for t, w in shares.items() if w > 0}

    def _live_width(self) -> int:
        try:
            return int(self.store.async_width)
        except Exception:
            return 1

    def _live_lanes(self) -> int:
        try:
            return int(self.store.lane_state().get("max_lanes", 1) or 1)
        except Exception:
            return 1

    def apply(self, plan: Plan) -> Plan:
        """Push the plan's unpinned knobs through the native setters.
        Knobs left ``None`` release the planner pin (the adaptive tuner
        owns them again)."""
        if self.store is None:
            return plan
        for name, cls in _CLS.items():
            if f"route_{name}" not in plan.pins:
                # "uring" shares the wire pin (1): the ring is a
                # different wire LOOP, not a different native route.
                mode = {-1: -1, "cma": 0, "tcp": 1, "uring": 1}[
                    plan.route[name] if plan.route[name] else -1]
                self.store.sched_pin_route(cls, mode)
                plan.engaged = plan.engaged or plan.route[name] is not None
            if f"lanes_{name}" not in plan.pins:
                self.store.sched_pin_lanes(
                    cls, plan.lanes[name] if plan.lanes[name] else -1)
                plan.engaged = plan.engaged or plan.lanes[name] is not None
        if plan.width is not None and "width" not in plan.pins:
            self.store.set_async_width(plan.width)
            plan.engaged = True
        if plan.depth is not None and "depth" not in plan.pins:
            plan.engaged = True  # consumed by the loader (planned_depth)
        if plan.tenants and hasattr(self.store, "set_tenant_lane_budget"):
            # Lane half of the tenant QoS budgets (the async half is
            # enforced natively by the share-aware admission gate).
            # Non-TCP backends never raise (the native call is a no-op
            # there), so any exception is a REAL failure — surface it
            # and do not record the budgets as engaged.
            applied = 0
            for tenant, budget in plan.tenants.items():
                try:
                    self.store.set_tenant_lane_budget(tenant,
                                                      budget["lanes"])
                    applied += 1
                except Exception as e:
                    warnings.warn(
                        f"tenant lane budget {tenant!r} not applied: "
                        f"{e}", RuntimeWarning, stacklevel=2)
            plan.engaged = plan.engaged or applied > 0
        return plan

    def replan(self, reason: str) -> Plan:
        """compute + apply + record — the single entry every trigger
        (epoch boundary, degradation, peer change) funnels through.
        Serialized: concurrent triggers (a worker's degradation vs the
        consumer's epoch boundary) must not interleave two plans' knob
        writes — the store would end up with a mixed assignment
        neither plan computed."""
        if not self.enabled:
            return self._plan
        with self._replan_mu:
            # ddtrace: the replan + its applied plan, next to the
            # transport events that motivated it.
            traced = trace_enabled()
            rank = -1
            if traced:
                if self.store is not None:
                    rank = int(getattr(self.store, "rank", -1) or 0)
                trace_emit("plan_replan", 0, rank, self.replans + 1)
            plan = self.apply(self.compute())
            plan.reason = reason
            with self._mu:
                self._plan = plan
                self.replans += 1
                if len(self.reasons) < 64:
                    self.reasons.append(reason)
            if traced:
                trace_emit("plan_applied", 0, rank, self.replans,
                           int(bool(plan.engaged)),
                           int(plan.depth or 0))
        return plan

    # -- triggers ----------------------------------------------------------

    def on_epoch(self) -> Plan:
        return self.replan("epoch")

    def on_degradation(self, what: str) -> Plan:
        """Ladder engagement / kErrPeerLost classification: the regime
        the plan was built for no longer holds."""
        return self.replan(f"degraded:{what}")

    def on_peer_change(self) -> Plan:
        """update_peer released the native pins and reset the tuners;
        rebuild (mostly releasing knobs until fresh samples land)."""
        return self.replan("peer_change")

    def on_admission_pressure(self, deferred: int, rejected: int) -> Plan:
        """Serving-gateway defer pressure crossed an epoch boundary:
        this job's reads were deferred (or shed outright) to protect a
        tenant's SLO, so the measured throughput the current plan is
        steering by includes queueing the plan did not choose. Replan —
        typically narrowing async width / lane spread so the gateway
        stops having to do the throttling for us."""
        if rejected > 0:
            return self.replan(f"admission:rejected={int(rejected)}")
        return self.replan(f"admission:deferred={int(deferred)}")

    # -- consumption -------------------------------------------------------

    def planned_depth(self, requested: int) -> int:
        """The readahead depth the loader should run this epoch: the
        user pin, else the plan, else the requested value — never above
        ``requested`` (the ring the caller budgeted for)."""
        self.requested_depth = max(1, int(requested))
        pins = self._plan.pins
        if isinstance(pins.get("depth"), int):
            # A user pin is explicit — it wins even above `requested`.
            return max(1, int(pins["depth"]))
        if self.enabled and self._plan.depth is not None:
            return max(1, min(self._plan.depth, self.requested_depth))
        return self.requested_depth

    def snapshot(self) -> Dict:
        """The ``summary()["sched"]`` payload: enablement, the current
        joint plan, predicted vs measured throughput, pins, replan
        triggers, the core-budget regime and the peer-liveness view the
        plan was built against (a dead peer's replan reason reads
        ``peer_change`` — the heartbeat detector fires the same
        listener elastic recovery does)."""
        suspected: List[int] = []
        if self.store is not None:
            try:
                suspected = [r for r, s in
                             enumerate(self.store.health_state()) if s]
            except Exception:
                suspected = []
        with self._mu:
            plan = self._plan
            # Measured side of predicted-vs-measured: the host
            # substrate's delivered window-fetch EWMA at the depth run.
            measured = 0.0
            cell = self.samples.cell(
                "window", 0, plan.depth or self.requested_depth)
            if cell is not None:
                measured = round(cell.ewma / 1e9, 3)
            # Per-tier read cells (tiered storage): the measured
            # hot-hit vs cold-miss window-fetch EWMAs and the warm-
            # ahead depth last planned from them.
            hot = self.samples.cell("tier", 0, 1)
            cold = self.samples.cell("tier", 0, 0)
            tier = {
                "hot_hit_gbps": round(hot.ewma / 1e9, 3)
                if hot is not None and hot.ewma else 0.0,
                "cold_miss_gbps": round(cold.ewma / 1e9, 3)
                if cold is not None and cold.ewma else 0.0,
                "prefetch": self._tier_prefetch,
            }
            return {
                "enabled": self.enabled,
                "engaged": plan.engaged,
                "plan": {"route": dict(plan.route),
                         "lanes": dict(plan.lanes),
                         "depth": plan.depth, "width": plan.width,
                         "tenants": {t: dict(b) for t, b in
                                     plan.tenants.items()}},
                "pins": dict(plan.pins),
                "predicted_gbps": dict(plan.predicted_gbps),
                "measured_window_gbps": measured,
                "replans": self.replans,
                "reasons": list(self.reasons),
                "no_core_headroom": self.no_core_headroom,
                "cores": self.model.cores,
                "peers": self.model.peers,
                "suspected_peers": suspected,
                "tier": tier,
            }
