"""Tunable-knob registry: every ``DDSTORE_*`` environment variable this
codebase documents, classified by how the cost-model scheduler treats
it.

The scheduler plans four knobs jointly (route x lanes x readahead depth
x async width); an env var that USED to be the only way to set one of
them is now a **pin** — explicitly setting it freezes that knob at the
user's value and the planner plans the rest. Everything else is plain
configuration the planner must not touch.

``tests/test_sched.py`` holds the drift guard: every ``DDSTORE_*`` name
appearing in README.md or MIGRATION.md must be registered here, so a
new knob cannot silently bypass the scheduler (it either pins a planned
knob or is consciously classified as config).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional

#: The jointly planned knobs (see :mod:`ddstore_tpu.sched.planner`).
PLANNED_KNOBS = ("route_bulk", "route_scatter", "lanes_bulk",
                 "lanes_scatter", "depth", "width", "prefetch")


@dataclass(frozen=True)
class Knob:
    env: str
    #: ``"pin"`` — setting this env freezes one of the planned knobs;
    #: ``"config"`` — plain configuration, never planned.
    kind: str
    #: Which :data:`PLANNED_KNOBS` entries an explicit value freezes
    #: (pins only).
    pins: tuple = ()
    description: str = ""


def _k(env: str, kind: str, pins: tuple = (), desc: str = "") -> Knob:
    return Knob(env, kind, pins, desc)


#: env name -> Knob. Keep sorted within each block.
REGISTRY: Dict[str, Knob] = {k.env: k for k in [
    # -- pins of planned knobs -------------------------------------------
    _k("DDSTORE_ASYNC_THREADS", "pin", ("width",),
       "async admission width; unset = 4/2/1 core ladder, planned"),
    _k("DDSTORE_CMA_BULK", "pin", ("route_bulk",),
       "1 = force CMA, 0 = force TCP for bulk reads"),
    _k("DDSTORE_CMA_SCATTER", "pin", ("route_scatter",),
       "1 = force CMA, 0 = force TCP for scatter reads"),
    _k("DDSTORE_CONNS_PER_PEER", "pin", ("lanes_bulk", "lanes_scatter"),
       "legacy alias of DDSTORE_TCP_LANES"),
    _k("DDSTORE_READAHEAD_DEPTH", "pin", ("depth",),
       "readahead windows in flight; unset = planned (bounded by the "
       "loader's readahead_windows argument)"),
    _k("DDSTORE_TCP_LANES", "pin", ("lanes_bulk", "lanes_scatter"),
       "per-peer connection pool size; explicit value pins stripe "
       "width"),
    _k("DDSTORE_TCP_LANES_AUTOTUNE", "pin",
       ("lanes_bulk", "lanes_scatter"),
       "0 pins striping at the full pool size"),
    _k("DDSTORE_TIER_PREFETCH_DEPTH", "pin", ("prefetch",),
       "hot-cache warm-ahead depth (windows planned + prefetched "
       "beyond the one being issued); unset = planned from the cache "
       "budget and the measured hot-hit/cold-miss cells; 0 disables "
       "warming"),
    # -- configuration (never planned) -----------------------------------
    _k("DDSTORE_BACKEND", "config", desc="local/tcp backend select"),
    _k("DDSTORE_BARRIER_TIMEOUT_S", "config"),
    _k("DDSTORE_BENCH_DEADLINE_S", "config"),
    _k("DDSTORE_BENCH_PHASE_TIMEOUT_S", "config"),
    _k("DDSTORE_BENCH_PROBE_TIMEOUT_S", "config"),
    _k("DDSTORE_BENCH_SKIP_PROBE", "config"),
    _k("DDSTORE_CHAOS_PHASE_TIMEOUT_S", "config"),
    _k("DDSTORE_CMA", "config", desc="0 disables the CMA fast path "
       "entirely (a capability switch, not a per-class preference)"),
    _k("DDSTORE_CONNECT_TIMEOUT_S", "config"),
    _k("DDSTORE_CONTROL_RETRY_MAX", "config",
       desc="bounded retry budget for control-plane round trips "
            "(var-seq probes, row-sum fetches, snapshot pin "
            "placement); default 2; the suspect oracle short-circuits "
            "a detector-declared-dead peer before any attempt"),
    _k("DDSTORE_CONTROL_TIMEOUT_MS", "config",
       desc="per-attempt deadline (ms) for control-plane round trips; "
            "default 1000 — replaces the old hardcoded one-shot "
            "1000/5000 ms kOpVarSeq/kOpRowSums timeouts (bulk row-sum "
            "fetches run at 5x this value per attempt, preserving the "
            "old window at the default)"),
    _k("DDSTORE_COORDINATOR", "config"),
    _k("DDSTORE_CXX", "config",
       desc="C++ compiler for the on-demand native build (default g++)"),
    _k("DDSTORE_DEBUG", "config"),
    _k("DDSTORE_DRYRUN_TIMEOUT_S", "config"),
    _k("DDSTORE_FAILOVER_PHASE_TIMEOUT_S", "config"),
    _k("DDSTORE_FAULT_RANKS", "config"),
    _k("DDSTORE_FAULT_SEED", "config"),
    _k("DDSTORE_FAULT_SPEC", "config"),
    _k("DDSTORE_GATEWAY", "config",
       desc="1 arms the serving gateway: kOpAttach/kOpLease sessions, "
            "histogram-driven admission in front of Get/GetBatch/"
            "ReadRuns (over-share tenants deferred then refused with "
            "ERR_ADMISSION + retry-after), lease reaping, drain; "
            "default 0, pinned byte-, error-code- and seeded-fault-"
            "counter-identical to the ungated tree"),
    _k("DDSTORE_GATEWAY_PHASE_TIMEOUT_S", "config",
       desc="bench gateway-phase subprocess cap, default 300"),
    _k("DDSTORE_GW_ADMIT_MARGIN", "config",
       desc="admission margin in percent of each protected tenant's "
            "SLO threshold (default 80): over-share reads defer once "
            "predicted p99 = live-histogram p99 x (1 + async queue "
            "depth) crosses threshold x margin/100"),
    _k("DDSTORE_GW_DEFER_MS", "config",
       desc="bounded deferral window before an over-share read is "
            "refused with ERR_ADMISSION (default 100); the refusal's "
            "retry-after hint scales with queue pressure"),
    _k("DDSTORE_GW_LANE_SHARE", "config",
       desc="QoS lane-budget share armed for a gateway tenant's first "
            "session and cleared at its last detach (default 0 = "
            "leave lane budgets to DDSTORE_TENANT_SHARES/scheduler)"),
    _k("DDSTORE_GW_LEASE_MS", "config",
       desc="gateway session lease (default 5000): client renews at "
            "~lease/3; expiry atomically releases the session's "
            "snapshot pins, quota reservation and lane share — the "
            "SIGKILL-safety bound"),
    _k("DDSTORE_GW_QUEUE", "config",
       desc="bounded admission deferral queue per rank (default 64); "
            "a full queue refuses immediately"),
    _k("DDSTORE_GW_RETRY_MAX", "config",
       desc="client-side ERR_ADMISSION retry budget per read in "
            "GatewaySession (default 8), each retry sleeping the "
            "server's retry-after hint with seeded jitter"),
    _k("DDSTORE_HEARTBEAT_MS", "config",
       desc="heartbeat ping interval (ms); unset = 250 when "
            "DDSTORE_REPLICATION > 1, else off; 0 disables"),
    _k("DDSTORE_HEARTBEAT_SUSPECT_N", "config",
       desc="consecutive missed pings before a peer is suspected "
            "(default 3)"),
    _k("DDSTORE_HOST", "config"),
    _k("DDSTORE_IFACES", "config"),
    _k("DDSTORE_INTEGRITY_PHASE_TIMEOUT_S", "config",
       desc="bench integrity-phase subprocess cap, default 300"),
    _k("DDSTORE_LANES_PHASE_TIMEOUT_S", "config"),
    _k("DDSTORE_METHOD", "config"),
    _k("DDSTORE_METRICS", "config",
       desc="0 disables the always-on ddmetrics latency/bytes "
            "histograms (default 1: per-store log2-bucketed cells per "
            "(op class, route, peer, reading tenant), updated at op "
            "end with relaxed atomic increments — live p50/p90/p99 in "
            "summary()['latency'] without tracing)"),
    _k("DDSTORE_NUM_PROCESSES", "config",
       desc="explicit pod size for pod_bootstrap (with "
            "DDSTORE_COORDINATOR/DDSTORE_PROCESS_ID)"),
    _k("DDSTORE_OP_DEADLINE_S", "config"),
    _k("DDSTORE_PEAK_FLOPS", "config"),
    _k("DDSTORE_POD_AUTODETECT", "config"),
    _k("DDSTORE_POOL_THREADS", "config"),
    _k("DDSTORE_PPSCHED_PHASE_TIMEOUT_S", "config"),
    _k("DDSTORE_PROCESS_ID", "config",
       desc="explicit pod process index for pod_bootstrap"),
    _k("DDSTORE_RANK", "config"),
    _k("DDSTORE_RDV_DIR", "config"),
    _k("DDSTORE_RDV_ID", "config"),
    _k("DDSTORE_REPLICATION", "config",
       desc="R-way shard replication: each rank mirrors the next R-1 "
            "ranks' shards, reads fail over transparently; default 1 "
            "(off, byte-identical to the unreplicated tree); RAM cost "
            "is R x the dataset"),
    _k("DDSTORE_READ_TIMEOUT_S", "config"),
    _k("DDSTORE_RETRY_BASE_MS", "config"),
    _k("DDSTORE_RETRY_MAX", "config"),
    _k("DDSTORE_SANITIZE", "config"),
    _k("DDSTORE_SCRUB_MS", "config",
       desc="background integrity scrubber: one resident mirror "
            "checked against its owner's published checksums per tick "
            "(ms), divergent mirrors re-pulled; default 0 (off)"),
    _k("DDSTORE_SCHED", "config",
       desc="0 disables the cost-model scheduler (independent tuners "
            "only); default on"),
    _k("DDSTORE_SCHED_PHASE_TIMEOUT_S", "config"),
    _k("DDSTORE_SLO_PHASE_TIMEOUT_S", "config",
       desc="bench slo-phase subprocess cap, default 300"),
    _k("DDSTORE_SLO_WINDOW_MS", "config",
       desc="minimum spacing between SLO evaluations (ms): an "
            "evaluate_slos() call inside the window is a no-op that "
            "keeps the running delta window intact; default 0 = every "
            "call evaluates"),
    _k("DDSTORE_SNAP_PIN_TTL_MS", "config",
       desc="TTL for stranded snapshot pins (default 0 = off): the "
            "reaper releases a pin whose owner is suspected dead or "
            "whose age passed the TTL, counting snapshot_stats()"
            "['reclaimed_pins'] — works with the gateway off"),
    _k("DDSTORE_SOAK_BUDGET_S", "config"),
    _k("DDSTORE_SOAK_PHASE_TIMEOUT_S", "config"),
    _k("DDSTORE_TENANTS_PHASE_TIMEOUT_S", "config",
       desc="bench tenants-phase subprocess cap, default 300"),
    _k("DDSTORE_TENANT_QUOTAS", "config",
       desc="per-tenant registration budgets 't=bytes[:vars],...' "
            "(< 0 = unlimited); an over-budget add/init is refused "
            "with ERR_QUOTA (-11), a distinct non-fatal class"),
    _k("DDSTORE_TIER_CACHE_BYTES", "config",
       desc="hot-row cache byte budget (default 0 = off, the whole "
            "tiering tree inert and byte-identical); size it to hold "
            "(ring depth + prefetch depth + 1) readahead windows of "
            "the active variables"),
    _k("DDSTORE_TIER_COLD_DIR", "config",
       desc="directory for cold-tier file-backed allocations (mirror "
            "fills / snapshot kept copies placed 'cold'); files are "
            "created unlinked, so crashes cannot leak disk"),
    _k("DDSTORE_TIER_PLACEMENT", "config",
       desc="per-tenant mirror/kept-copy placement "
            "'tenant=cold|hot,...' (a bare 'cold' names the default "
            "tenant); default hot — cold requires "
            "DDSTORE_TIER_COLD_DIR"),
    _k("DDSTORE_TIERED_PHASE_TIMEOUT_S", "config",
       desc="bench tiered-phase subprocess cap, default 300"),
    _k("DDSTORE_TENANT_SLOS", "config",
       desc="per-tenant latency objectives 't=p99:5ms,...' (a bare "
            "'p99:5ms' names the default tenant; units ns/us/ms/s) "
            "evaluated per epoch window over the live ddmetrics "
            "histograms — a breach emits an slo_breach trace event, "
            "dumps the flight recorder and replans the tenant's "
            "routes/lanes/shares; default unset = monitor inert"),
    _k("DDSTORE_TENANT_SHARES", "config",
       desc="per-tenant QoS weights 't=weight,...': async admission "
            "is share-split (each tenant runs at most max(1, width * "
            "share / total) concurrent async reads) and the scheduler "
            "plans matching per-tenant lane budgets"),
    _k("DDSTORE_TRACE", "config",
       desc="1 enables the ddtrace event rings at load (default off: "
            "one relaxed load per instrumentation site, frames "
            "byte-identical to the untraced tree)"),
    _k("DDSTORE_TRACE_FLIGHT", "config",
       desc="flight-recorder snapshot bound in events (default 16384)"),
    _k("DDSTORE_TRACE_PHASE_TIMEOUT_S", "config",
       desc="bench trace-phase subprocess cap, default 300"),
    _k("DDSTORE_TRACE_RING", "config",
       desc="per-thread trace ring capacity in events (default 4096); "
            "overflow overwrites oldest and counts a drop"),
    _k("DDSTORE_TRANSPORT", "config",
       desc="wire backend inside backend='tcp': 'tcp' (default) or "
            "'uring' — the io_uring batch loop (one io_uring_enter "
            "per frame burst; probe-gated with loud TCP fallback, "
            "byte-identical wire stream either way)"),
    _k("DDSTORE_UDS", "config"),
    _k("DDSTORE_URING_COLD", "config",
       desc="O_DIRECT serving of readonly cold (tier-1) shards "
            "through the submission ring: 1/0 force on/off; 'auto' "
            "(default) follows the uring wire backend's engagement"),
    _k("DDSTORE_URING_DEPTH", "config",
       desc="SQ entries per lane ring (default 256, clamped to "
            "[64, 4096]); bounds the frames one io_uring_enter can "
            "carry"),
    _k("DDSTORE_URING_PHASE_TIMEOUT_S", "config",
       desc="bench uring-phase subprocess cap, default 300"),
    _k("DDSTORE_URING_REGBUF", "config",
       desc="0 disables IORING_REGISTER_BUFFERS/READ_FIXED for the "
            "cold-tier bounce buffer (default 1; refusal falls back "
            "to plain IORING_OP_READ silently)"),
    _k("DDSTORE_VERIFY", "config",
       desc="1 = checksum-verify every remote read leg against the "
            "owner's published per-row sums (mismatch -> transient "
            "seq retry -> one primary retry -> replica chain -> "
            "ERR_CORRUPT); default 0, pinned byte-, error-code- and "
            "seeded-fault-counter-identical to the unverified tree"),
    _k("DDSTORE_VERIFY_SEED", "config",
       desc="seed of the per-row checksum function (must agree across "
            "ranks; default 0)"),
    _k("DDSTORE_WORLD", "config"),
]}


def _int_env(name: str) -> Optional[int]:
    v = os.environ.get(name, "").strip()
    if not v:
        return None
    try:
        return int(v)
    except ValueError:
        return None


def pinned_knobs(env: Optional[dict] = None) -> Dict[str, object]:
    """The planned knobs the USER froze via env vars, with their pinned
    values — the planner plans everything NOT in this dict.

    Returns a subset of :data:`PLANNED_KNOBS` keys: routes map to
    ``"cma"``/``"tcp"``, lanes to an int width (``"pool"`` when only
    autotune was turned off — pinned at the pool size), depth/width to
    ints."""
    e = os.environ if env is None else env
    pins: Dict[str, object] = {}
    for cls, var in (("route_bulk", "DDSTORE_CMA_BULK"),
                     ("route_scatter", "DDSTORE_CMA_SCATTER")):
        v = e.get(var, "").strip()
        if v.startswith("1"):
            pins[cls] = "cma"
        elif v.startswith("0"):
            pins[cls] = "tcp"
    lanes = None
    for var in ("DDSTORE_TCP_LANES", "DDSTORE_CONNS_PER_PEER"):
        v = e.get(var, "").strip()
        if v:
            try:
                lanes = int(v)
            except ValueError:
                lanes = None
            break
    if lanes is not None:
        pins["lanes_bulk"] = pins["lanes_scatter"] = lanes
    elif e.get("DDSTORE_TCP_LANES_AUTOTUNE", "").strip() == "0":
        # Autotune off with no explicit width: striping is pinned at
        # the (core-ladder) pool size — still a user decision the
        # planner must not override.
        pins["lanes_bulk"] = pins["lanes_scatter"] = "pool"
    v = e.get("DDSTORE_ASYNC_THREADS", "").strip()
    if v:
        try:
            pins["width"] = int(v)
        except ValueError:
            pass
    v = e.get("DDSTORE_READAHEAD_DEPTH", "").strip()
    if v:
        try:
            pins["depth"] = int(v)
        except ValueError:
            pass
    v = e.get("DDSTORE_TIER_PREFETCH_DEPTH", "").strip()
    if v:
        try:
            pins["prefetch"] = int(v)
        except ValueError:
            pass
    return pins
