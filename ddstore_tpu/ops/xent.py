"""Fused linear + softmax cross-entropy (the LM-head hot path).

At LM scale the head is the single largest tensor in the step: logits are
``(tokens, vocab)`` — 2 GiB in f32 at 16 Ki tokens x 32 Ki vocab — and the
standard ``logits = x @ W; log_softmax`` pipeline writes them to HBM in the
forward AND re-materializes ``dlogits`` in the backward. This op computes
the same per-token negative log-likelihood by streaming the vocab dimension
in blocks through an online logsumexp, so peak memory is ``(tokens,
block)`` instead of ``(tokens, vocab)`` and the logits never round-trip
HBM. The backward recomputes each logits block from the saved activations
(flash-attention-style rematerialization: trade one extra matmul pass for
the 2x logits traffic).

The matmuls stay large, static and MXU-shaped (``jnp.dot`` with f32
accumulation, vocab blocks of a few thousand columns), the scan is a
``lax.scan`` over a static block count — exactly the control flow XLA
pipelines well on TPU.

Reference parity note: the reference has no model math at all (its model
is the example VAE, /root/reference/examples/vae/vae-ddp.py:174-200); this
op exists for the long-context LM flagship that SURVEY §2.2/§7 adds on
top.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = float("-inf")


def _num_blocks(v: int, block: int) -> int:
    return -(-v // block)


def _block_cols(w: jax.Array, i: int, block: int) -> jax.Array:
    """Columns ``[i*block, (i+1)*block)`` of ``w``, zero-padded past V.

    ``lax.dynamic_slice`` clamps out-of-range starts, which would silently
    alias the last in-range block; pad once instead so every block is a
    real slice.
    """
    return jax.lax.dynamic_slice_in_dim(w, i * block, block, axis=1)


def _pad_cols(w: jax.Array, block: int) -> jax.Array:
    v = w.shape[1]
    pad = _num_blocks(v, block) * block - v
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
    return w


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_linear_xent(x: jax.Array, w: jax.Array, targets: jax.Array,
                      block: int = 8192,
                      compute_dtype: Optional[jnp.dtype] = None
                      ) -> jax.Array:
    """Per-token NLL of ``softmax(x @ w)`` without materializing logits.

    Args:
      x: ``(n, d)`` activations (any float dtype).
      w: ``(d, v)`` head kernel.
      targets: ``(n,)`` int class ids in ``[0, v)``.
      block: vocab-block width (static; clamped to ``v``).
      compute_dtype: dtype the matmul operands are cast to (accumulation
        is always f32). Default: ``x.dtype``.

    Returns ``(n,)`` f32 negative log-likelihoods; ``nll.mean()`` equals
    ``loss_fn(x @ w, targets)`` of the unfused path up to summation order.
    Differentiable in ``x`` and ``w``.
    """
    nll, _ = _fwd(x, w, targets, block, compute_dtype)
    return nll


def _logits_block(x, wp, i, block, v, compute_dtype):
    """Logits for vocab block ``i`` from the PADDED kernel ``wp``; columns
    past the true vocab size ``v`` are masked to -inf."""
    dt = compute_dtype or x.dtype
    wb = _block_cols(wp, i, block)
    lg = jnp.dot(x.astype(dt), wb.astype(dt),
                 preferred_element_type=jnp.float32)
    col = i * block + jnp.arange(block)
    return jnp.where(col[None, :] < v, lg, NEG_INF)


def _fwd(x, w, targets, block, compute_dtype):
    n, _ = x.shape
    v = w.shape[1]
    block = min(block, v)
    nb = _num_blocks(v, block)
    wp = _pad_cols(w, block)
    rows = jnp.arange(n)

    def body(carry, i):
        m, l, tl = carry
        lg = _logits_block(x, wp, i, block, v, compute_dtype)
        bm = jnp.max(lg, axis=-1)
        m_new = jnp.maximum(m, bm)
        # exp(-inf - -inf) can't occur: m_new >= bm > -inf whenever any
        # real column exists in the block, and m starts finite-safe below.
        l = l * jnp.exp(m - m_new) + jnp.exp(lg - m_new[:, None]).sum(-1)
        t_local = targets - i * block
        in_blk = (t_local >= 0) & (t_local < block)
        picked = lg[rows, jnp.clip(t_local, 0, block - 1)]
        tl = jnp.where(in_blk, picked, tl)
        return (m_new, l, tl), None

    init = (jnp.full((n,), -1e30, jnp.float32),  # finite: avoids inf-inf
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, l, tl), _ = jax.lax.scan(body, init, jnp.arange(nb))
    lse = m + jnp.log(l)
    nll = lse - tl
    return nll, (x, w, targets, lse)


def _bwd(block, compute_dtype, res, g):
    x, w, targets, lse = res
    n, d = x.shape
    v = w.shape[1]
    block = min(block, v)
    nb = _num_blocks(v, block)
    wp = _pad_cols(w, block)
    rows = jnp.arange(n)
    gcol = g[:, None].astype(jnp.float32)
    dt = compute_dtype or x.dtype

    def body(dx, i):
        lg = _logits_block(x, wp, i, block, v, compute_dtype)
        p = jnp.exp(lg - lse[:, None])  # softmax block; 0 at padded cols
        t_local = targets - i * block
        in_blk = (t_local >= 0) & (t_local < block)
        onehot = (jnp.arange(block)[None, :]
                  == jnp.clip(t_local, 0, block - 1)[:, None])
        p = p - jnp.where(in_blk[:, None], onehot, False)
        dlg = (p * gcol).astype(dt)
        wb = _block_cols(wp, i, block)
        dx = dx + jnp.dot(dlg, wb.astype(dt).T,
                          preferred_element_type=jnp.float32)
        dwb = jnp.dot(x.astype(dt).T, dlg,
                      preferred_element_type=jnp.float32)
        # dw comes back as stacked per-block ys — carrying the full (d, v)
        # buffer through the scan would stream it through HBM every
        # iteration.
        return dx, dwb

    dx, dws = jax.lax.scan(body, jnp.zeros((n, d), jnp.float32),
                           jnp.arange(nb))
    dw = jnp.moveaxis(dws, 0, 1).reshape(d, nb * block)[:, :v]
    return (dx.astype(x.dtype), dw.astype(w.dtype), None)


fused_linear_xent.defvjp(_fwd, _bwd)
