"""Attention ops: Pallas TPU flash attention + XLA reference.

The building block for long-context support (sequence/context parallelism
is absent in the reference — SURVEY §2.2 — and a first-class goal here).
Both implementations return ``(out, lse)`` where ``lse`` is the per-query
log-sum-exp of the attention scores: that pair is the composable unit —
:func:`ddstore_tpu.parallel.ring_attention.ring_attention` combines
``(out, lse)`` blocks across devices with the same online-softmax algebra
the kernel uses across key blocks.

Design notes (TPU):
* the kernel streams K/V blocks through VMEM with a running (m, l, acc)
  online softmax in f32 scratch — O(S) memory, no S×S materialization;
* QK^T and PV ride the MXU via ``jnp.dot`` with f32 accumulation;
* causal masking takes global ``q_offset``/``kv_offset`` so the same
  kernel serves ring-attention steps, where the kv chunk's global
  position rotates per step;
* on CPU (tests) the identical kernel runs in interpreter mode.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

try:  # pallas is TPU/interpret-only; keep the module importable anywhere
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

NEG_INF = float("-inf")


def _causal_liveness(iq, ik, block_q, block_k, q_offset, kv_offset):
    """(live, diag) for a causal (q-block, k-block) pair: ``live`` = the
    block has any unmasked entry; ``diag`` = it straddles the diagonal
    and needs the iota mask (blocks entirely in the past are mask-free —
    the mask's compare/select is pure VPU cost). THE single classification
    shared by the forward and both backward kernels."""
    q_lo = q_offset + iq * block_q
    k_lo = kv_offset + ik * block_k
    live = k_lo <= q_lo + block_q - 1
    diag = live & (k_lo + block_k - 1 > q_lo)
    return live, diag


def _masked_dispatch(causal, live, diag, update):
    """Run ``update(masked)`` under the liveness predicates: the diagonal
    body with masking, interior live blocks without, dead blocks not at
    all (non-causal: one unmasked body, unconditionally)."""
    if causal:
        pl.when(diag)(lambda: update(True))
        pl.when(live & ~diag)(lambda: update(False))
    else:
        update(False)


def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array, *,
                  causal: bool = False, q_offset: int = 0,
                  kv_offset: int = 0, scale: Optional[float] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """Plain-XLA attention over (..., S, D); returns (out, lse in f32)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("...qd,...kd->...qk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[-2])[:, None]
        kpos = kv_offset + jnp.arange(k.shape[-2])[None, :]
        s = jnp.where(kpos <= qpos, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    # Fully-masked rows (possible in ring steps) must yield out=0, lse=-inf
    # without NaNs: exp(-inf - -inf) is guarded by zeroing those rows.
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - safe_m)
    p = jnp.where(jnp.isfinite(m), p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("...qk,...kd->...qd", p, v,
                     preferred_element_type=jnp.float32)
    out = out / jnp.maximum(l, 1e-30)
    lse = (safe_m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
    lse = jnp.where(jnp.isfinite(m[..., 0]), lse, NEG_INF)
    return out.astype(q.dtype), lse


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                  *, scale, causal, q_offset, kv_offset, block_q, block_k):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # For causal, a K/V block entirely in the future contributes nothing —
    # predicate the whole accumulation away (≈halves causal FLOPs). Blocks
    # entirely in the PAST need no mask either: the iota/compare/select on
    # a (block_q, block_k) tile is pure VPU work and the kernel is
    # VPU-bound, so interior blocks take a mask-free body and only the
    # O(S/block) diagonal-straddling blocks pay for masking.
    if causal:
        live, diag = _causal_liveness(iq, ik, block_q, block_k, q_offset,
                                      kv_offset)
    else:
        live, diag = True, False

    def update(masked):
        q = q_ref[0]  # (block_q, D)
        k = k_ref[0]  # (block_k, D)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale

        if masked:
            qpos = q_offset + iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kv_offset + ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)

        m_prev = m_scr[:, :1]                               # (block_q, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Rows with everything masked so far keep m=-inf; safe_m keeps the
        # subtraction finite and exp(-inf - 0) = 0 zeroes their p exactly
        # (no full-block select needed).
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - safe_m)
        corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - safe_m), 0.0)
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    _masked_dispatch(causal, live, diag, update)

    @pl.when(ik == nk - 1)
    def _():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        m = m_scr[:, :1]
        lse = jnp.where(jnp.isfinite(m),
                        m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)
        lse_ref[0] = jnp.broadcast_to(lse, (block_q, 128))


# Grid-step overhead on TPU is ~0.3us and steps run sequentially per core,
# so blocks must be big enough that the MXU work dominates: 512x2048 blocks
# measured 100.8 TF/s vs 12.8 TF/s at 128x128 on v5e (7.0x over XLA's 14.4).
_SEMS = ("parallel", "parallel", "arbitrary")


def _tpu_params(interpret):
    if interpret or not _HAS_PALLAS:
        return {}
    return {"compiler_params": pltpu.CompilerParams(
        dimension_semantics=_SEMS)}


def _fwd_impl(q, k, v, causal, q_offset, kv_offset, scale, block_q, block_k,
              interpret):
    """Runs the forward kernel; returns (out, lse, lse128-residual)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    grid = (b * h, sq // block_q, sk // block_k)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, q_offset=q_offset,
        kv_offset=kv_offset, block_q=block_q, block_k=block_k)
    out_f, lse_f = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            # lse carries a broadcast 128-lane dim purely so its block is
            # (block_q, 128)-tile-aligned for the TPU lowering; lane 0 is
            # the value. The full tensor doubles as the backward residual.
            pl.BlockSpec((1, block_q, 128), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denom
            pltpu.VMEM((block_q, d), jnp.float32),    # running numerator
        ],
        interpret=interpret,
        **_tpu_params(interpret),
    )(qf, kf, vf)
    return (out_f.reshape(b, h, sq, d), lse_f[..., 0].reshape(b, h, sq),
            lse_f)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, dta_ref, dq_ref,
                   dq_acc, *, scale, causal, q_offset, kv_offset, block_q,
                   block_k):
    """dq for one q block, streaming k/v blocks (recompute-p flash bwd).

    ``dta`` packs the per-row residual scalars into one 128-lane tensor
    (lane 0 = c = delta - dlse with delta = rowsum(do*o); lane 1 = lse):
    one streamed side input instead of two."""
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    if causal:
        live, diag = _causal_liveness(iq, ik, block_q, block_k, q_offset,
                                      kv_offset)
    else:
        live, diag = True, False

    def update(masked):
        q = q_ref[0]
        k = k_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if masked:
            qpos = q_offset + iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kv_offset + ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        lse = dta_ref[0][:, 1:2]                             # (block_q, 1)
        # Fully-masked rows have lse = -inf; exp(s - safe_lse) is then
        # exp(-inf - big) = 0 for every column — no full-block select.
        safe_lse = jnp.where(jnp.isfinite(lse), lse, 1e30)
        p = jnp.exp(s - safe_lse)
        do = do_ref[0]
        dp = jnp.dot(do, v_ref[0].T, preferred_element_type=jnp.float32)
        # ds = p * (dp - c) with c = delta - dlse packed in lane 0.
        t = p * (dp - dta_ref[0][:, :1])
        dq_acc[:] = dq_acc[:] + jnp.dot(
            t.astype(k.dtype), k, preferred_element_type=jnp.float32) * scale

    _masked_dispatch(causal, live, diag, update)

    @pl.when(ik == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, dta_ref, dk_ref,
                    dv_ref, dk_acc, dv_acc, *, scale, causal, q_offset,
                    kv_offset, block_q, block_k):
    """dk/dv for one k/v block, streaming q blocks.

    The q-side streams (q, do, dta) re-fetch every grid step here (their
    block index rides the innermost loop), so the packed single ``dta``
    side input (c = delta - dlse in lane 0, lse in lane 1) halves the
    f32 side-stream HBM traffic vs separate lse + dta tensors."""
    ik, iq = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    if causal:
        live, diag = _causal_liveness(iq, ik, block_q, block_k, q_offset,
                                      kv_offset)
    else:
        live, diag = True, False

    def update(masked):
        q = q_ref[0]
        k = k_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if masked:
            qpos = q_offset + iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kv_offset + ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        lse = dta_ref[0][:, 1:2]
        safe_lse = jnp.where(jnp.isfinite(lse), lse, 1e30)
        p = jnp.exp(s - safe_lse)
        do = do_ref[0]
        dv_acc[:] = dv_acc[:] + jnp.dot(
            p.astype(do.dtype).T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v_ref[0].T, preferred_element_type=jnp.float32)
        t = p * (dp - dta_ref[0][:, :1])
        dk_acc[:] = dk_acc[:] + jnp.dot(
            t.astype(q.dtype).T, q, preferred_element_type=jnp.float32) \
            * scale

    _masked_dispatch(causal, live, diag, update)

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=tuple(range(3, 11)))
def _flash(q, k, v, causal, q_offset, kv_offset, scale, block_q, block_k,
           bwd_blocks, interpret):
    out, lse, _ = _fwd_impl(q, k, v, causal, q_offset, kv_offset, scale,
                            block_q, block_k, interpret)
    return out, lse


def _flash_fwd(q, k, v, causal, q_offset, kv_offset, scale, block_q,
               block_k, bwd_blocks, interpret):
    out, lse, _ = _fwd_impl(q, k, v, causal, q_offset, kv_offset,
                            scale, block_q, block_k, interpret)
    # Residual is the THIN (B, H, S) lse — the kernel's 128-lane output
    # is tile-alignment scaffolding and holding it across fwd→bwd would
    # cost 128x the activation memory (~1 GiB at the S=8192 LM config).
    return (out, lse), (q, k, v, out, lse)


def _flash_bwd(causal, q_offset, kv_offset, scale, block_q, block_k,
               bwd_blocks, interpret, res, g):
    q, k, v, out, lse = res
    do, dlse = g
    # The backward kernels stream different data patterns than the
    # forward (dq: k/v innermost; dkv: the whole q side innermost), so
    # they take their own block shapes.
    bq_dq, bk_dq, bq_dkv, bk_dkv = bwd_blocks
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bhs = b * h
    qf = q.reshape(bhs, sq, d)
    kf = k.reshape(bhs, sk, d)
    vf = v.reshape(bhs, sk, d)
    dof = do.reshape(bhs, sq, d)
    # Per-row residual scalars packed into ONE 128-lane tensor: lane 0
    # carries c = delta - dlse (delta = rowsum(do*o); the lse cotangent
    # folds into the same term since ds = p*(dp - delta + dlse)), lane 1
    # carries lse. stack+pad lowers to a single fused 128-lane write —
    # per-lane .at[].set constructions each cost a full-tensor
    # dynamic-update-slice pass (~2 ms/layer on v5e, profiled).
    delta = jnp.sum(dof.astype(jnp.float32)
                    * out.reshape(bhs, sq, d).astype(jnp.float32), axis=-1)
    c = delta - dlse.reshape(bhs, sq).astype(jnp.float32)
    dta = jnp.pad(jnp.stack([c, lse.reshape(bhs, sq)], axis=-1),
                  ((0, 0), (0, 0), (0, 126)))

    common = dict(scale=scale, causal=causal, q_offset=q_offset,
                  kv_offset=kv_offset)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=bq_dq, block_k=bk_dq,
                          **common),
        grid=(bhs, sq // bq_dq, sk // bk_dq),
        in_specs=[
            pl.BlockSpec((1, bq_dq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk_dq, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk_dq, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bq_dq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bq_dq, 128), lambda bh, i, j: (bh, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq_dq, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bhs, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq_dq, d), jnp.float32)],
        interpret=interpret,
        **_tpu_params(interpret),
    )(qf, kf, vf, dof, dta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=bq_dkv, block_k=bk_dkv,
                          **common),
        grid=(bhs, sk // bk_dkv, sq // bq_dkv),
        in_specs=[
            pl.BlockSpec((1, bq_dkv, d), lambda bh, j, i: (bh, i, 0)),
            pl.BlockSpec((1, bk_dkv, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, bk_dkv, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, bq_dkv, d), lambda bh, j, i: (bh, i, 0)),
            pl.BlockSpec((1, bq_dkv, 128), lambda bh, j, i: (bh, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk_dkv, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, bk_dkv, d), lambda bh, j, i: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bhs, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bhs, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk_dkv, d), jnp.float32),
            pltpu.VMEM((bk_dkv, d), jnp.float32),
        ],
        interpret=interpret,
        **_tpu_params(interpret),
    )(qf, kf, vf, dof, dta)
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


_flash.defvjp(_flash_fwd, _flash_bwd)


def _fit_block(block: int, s: int) -> int:
    """Largest multiple of 8 that divides ``s`` and is <= ``block``
    (0 if none — i.e. s is not a multiple of 8)."""
    block = min(block, s)
    for b in range(block - block % 8, 7, -8):
        if s % b == 0:
            return b
    return 0


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False, q_offset: int = 0,
                    kv_offset: int = 0, scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    bwd_blocks: Optional[Tuple[int, int, int, int]] = None,
                    interpret: Optional[bool] = None
                    ) -> Tuple[jax.Array, jax.Array]:
    """Pallas flash attention over (B, H, S, D); returns (out, lse).

    Differentiable: the backward pass is the standard recompute-p flash
    backward as two Pallas kernels (dq streaming K/V blocks; dk/dv
    streaming Q blocks), so training never materializes S×S. Sequence
    lengths must be multiples of 8 (callers pad; the data layer's budgets
    already guarantee static shapes). On non-TPU backends the same
    kernels run in interpreter mode.

    block_q/block_k (forward) and ``bwd_blocks`` = (block_q_dq,
    block_k_dq, block_q_dkv, block_k_dkv) are upper bounds, fitted per
    call to the largest divisor of the sequence length that is a multiple
    of 8. The defaults are length-adaptive, tuned on v5e with FULL
    fwd+dq+dkv gradients: 512x2048 below S=8192 (measured ~101 TF/s
    useful vs ~13 TF/s at 128x128 — grid-step overhead, not FLOPs,
    dominates small blocks) and 1024x1024 at S>=8192 (2048-wide q blocks
    exceed VMEM). The backward defaults follow block_q/block_k unless
    overridden.
    """
    if not _HAS_PALLAS:  # pragma: no cover
        return mha_reference(q, k, v, causal=causal, q_offset=q_offset,
                             kv_offset=kv_offset, scale=scale)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if block_q is None:
        block_q = 1024 if sq >= 8192 else 512
    if block_k is None:
        block_k = 1024 if sq >= 8192 else 2048
    # Block sizes are upper bounds: fit each to the largest multiple of 8
    # (Mosaic sublane tile) that divides the sequence. Any seq length
    # divisible by 8 therefore works with the big TPU-tuned defaults
    # (e.g. sq=640 fits block_q=320); a misaligned length fails with the
    # same error on every backend, not just at TPU lowering time.
    block_q = _fit_block(block_q, sq)
    block_k = _fit_block(block_k, sk)
    if not block_q or not block_k:
        raise ValueError(f"seq lens ({sq},{sk}) must be multiples of 8 "
                         f"(TPU tile alignment)")
    if bwd_blocks is None:
        bwd_blocks = (block_q, block_k, block_q, block_k)
    else:
        if any(bl < 8 for bl in bwd_blocks):
            raise ValueError(f"bwd_blocks entries must be >= 8 (TPU "
                             f"sublane tile), got {bwd_blocks}")
        bq_dq, bk_dq, bq_dkv, bk_dkv = bwd_blocks
        bwd_blocks = (_fit_block(bq_dq, sq), _fit_block(bk_dq, sk),
                      _fit_block(bq_dkv, sq), _fit_block(bk_dkv, sk))
        if not all(bwd_blocks):
            raise ValueError(f"seq lens ({sq},{sk}) must be multiples of "
                             f"8 (TPU tile alignment)")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, causal, q_offset, kv_offset, scale, block_q,
                  block_k, bwd_blocks, interpret)
