"""TPU kernels and compute ops (Pallas + XLA fallbacks)."""
