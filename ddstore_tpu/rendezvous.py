"""Process-group formation and out-of-band metadata exchange.

The reference does all of its control-plane exchange with MPI collectives
(``MPI_Allgather`` of shard lengths, endpoint names, and rkeys —
/root/reference/include/ddstore.hpp:75-89, src/common.cxx:285-306). TPU-VM
hosts have no MPI, so the control plane is its own small abstraction here: a
:class:`ProcessGroup` provides ``rank``/``size``/``allgather``/``barrier``/
``split``, with four implementations:

* :class:`SingleGroup` — one process (degenerate but uniform).
* :class:`ThreadGroup` — N "ranks" as threads of one process; pairs with the
  in-process transport for unit tests.
* :class:`FileGroup` — N local processes rendezvous through a shared
  directory; pairs with the TCP transport — the ``mpirun -n 4`` analogue for
  multi-process tests on one machine (reference test strategy,
  README.md:182-198).
* :class:`JaxGroup` — wraps an initialized ``jax.distributed`` runtime on a
  real multi-host pod (process_index/process_count + multihost utils).

Only setup-time metadata moves through these groups; the data plane and the
per-batch epoch barrier run over the native transport.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional


class ProcessGroup:
    """Abstract control-plane group."""

    rank: int
    size: int

    def allgather(self, obj: Any) -> List[Any]:
        raise NotImplementedError

    def barrier(self) -> None:
        self.allgather(None)

    def split(self, color: int) -> "ProcessGroup":
        """Partition into subgroups of ranks sharing `color` (the
        ``comm.Split(rank // width, rank)`` replica-group mechanism,
        reference examples/vae/distdataset.py:25-30). Rank order within a
        subgroup follows parent rank order."""
        raise NotImplementedError

    def broadcast(self, obj: Any, root: int = 0) -> Any:
        return self.allgather(obj)[root]


class SingleGroup(ProcessGroup):
    def __init__(self):
        self.rank = 0
        self.size = 1

    def allgather(self, obj: Any) -> List[Any]:
        return [obj]

    def split(self, color: int) -> "ProcessGroup":
        return SingleGroup()


class _ThreadGroupState:
    def __init__(self, size: int):
        self.size = size
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.seq = 0
        self.slots: Dict[int, List[Any]] = {}
        self.arrived: Dict[int, int] = {}
        self.left: Dict[int, int] = {}


_thread_groups: Dict[str, _ThreadGroupState] = {}
_thread_groups_lock = threading.Lock()


class ThreadGroup(ProcessGroup):
    """All ranks are threads in one process, sharing state by name."""

    def __init__(self, name: str, rank: int, size: int):
        self.name = name
        self.rank = rank
        self.size = size
        with _thread_groups_lock:
            st = _thread_groups.get(name)
            if st is None:
                st = _ThreadGroupState(size)
                _thread_groups[name] = st
        assert st.size == size
        self._st = st
        self._seq = 0

    def allgather(self, obj: Any) -> List[Any]:
        st = self._st
        seq = self._seq
        self._seq += 1
        with st.cv:
            slot = st.slots.setdefault(seq, [None] * st.size)
            slot[self.rank] = obj
            st.arrived[seq] = st.arrived.get(seq, 0) + 1
            st.cv.notify_all()
            if not st.cv.wait_for(lambda: st.arrived.get(seq, 0) >= st.size,
                                  timeout=120):
                raise TimeoutError("ThreadGroup allgather timed out")
            result = list(st.slots[seq])
            st.left[seq] = st.left.get(seq, 0) + 1
            if st.left[seq] == st.size:
                del st.slots[seq], st.arrived[seq], st.left[seq]
        return result

    def split(self, color: int) -> "ProcessGroup":
        colors = self.allgather(color)
        members = [r for r, c in enumerate(colors) if c == color]
        return ThreadGroup(f"{self.name}/s{self._seq}c{color}",
                           members.index(self.rank), len(members))


class FileGroup(ProcessGroup):
    """Rendezvous through a shared directory (local multi-process tests, or
    any shared filesystem). Each collective writes ``{run}.{seq}.{rank}.pkl``
    and polls for the full set.

    Staleness protocol: rank 0 cleans the directory and atomically publishes
    a MARKER file holding a fresh run nonce; every other rank waits for the
    marker and namespaces its files by that nonce. A previous (crashed or
    finished) run's files can therefore never be consumed as live data —
    the worst case for a botched launch is a timeout, never wrong peers.
    One directory per concurrent job; files are pickles, so the directory
    must not be writable by untrusted users (created 0700).

    Directory REUSE across launches (the auto_group default dir, or any
    fixed DDSTORE_RDV_DIR) adds one more race: a non-zero rank of launch
    N+1 can read launch N's still-present marker and find launch N's
    files — a complete-looking hello set, roster, and allgather payloads
    for a dead generation — before rank 0 of launch N+1 wipes the
    directory. File existence is therefore never proof of membership:
    each rank's hello carries a fresh per-process instance nonce, and a
    rank only joins once a roster written by rank 0 names that nonce. A
    dead generation's roster cannot name a fresh process, so ranks that
    raced ahead simply wait, converging to rank 0's fresh marker when it
    lands. After the join, a marker change observed mid-collective means
    a NEW world launched in this directory — the collective raises
    immediately (this process is the stale one) instead of burning the
    full timeout.

    One identity gap remains without operator help: a straggler rank
    from a previous launch that never joined (still in its hello loop)
    is a live process writing fresh nonces, indistinguishable from a
    slow rank of the current launch — it can win a rank slot. Setting a
    per-launch ``DDSTORE_RDV_ID`` (or ``launch_id``) closes it: rank 0
    rosters only hellos carrying its own id.
    """

    def __init__(self, root: str, rank: int, size: int,
                 timeout: float = 120.0,
                 launch_id: Optional[str] = None):
        self.root = root
        self.rank = rank
        self.size = size
        self.timeout = timeout
        os.makedirs(root, exist_ok=True)
        try:
            os.chmod(root, 0o700)
        except OSError:
            pass
        import uuid as _uuid

        self._seq = 0
        self._me = _uuid.uuid4().hex[:12]  # instance nonce: THIS process
        # Optional operator-provided launch identity (DDSTORE_RDV_ID or
        # the launch_id argument): rank 0 rosters only hellos carrying
        # the same id, so a straggler rank from a PREVIOUS launch that
        # converges to this launch's marker can never win a rank slot.
        # Deliberately NOT auto-sourced from scheduler job ids: an
        # elastic replacement rank may run under a different batch job
        # than the survivors (it must still join), and relaunches inside
        # one allocation share the job id (no protection anyway) — only
        # the operator knows what constitutes "one launch". Without an
        # id (default), a straggler is indistinguishable from a
        # legitimately slow rank of this launch.
        if launch_id is None:
            launch_id = os.environ.get("DDSTORE_RDV_ID")
        self._launch = launch_id
        # ONE join budget for the whole constructor: the marker wait and
        # the hello phase share this deadline, so a non-zero rank's join
        # is bounded by `timeout` — not ~2x it (marker read consuming a
        # full budget, then the hello loop starting a fresh one).
        deadline = time.time() + timeout
        marker = os.path.join(root, "MARKER")
        if rank == 0:
            for f in os.listdir(root):
                if f.endswith((".pkl", ".tmp")) or f == "MARKER":
                    try:
                        os.unlink(os.path.join(root, f))
                    except OSError:
                        pass
            self._run = _uuid.uuid4().hex[:12]
            tmp = marker + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(self._run)
            os.replace(tmp, marker)
        else:
            self._run = self._read_marker(marker, deadline)
        # Hello phase with a liveness proof. Every rank publishes
        # {run}.hello.{rank} holding its instance nonce; rank 0 collects
        # the full set and answers with {run}.roster listing the nonces
        # it saw; a non-zero rank completes only when a roster NAMES ITS
        # OWN NONCE. File existence alone is not enough: a reused
        # directory can hold a previous launch's complete hello set (and
        # roster, and payloads), and completing against those would read
        # a dead generation's data as live. A stale roster cannot name a
        # fresh process's nonce, so late rank-0 arrival just makes the
        # others wait, re-reading the marker (and re-publishing their
        # hellos) until the fresh generation acknowledges them.
        written_for = last_run = None
        conflict = False
        spins = 0
        rostered: Dict[int, str] = {}   # rank 0: admitted so far
        mismatched: set = set()         # rank 0: hellos with a foreign id
        while True:
            if written_for != self._run:
                hello = os.path.join(root,
                                     f"{self._run}.hello.{self.rank}.pkl")
                # Per-process tmp name: two processes competing for one
                # rank slot (zombie straggler) write the same final path
                # but must never collide on the staging file; and a new
                # launch's wipe can unlink the staging file mid-publish —
                # that's a retry, not a crash.
                tmp_h = f"{hello}.{self._me}.tmp"
                try:
                    with open(tmp_h, "wb") as fh:
                        pickle.dump((self._launch, self._me), fh)
                    os.replace(tmp_h, hello)
                except OSError:
                    if self._current_run() == self._run:
                        raise  # real I/O failure (ENOSPC, EACCES, ...)
                    # wiped by a newer launch mid-publish (marker gone or
                    # replaced); converge via the marker re-read below
                else:
                    written_for = self._run
                if last_run != self._run:
                    conflict = False  # that conflict was a prior run's
                    last_run = self._run
            if rank == 0:
                # Admission is first-match-wins per rank, so already-
                # rostered entries never need re-reading (a later
                # overwrite by a squatter changes nothing).
                for r in range(size):
                    if r in rostered:
                        continue
                    p = os.path.join(root, f"{self._run}.hello.{r}.pkl")
                    try:
                        with open(p, "rb") as fh:
                            lid, nonce = pickle.load(fh)
                    except (OSError, EOFError, pickle.UnpicklingError,
                            TypeError, ValueError):
                        continue
                    if lid == self._launch:
                        rostered[r] = nonce
                        mismatched.discard(r)
                    else:
                        mismatched.add(r)
                if len(rostered) == size:
                    rpath = os.path.join(root, f"{self._run}.roster.pkl")
                    with open(rpath + ".tmp", "wb") as fh:
                        pickle.dump(rostered, fh)
                    os.replace(rpath + ".tmp", rpath)
                    break
            else:
                try:
                    with open(os.path.join(
                            root, f"{self._run}.roster.pkl"), "rb") as fh:
                        roster = pickle.load(fh)
                    ours = roster.get(self.rank)
                    if ours == self._me:
                        break
                    # A roster naming someone else for our rank is either
                    # a dead generation's leftover (resolved when rank 0's
                    # fresh marker lands) or a live conflict (duplicate
                    # rank / zombie). Indistinguishable from files alone —
                    # keep waiting, and diagnose on timeout.
                    conflict = conflict or ours is not None
                except (OSError, EOFError, pickle.UnpicklingError):
                    pass
            if time.time() > deadline:
                missing = [r for r in range(size) if not os.path.exists(
                    os.path.join(root, f"{self._run}.hello.{r}.pkl"))]
                detail = (f"missing hello from ranks {missing}" if missing
                          else "all hello files present but not admitted"
                          if rank == 0 else
                          "roster present but names another process for "
                          "this rank — duplicate rank, or a zombie from a "
                          "previous launch sharing the directory"
                          if conflict else
                          "all hellos present, no roster from rank 0")
                if mismatched:
                    detail += (f"; hellos from ranks {sorted(mismatched)} "
                               f"carried a different launch id — "
                               f"DDSTORE_RDV_ID inconsistent across ranks, "
                               f"or stragglers from a previous launch")
                raise TimeoutError(f"FileGroup hello: {detail} in {root}")
            time.sleep(0.005)
            spins += 1
            if rank == 0:
                if spins % 50 == 0:
                    self._raise_if_stale("hello")
            else:
                try:
                    self._run = self._read_marker(marker, deadline)
                except TimeoutError:
                    pass
                if spins % 50 == 0:
                    # Re-publish: a straggler from another launch writing
                    # to the same rank slot can overwrite our hello; with
                    # a launch id set, rank 0 ignores the straggler's, so
                    # periodic rewrites guarantee ours is eventually seen.
                    written_for = None

    @staticmethod
    def _read_marker(marker: str, deadline: float) -> str:
        while True:
            try:
                with open(marker) as fh:
                    run = fh.read().strip()
                if run:
                    return run
            except OSError:
                pass
            if time.time() > deadline:
                # Name the missing peer artifact, matching the TCP
                # barrier's "waiting for rank k" diagnostics: only rank 0
                # publishes the marker, so its absence means rank 0 never
                # started (or a new launch wiped mid-join).
                raise TimeoutError(
                    f"FileGroup: waiting on rank 0's MARKER at {marker} "
                    f"— rank 0 never published the run nonce (not "
                    f"started, crashed pre-publish, or a different "
                    f"launch wiped the directory)")
            time.sleep(0.005)

    def _publish(self, seq: int, obj: Any) -> None:
        path = os.path.join(self.root, f"{self._run}.{seq}.{self.rank}.pkl")
        tmp = f"{path}.{self._me}.tmp"
        for attempt in (0, 1):
            try:
                with open(tmp, "wb") as f:
                    pickle.dump(obj, f)
                os.replace(tmp, path)  # atomic publish
                return
            except OSError:
                # A newer launch's wipe can unlink the staging file
                # between write and replace; diagnose that instead of
                # surfacing a bare FileNotFoundError.
                self._raise_if_stale(f"publish {seq}")
                if self._current_run() == self._run:
                    raise  # real I/O failure (ENOSPC, EACCES, ...)
                # Marker MISSING (mid-wipe window: rank 0 of a new launch
                # deleted it, its replacement imminent): retry once —
                # a transient unrelated unlink resolves — then diagnose
                # the takeover rather than leak a bare FileNotFoundError.
                if attempt:
                    raise TimeoutError(
                        f"FileGroup publish {seq}: rendezvous generation "
                        f"changed under a live run — this rank is stale "
                        f"(a new world is launching in {self.root})")
                time.sleep(0.005)

    def _current_run(self) -> Optional[str]:
        try:
            with open(os.path.join(self.root, "MARKER")) as fh:
                return fh.read().strip() or None
        except OSError:
            return None  # mid-wipe: rank 0 deleted it, new one imminent

    def _raise_if_stale(self, context: str) -> None:
        """Fail fast when a NEW launch took the directory: the marker no
        longer holds this group's nonce. A missing/mid-wipe marker (None)
        is not treated as takeover — the next read resolves it."""
        run = self._current_run()
        if run is not None and run != self._run:
            raise TimeoutError(
                f"FileGroup {context}: rendezvous generation changed "
                f"under a live run — this rank is stale (a new world "
                f"launched in {self.root})")

    def allgather(self, obj: Any) -> List[Any]:
        seq = self._seq
        self._seq += 1
        self._publish(seq, obj)
        deadline = time.time() + self.timeout
        result: List[Any] = [None] * self.size
        pending = set(range(self.size))
        spins = 0
        while pending:
            for r in list(pending):
                p = os.path.join(self.root, f"{self._run}.{seq}.{r}.pkl")
                if os.path.exists(p):
                    try:
                        with open(p, "rb") as f:
                            result[r] = pickle.load(f)
                    except (FileNotFoundError, EOFError,
                            pickle.UnpicklingError):
                        # writer mid-replace, or a new launch's wipe
                        # unlinked the file between exists() and open();
                        # the generation check below diagnoses the latter.
                        # Other OSErrors (EIO, EACCES) propagate — they
                        # are real failures, not races.
                        continue
                    pending.discard(r)
            if pending:
                if time.time() > deadline:
                    # Name the exact peer marker files never published —
                    # the TCP barrier's "waiting for rank k" diagnostic,
                    # filesystem edition (barrier() rides allgather, so
                    # barrier timeouts carry this too).
                    waiting = ", ".join(
                        f"rank {r} ({self._run}.{seq}.{r}.pkl)"
                        for r in sorted(pending))
                    raise TimeoutError(
                        f"FileGroup allgather {seq}: timed out after "
                        f"{self.timeout:.0f}s waiting on {waiting} "
                        f"in {self.root}")
                time.sleep(0.005)
                spins += 1
                if spins % 50 == 0:
                    # Every rank, including 0 (which wrote this run's
                    # marker itself): membership is roster-gated at
                    # construction, so a nonce change mid-collective
                    # means a NEW world launched in this directory and
                    # this process belongs to the dead one.
                    self._raise_if_stale(f"allgather {seq}")
        return result

    def split(self, color: int) -> "ProcessGroup":
        colors = self.allgather(color)
        members = [r for r, c in enumerate(colors) if c == color]
        sub = FileGroup(os.path.join(self.root, f"s{self._seq}c{color}"),
                        members.index(self.rank), len(members),
                        self.timeout, launch_id=self._launch)
        return sub


class JaxGroup(ProcessGroup):
    """Control plane over an initialized ``jax.distributed`` runtime — the
    production path on a multi-host TPU pod. Uses the in-process KV store of
    the distributed runtime via ``multihost_utils`` broadcast."""

    def __init__(self, prefix: str = "ddstore"):
        import jax

        self.rank = jax.process_index()
        self.size = jax.process_count()
        self._prefix = prefix
        self._seq = 0

    def allgather(self, obj: Any) -> List[Any]:
        import jax
        import numpy as np
        from jax.experimental import multihost_utils

        self._seq += 1
        payload = pickle.dumps(obj)
        # Fixed-width byte tensor allgather: broadcast lengths first.
        n = np.int64(len(payload))
        lens = multihost_utils.process_allgather(n)
        width = int(max(lens))
        buf = np.zeros(width, dtype=np.uint8)
        buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
        gathered = multihost_utils.process_allgather(buf)
        out = []
        for r in range(self.size):
            out.append(pickle.loads(gathered[r, : int(lens[r])].tobytes()))
        return out

    def barrier(self) -> None:
        from jax.experimental import multihost_utils

        self._seq += 1
        multihost_utils.sync_global_devices(f"{self._prefix}:{self._seq}")

    def split(self, color: int) -> "ProcessGroup":
        colors = self.allgather(color)
        members = [r for r, c in enumerate(colors) if c == color]
        return _SubGroup(self, members.index(self.rank), members)


class _SubGroup(ProcessGroup):
    """Subgroup view over a parent group: collectives run on the parent and
    are filtered to members (every parent rank participates, like
    ``comm.Split`` where all ranks call the collective)."""

    def __init__(self, parent: ProcessGroup, rank: int, members: List[int]):
        self.parent = parent
        self.rank = rank
        self.size = len(members)
        self.members = members

    def allgather(self, obj: Any) -> List[Any]:
        everything = self.parent.allgather(obj)
        return [everything[m] for m in self.members]

    def split(self, color: int) -> "ProcessGroup":
        colors = self.allgather(color)
        members = [r for r, c in enumerate(colors) if c == color]
        return _SubGroup(self, members.index(self.rank),
                         members)


# ---------------------------------------------------------------------------
# Pod / scheduler bootstrap
# ---------------------------------------------------------------------------
#
# The reference bootstraps torch.distributed from scheduler env — Summit LSB
# and SLURM node lists (/root/reference/examples/vae/vae-ddp.py:61-145). The
# TPU-pod equivalent is bringing up `jax.distributed` itself; these helpers
# detect the same scheduler families plus GCE/GKE TPU-pod metadata env, pick
# a coordinator deterministically, and hand back a ready ProcessGroup.


class PodConfig:
    """Where this process sits in the pod/job and who coordinates."""

    __slots__ = ("coordinator", "num_processes", "process_id", "source")

    def __init__(self, coordinator: str, num_processes: int,
                 process_id: int, source: str):
        self.coordinator = coordinator
        self.num_processes = num_processes
        self.process_id = process_id
        self.source = source

    def __repr__(self):  # pragma: no cover
        return (f"PodConfig({self.coordinator!r}, n={self.num_processes}, "
                f"id={self.process_id}, via {self.source})")


def _expand_item(item: str) -> List[str]:
    """Expand ONE nodelist item, cross-producting every bracket group and
    preserving any literal text between/after them: ``"r[0-1]n[01-02]"``
    -> ``["r0n01", "r0n02", "r1n01", "r1n02"]``; ``"cn[1-2]-ib"`` ->
    ``["cn1-ib", "cn2-ib"]``."""
    lb = item.find("[")
    if lb < 0:
        return [item] if item else []
    rb = item.index("]", lb)
    expansions: List[str] = []
    for part in item[lb + 1: rb].split(","):
        if "-" in part:
            lo, hi = part.split("-")
            width = len(lo)
            expansions.extend(f"{v:0{width}d}"
                              for v in range(int(lo), int(hi) + 1))
        else:
            expansions.append(part)
    tails = _expand_item(item[rb + 1:]) or [""]
    return [item[:lb] + e + t for e in expansions for t in tails]


def parse_nodelist(nodelist: str) -> List[str]:
    """Expand a SLURM-style compressed node list into hostnames:
    ``"tpu[001-003,07],login1"`` -> ``["tpu001", "tpu002", "tpu003",
    "tpu07", "login1"]`` (zero-padding preserved; bracket groups may have
    suffixes or repeat, e.g. ``"cn[1-2]-ib"``)."""
    # Split on top-level commas only (commas inside [...] are ranges).
    items: List[str] = []
    depth, start = 0, 0
    for i, ch in enumerate(nodelist):
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        elif ch == "," and depth == 0:
            items.append(nodelist[start:i])
            start = i + 1
    items.append(nodelist[start:])
    hosts: List[str] = []
    for item in items:
        hosts.extend(_expand_item(item))
    return hosts


def detect_pod_env(env: Optional[Dict[str, str]] = None,
                   port: int = 8476) -> Optional[PodConfig]:
    """Inspect the environment for a multi-process launch context.

    Priority: explicit ``DDSTORE_COORDINATOR``/``DDSTORE_NUM_PROCESSES``/
    ``DDSTORE_PROCESS_ID`` -> GKE/GCE TPU pod metadata (``TPU_WORKER_ID``,
    ``TPU_WORKER_HOSTNAMES``) -> SLURM (``SLURM_PROCID``/``SLURM_NPROCS``/
    ``SLURM_NODELIST``, the reference's CADES path, vae-ddp.py:32-35,118)
    -> LSF/Summit (``LSB_MCPU_HOSTS``ancestry + ``OMPI_COMM_WORLD_*``,
    vae-ddp.py:28-31,112-117). Returns None when nothing matches (single
    process)."""
    e = os.environ if env is None else env

    if "DDSTORE_COORDINATOR" in e:
        coord = e["DDSTORE_COORDINATOR"]
        if ":" not in coord:
            coord = f"{coord}:{port}"
        return PodConfig(coord, int(e["DDSTORE_NUM_PROCESSES"]),
                         int(e["DDSTORE_PROCESS_ID"]), "explicit")

    if "TPU_WORKER_HOSTNAMES" in e and "TPU_WORKER_ID" in e:
        hosts = [h.strip() for h in e["TPU_WORKER_HOSTNAMES"].split(",")
                 if h.strip()]
        return PodConfig(f"{hosts[0]}:{port}", len(hosts),
                         int(e["TPU_WORKER_ID"]), "tpu-pod")

    if "SLURM_PROCID" in e:
        nproc = int(e.get("SLURM_NPROCS", e.get("SLURM_NTASKS", "1")))
        hosts = parse_nodelist(e.get("SLURM_NODELIST", ""))
        if not hosts:
            return None
        return PodConfig(f"{hosts[0]}:{port}", nproc,
                         int(e["SLURM_PROCID"]), "slurm")

    if ("LSB_MCPU_HOSTS" in e and "OMPI_COMM_WORLD_RANK" in e
            and "OMPI_COMM_WORLD_SIZE" in e):
        # "host1 ncpu1 host2 ncpu2 ..." — first entry may be a launch node
        # (the reference drops entry 0, vae-ddp.py:112-117 uses [1]).
        # A partial LSF env (empty host var, missing size) falls through
        # to the remaining detectors instead of raising.
        hosts = e["LSB_MCPU_HOSTS"].split()[0::2]
        if hosts:
            coord = hosts[1] if len(hosts) > 1 else hosts[0]
            return PodConfig(f"{coord}:{port}",
                             int(e["OMPI_COMM_WORLD_SIZE"]),
                             int(e["OMPI_COMM_WORLD_RANK"]), "lsf")

    return None


def pod_bootstrap(env: Optional[Dict[str, str]] = None, port: int = 8476,
                  timeout: float = 120.0) -> ProcessGroup:
    """Bring up ``jax.distributed`` (if a pod/scheduler context is
    detected) and return the matching ProcessGroup — the one-call
    production entry point on GCE/GKE TPU pods::

        group = ddstore_tpu.pod_bootstrap()
        store = ddstore_tpu.DDStore(group, backend="tcp")

    Detection falls back to JAX's own auto-detection
    (``jax.distributed.initialize()`` with no arguments handles Cloud TPU
    metadata) and finally to a single-process group. Safe to call when
    ``jax.distributed`` is already initialized (it is left untouched);
    a FAILED initialization propagates — a multi-host job must fail
    loudly, not silently degrade to world-of-1 stores."""
    import jax

    e = os.environ if env is None else env
    already_up = jax.distributed.is_initialized() \
        if hasattr(jax.distributed, "is_initialized") \
        else jax.process_count() > 1
    if not already_up:
        cfg = detect_pod_env(env, port)
        if cfg is not None:
            jax.distributed.initialize(
                coordinator_address=cfg.coordinator,
                num_processes=cfg.num_processes,
                process_id=cfg.process_id,
                initialization_timeout=int(timeout))
        elif e.get("DDSTORE_POD_AUTODETECT") == "1":
            # On Cloud TPU, no-arg initialize reads the metadata server.
            jax.distributed.initialize(initialization_timeout=int(timeout))
    if jax.process_count() > 1:
        return JaxGroup()
    return SingleGroup()


def auto_group(timeout: float = 120.0) -> ProcessGroup:
    """Pick a group from the environment.

    Priority: explicit ``DDSTORE_RANK``/``DDSTORE_WORLD``/``DDSTORE_RDV_DIR``
    (file rendezvous, the test harness path) → initialized jax.distributed →
    single process. The env-var inventory mirrors the reference's
    (``DDSTORE_METHOD``/SLURM vars, distdataset.py:32-34) but with the
    TPU-pod deployment model.
    """
    if "DDSTORE_RANK" in os.environ:
        rank = int(os.environ["DDSTORE_RANK"])
        world = int(os.environ["DDSTORE_WORLD"])
        root = os.environ.get(
            "DDSTORE_RDV_DIR", f"/tmp/ddstore_rdv_{os.getuid()}")
        return FileGroup(root, rank, world, timeout)
    try:
        import jax

        if jax.process_count() > 1:
            return JaxGroup()
    except Exception:
        pass
    return SingleGroup()
