"""ddtrace CLI: merge per-rank dumps, render postmortem span trees.

Workflow (README "Distributed tracing & flight recorder")::

    # each rank saves its dump (live rings or the flight snapshot)
    from ddstore_tpu import obs
    obs.save_dump(f"/tmp/trace.r{store.rank}.npy", store.trace_dump())

    # merge into Chrome trace-event JSON (chrome://tracing / Perfetto)
    python -m ddstore_tpu.obs merge -o trace.json /tmp/trace.r*.npy

    # or read the story in the terminal
    python -m ddstore_tpu.obs tree /tmp/trace.r*.npy
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import chrome_trace, load_dump, merge, span_tree


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ddstore_tpu.obs",
        description="Merge/render ddstore trace dumps.")
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser(
        "merge", help="merge per-rank .npy dumps into Chrome "
        "trace-event JSON (chrome://tracing, Perfetto)")
    mp.add_argument("dumps", nargs="+", help="per-rank dump .npy files")
    mp.add_argument("-o", "--out", default="-",
                    help="output path (default stdout)")
    tp = sub.add_parser(
        "tree", help="render the merged span tree as text "
        "(postmortems over a flight dump)")
    tp.add_argument("dumps", nargs="+")
    tp.add_argument("--span", type=lambda s: int(s, 16), default=None,
                    help="render one span only (hex id)")
    args = ap.parse_args(argv)

    events = merge([load_dump(p) for p in args.dumps])
    if args.cmd == "merge":
        payload = json.dumps(chrome_trace(events))
        if args.out == "-":
            print(payload)
        else:
            with open(args.out, "w") as f:
                f.write(payload)
            print(f"# {len(events)} events -> {args.out}",
                  file=sys.stderr)
    else:
        print(span_tree(events, span=args.span))
    return 0


if __name__ == "__main__":
    sys.exit(main())
