"""ddtrace / ddmetrics CLI: merge per-rank dumps, render postmortem
span trees, print latency tables, and export/watch live metrics
snapshots.

Workflow (README "Distributed tracing & flight recorder" + "Live
metrics & SLOs")::

    # each rank saves its trace dump (live rings or flight snapshot)
    from ddstore_tpu import obs
    obs.save_dump(f"/tmp/trace.r{store.rank}.npy", store.trace_dump())

    # merge into Chrome trace-event JSON (chrome://tracing / Perfetto)
    python -m ddstore_tpu.obs merge -o trace.json /tmp/trace.r*.npy

    # or read the story in the terminal
    python -m ddstore_tpu.obs tree /tmp/trace.r*.npy

    # measured per-(class, route, peer) percentiles from a saved dump
    python -m ddstore_tpu.obs latency /tmp/trace.r*.npy

    # live histogram snapshots (no tracing needed):
    obs.save_metrics(f"/tmp/m.r{store.rank}.npy",
                     store.metrics_snapshot())
    python -m ddstore_tpu.obs top /tmp/m.r*.npy           # one shot
    python -m ddstore_tpu.obs top --watch 2 /tmp/m.r*.npy # refresh
    python -m ddstore_tpu.obs metrics --format prom /tmp/m.r*.npy
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from . import (chrome_trace, latency_text, load_dump, load_metrics,
               merge, merge_metrics, metrics_json, prometheus_text,
               span_latency, span_tree)


def _load_cells(paths):
    cells = []
    for p in paths:
        try:
            cells.append(load_metrics(p))
        except (OSError, ValueError) as e:
            print(f"# skipping {p}: {e}", file=sys.stderr)
    return merge_metrics(cells)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ddstore_tpu.obs",
        description="Merge/render ddstore trace dumps and live "
                    "metrics snapshots.")
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser(
        "merge", help="merge per-rank .npy dumps into Chrome "
        "trace-event JSON (chrome://tracing, Perfetto)")
    mp.add_argument("dumps", nargs="+", help="per-rank dump .npy files")
    mp.add_argument("-o", "--out", default="-",
                    help="output path (default stdout)")
    tp = sub.add_parser(
        "tree", help="render the merged span tree as text "
        "(postmortems over a flight dump)")
    tp.add_argument("dumps", nargs="+")
    tp.add_argument("--span", type=lambda s: int(s, 16), default=None,
                    help="render one span only (hex id)")
    lp = sub.add_parser(
        "latency", help="measured per-(class, route, peer) latency "
        "percentiles from saved TRACE dumps (span_latency) — the same "
        "report path the live histograms feed")
    lp.add_argument("dumps", nargs="+")
    xp = sub.add_parser(
        "top", help="live-metrics terminal view over saved histogram "
        "snapshots (obs.save_metrics); --watch re-reads and redraws")
    xp.add_argument("snapshots", nargs="+")
    xp.add_argument("--watch", type=float, default=0.0, metavar="SECS",
                    help="re-read the files and redraw every SECS")
    ep = sub.add_parser(
        "metrics", help="export merged histogram snapshots as "
        "Prometheus exposition text or JSON")
    ep.add_argument("snapshots", nargs="+")
    ep.add_argument("--format", choices=("prom", "json"),
                    default="prom")
    ep.add_argument("-o", "--out", default="-",
                    help="output path (default stdout)")
    args = ap.parse_args(argv)

    if args.cmd in ("merge", "tree", "latency"):
        events = merge([load_dump(p) for p in args.dumps])
    if args.cmd == "merge":
        payload = json.dumps(chrome_trace(events))
        if args.out == "-":
            print(payload)
        else:
            with open(args.out, "w") as f:
                f.write(payload)
            print(f"# {len(events)} events -> {args.out}",
                  file=sys.stderr)
    elif args.cmd == "tree":
        print(span_tree(events, span=args.span))
    elif args.cmd == "latency":
        table = span_latency(events)
        head = (f"{'class|route|peer':<28} {'count':>8} "
                f"{'p50_ms':>9} {'p99_ms':>9}")
        print(head)
        print("-" * len(head))
        for key in sorted(table):
            r = table[key]
            print(f"{key:<28} {r['count']:>8} {r['p50_ms']:>9.3f} "
                  f"{r['p99_ms']:>9.3f}")
        if not table:
            print("(no op spans in the dump)")
    elif args.cmd == "top":
        while True:
            cells = _load_cells(args.snapshots)
            text = latency_text(
                cells, title=f"ddmetrics ({len(args.snapshots)} "
                             f"snapshot file(s))")
            if args.watch > 0:
                # ANSI clear+home keeps the table in place like top(1).
                sys.stdout.write("\x1b[2J\x1b[H")
            print(text, flush=True)
            if args.watch <= 0:
                break
            try:
                time.sleep(args.watch)
            except KeyboardInterrupt:
                break
    else:  # metrics
        cells = _load_cells(args.snapshots)
        payload = prometheus_text(cells) if args.format == "prom" \
            else json.dumps(metrics_json(cells), indent=2)
        if args.out == "-":
            print(payload)
        else:
            with open(args.out, "w") as f:
                f.write(payload)
            print(f"# {len(cells)} cell(s) -> {args.out}",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
