"""ddtrace consumer side: merge per-rank event dumps into Chrome
trace-event JSON, render span trees for postmortems, and derive the
measured per-class latency percentiles ``summary()["trace"]`` reports.

The native half (``native/trace.{h,cc}``) records fixed-size typed
events into per-thread lock-free rings and snapshots them into a flight
recorder on failure; this package turns those dumps into things a human
(or chrome://tracing / Perfetto) can read:

* :func:`merge` — concatenate per-rank dumps, time-sorted.
* :func:`chrome_trace` — Chrome trace-event JSON (load in
  chrome://tracing or https://ui.perfetto.dev): op/serve legs become
  async begin/end pairs keyed by span id, everything else instants.
* :func:`span_tree` — plain-text per-span rendering for terminal
  postmortems (the flight dump of a killed owner reads as a story:
  retries, the suspect verdict, every replica-rerouted op).
* :func:`span_latency` — measured p50/p99 per (op class, route, peer)
  from op begin/end pairs — replacing ad-hoc guesswork about where a
  read's time went.
* ``python -m ddstore_tpu.obs merge|tree`` — the CLI over saved dumps
  (``save_dump``/``load_dump``: one ``.npy`` per rank).
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

import numpy as np

from ..binding import (METRICS_BUCKETS, METRICS_CELL_DTYPE,
                       METRICS_ROUTES, TRACE_EVENT_DTYPE,
                       TRACE_FLIGHT_REASONS, TRACE_OP_CLASSES,
                       TRACE_TYPES)

__all__ = ["merge", "chrome_trace", "span_tree", "span_latency",
           "trace_summary", "save_dump", "load_dump",
           "merge_metrics", "diff_metrics", "hist_percentile",
           "latency_table", "latency_text", "prometheus_text",
           "metrics_json", "save_metrics", "load_metrics"]


def save_dump(path: str, events: np.ndarray) -> str:
    """Persist one rank's dump (``DDStore.trace_dump()`` /
    ``trace_flight_dump()``) as a ``.npy`` the merge CLI consumes."""
    arr = np.asarray(events, dtype=TRACE_EVENT_DTYPE)
    np.save(path, arr)
    return path if path.endswith(".npy") else path + ".npy"


def load_dump(path: str) -> np.ndarray:
    arr = np.load(path)
    if arr.dtype != TRACE_EVENT_DTYPE:
        raise ValueError(f"{path}: not a ddstore trace dump "
                         f"(dtype {arr.dtype})")
    return arr


def merge(dumps: Iterable[np.ndarray]) -> np.ndarray:
    """Concatenate per-rank dumps into one time-sorted stream. Ranks of
    ONE machine share CLOCK_MONOTONIC (the in-process ThreadGroup and
    local FileGroup cases); across hosts the order is per-rank exact,
    cross-rank approximate — spans, not clocks, carry the causality."""
    arrs = [np.asarray(d, dtype=TRACE_EVENT_DTYPE) for d in dumps]
    if not arrs:
        return np.empty(0, dtype=TRACE_EVENT_DTYPE)
    cat = np.concatenate(arrs)
    return cat[np.argsort(cat["t_ns"], kind="stable")]


def _event_name(ev) -> str:
    t = TRACE_TYPES.get(int(ev["type"]), f"type{int(ev['type'])}")
    if t in ("op_begin", "op_end"):
        cls = TRACE_OP_CLASSES.get(int(ev["a"]), str(int(ev["a"])))
        return f"op:{cls}"
    if t in ("serve_begin", "serve_end"):
        return "serve"
    return t


def _args_of(ev) -> Dict:
    t = TRACE_TYPES.get(int(ev["type"]), "")
    a, b, c = int(ev["a"]), int(ev["b"]), int(ev["c"])
    if t == "op_begin":
        return {"class": TRACE_OP_CLASSES.get(a, a), "peer": b,
                "bytes": c}
    if t == "op_end":
        return {"class": TRACE_OP_CLASSES.get(a, a), "rc": b, "bytes": c}
    if t == "retry":
        return {"peer": a, "attempt": b, "rc": c}
    if t == "backoff":
        return {"peer": a, "sleep_ms": b, "attempt": c}
    if t in ("lane_dial", "lane_close"):
        return {"lane": a, "uds" if t == "lane_dial" else "rc": b}
    if t == "serve_begin":
        return {"src": a, "ops": b, "bytes": c}
    if t == "serve_end":
        return {"src": a, "status": b, "bytes": c}
    if t == "cma_read":
        return {"peer": a, "ops": b, "bytes": c}
    if t == "window_issue":
        return {"window": a, "rows": b, "bytes": c}
    if t == "window_ready":
        return {"window": a, "bytes": b, "fetch_us": c}
    if t == "window_stall":
        return {"window": a, "stall_us": c}
    if t in ("suspect", "suspect_clear"):
        return {"peer": a, "source": "ladder" if b else "heartbeat"}
    if t == "quota_reject":
        return {"bytes": a}
    if t == "lane_budget_rotate":
        return {"lanes": a, "rotation": b}
    if t == "flight":
        return {"reason": TRACE_FLIGHT_REASONS.get(a, a)}
    if t == "failover":
        return {"dead_owner": a, "served_by": b, "ops": c}
    if t == "plan_applied":
        return {"replan": a, "engaged": b, "depth": c}
    return {"a": a, "b": b, "c": c}


def chrome_trace(events: np.ndarray) -> List[Dict]:
    """Chrome trace-event JSON array. Ops and serve legs become async
    begin/end pairs keyed by span id (nesting renders in Perfetto's
    async tracks); everything else becomes an instant event. pid =
    rank, tid = native thread id."""
    events = np.asarray(events, dtype=TRACE_EVENT_DTYPE)
    if events.size == 0:
        return []
    t0 = int(events["t_ns"].min())
    out: List[Dict] = []
    begin = {"op_begin", "serve_begin", "window_issue"}
    end = {"op_end", "serve_end", "window_ready"}
    for ev in events:
        t = TRACE_TYPES.get(int(ev["type"]), "")
        rec = {
            "name": _event_name(ev),
            "cat": "ddstore",
            "ts": (int(ev["t_ns"]) - t0) / 1e3,  # microseconds
            "pid": int(ev["rank"]),
            "tid": int(ev["tid"]),
            "args": _args_of(ev),
        }
        span = int(ev["span"])
        if span and t in begin:
            rec["ph"] = "b"
            rec["id"] = f"{span:x}"
        elif span and t in end:
            rec["ph"] = "e"
            rec["id"] = f"{span:x}"
        else:
            rec["ph"] = "i"
            rec["s"] = "t"
            if span:
                rec["args"]["span"] = f"{span:x}"
        out.append(rec)
    return out


def span_tree(events: np.ndarray, span: Optional[int] = None,
              max_spans: int = 50) -> str:
    """Plain-text postmortem rendering: one block per span (time
    order), every event on its own line with rank/thread/timing — the
    flight dump of a failed read names the dead peer, the suspect
    verdict and each replica-rerouted op in one read."""
    events = np.asarray(events, dtype=TRACE_EVENT_DTYPE)
    if events.size == 0:
        return "(no events)"
    events = events[np.argsort(events["t_ns"], kind="stable")]
    t0 = int(events["t_ns"].min())
    by_span: Dict[int, List] = {}
    loose: List = []
    for ev in events:
        s = int(ev["span"])
        if span is not None and s != span:
            continue
        (by_span.setdefault(s, []) if s else loose).append(ev)
    lines: List[str] = []

    def fmt(ev, indent="  "):
        dt_ms = (int(ev["t_ns"]) - t0) / 1e6
        args = ", ".join(f"{k}={v}" for k, v in _args_of(ev).items())
        return (f"{indent}+{dt_ms:9.3f}ms r{int(ev['rank'])}/t"
                f"{int(ev['tid'])} {_event_name(ev)} ({args})")

    shown = 0
    for s, evs in sorted(by_span.items(),
                         key=lambda kv: int(kv[1][0]["t_ns"])):
        if shown >= max_spans:
            lines.append(f"... {len(by_span) - shown} more span(s)")
            break
        shown += 1
        lines.append(f"span {s:x}:")
        lines.extend(fmt(ev) for ev in evs)
    if loose and span is None:
        lines.append("(unspanned):")
        lines.extend(fmt(ev) for ev in loose)
    return "\n".join(lines)


def _percentile(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = min(len(xs) - 1, max(0, int(round(q / 100 * (len(xs) - 1)))))
    return xs[k]


def span_latency(events: np.ndarray) -> Dict[str, Dict]:
    """Measured op latency percentiles per ``(class, route, peer)``
    from op begin/end pairs — ``class`` the op kind, ``route`` derived
    from the span's transport events (``cma`` when a CMA read served
    it, ``tcp`` when a wire/serve leg did, else ``local``), ``peer``
    the begin event's target (-1 = multi-peer). Keys are
    ``"class|route|peer"``; values carry count/p50_ms/p99_ms."""
    events = np.asarray(events, dtype=TRACE_EVENT_DTYPE)
    begins: Dict = {}
    route: Dict = {}
    samples: Dict[str, List[float]] = {}
    for ev in events[np.argsort(events["t_ns"], kind="stable")]:
        t = TRACE_TYPES.get(int(ev["type"]), "")
        s = int(ev["span"])
        if not s:
            continue
        if t == "cma_read":
            route[s] = "cma"
        elif t in ("serve_begin", "serve_end", "lane_dial", "retry") \
                and route.get(s) != "cma":
            route[s] = "tcp"
        if t == "op_begin":
            key = (s, int(ev["a"]))
            # First begin wins: async issue -> completion is THE span
            # latency; nested execution legs refine the route only.
            begins.setdefault(key, (int(ev["t_ns"]), int(ev["b"])))
        elif t == "op_end":
            key = (s, int(ev["a"]))
            if key not in begins:
                continue
            t_begin, peer = begins[key]
            cls = TRACE_OP_CLASSES.get(int(ev["a"]), str(int(ev["a"])))
            k = f"{cls}|{route.get(s, 'local')}|{peer}"
            samples.setdefault(k, []).append(
                (int(ev["t_ns"]) - t_begin) / 1e6)
    return {
        k: {"count": len(v),
            "p50_ms": round(_percentile(v, 50), 4),
            "p99_ms": round(_percentile(v, 99), 4)}
        for k, v in samples.items()}


# -- ddmetrics: live histogram cells (merge / percentiles / exporters) -------
#
# The native half (metrics_hist.{h,cc}) keeps per-store log2-bucketed
# latency/bytes histograms per (op class, route, peer, reading tenant);
# this half merges per-rank snapshots into one cluster view, derives
# percentiles, and renders them for humans (terminal table), Prometheus
# scrapers (exposition text) and dashboards (JSON).


def save_metrics(path: str, cells: np.ndarray) -> str:
    """Persist one rank's histogram snapshot
    (``DDStore.metrics_snapshot()``) as a ``.npy`` the metrics CLI
    consumes (``python -m ddstore_tpu.obs top``)."""
    arr = np.asarray(cells, dtype=METRICS_CELL_DTYPE)
    np.save(path, arr)
    return path if path.endswith(".npy") else path + ".npy"


def load_metrics(path: str) -> np.ndarray:
    arr = np.load(path)
    if arr.dtype != METRICS_CELL_DTYPE:
        raise ValueError(f"{path}: not a ddstore metrics snapshot "
                         f"(dtype {arr.dtype})")
    return arr


def _cell_key(c) -> tuple:
    return (int(c["cls"]), int(c["route"]), int(c["peer"]),
            bytes(c["tenant"]))


def merge_metrics(snapshots: Iterable[np.ndarray]) -> np.ndarray:
    """Merge per-rank cell snapshots into one cluster view: cells with
    equal (class, route, peer, tenant) keys sum bucket-wise —
    histograms compose exactly, unlike percentiles."""
    out: Dict[tuple, np.ndarray] = {}
    for snap in snapshots:
        snap = np.asarray(snap, dtype=METRICS_CELL_DTYPE)
        for c in snap:
            k = _cell_key(c)
            if k in out:
                acc = out[k]
                for f in ("count", "lat_sum_ns", "bytes_sum", "lat",
                          "bytes"):
                    acc[f] += c[f]
            else:
                out[k] = c.copy()
    if not out:
        return np.empty(0, dtype=METRICS_CELL_DTYPE)
    return np.array([out[k] for k in sorted(out)],
                    dtype=METRICS_CELL_DTYPE)


def diff_metrics(begin: Optional[np.ndarray],
                 end: np.ndarray) -> np.ndarray:
    """Per-window delta of two cumulative snapshots of ONE store
    (``end - begin`` bucket-wise; cells absent from ``begin`` delta
    against zero). Counters are monotone EXCEPT across a
    ``metrics_reset()``: a field that fell below its baseline reads as
    "the window restarted at zero" (the raw end value), never as a
    wrapped ~2^64 uint — the same clamp the native SLO window applies."""
    end = np.asarray(end, dtype=METRICS_CELL_DTYPE)
    if begin is None or len(begin) == 0:
        return end.copy()
    base = {_cell_key(c): c for c in
            np.asarray(begin, dtype=METRICS_CELL_DTYPE)}
    rows = []
    for c in end:
        b = base.get(_cell_key(c))
        d = c.copy()
        if b is not None:
            for f in ("count", "lat_sum_ns", "bytes_sum"):
                d[f] = d[f] - b[f] if d[f] >= b[f] else d[f]
            for f in ("lat", "bytes"):
                d[f] = np.where(d[f] >= b[f], d[f] - b[f], d[f])
        if int(d["count"]) > 0:
            rows.append(d)
    return np.array(rows, dtype=METRICS_CELL_DTYPE) if rows \
        else np.empty(0, dtype=METRICS_CELL_DTYPE)


def hist_percentile(hist, q: float) -> int:
    """The q-th percentile of a log2-bucketed histogram, reported as
    the quantile bucket's UPPER bound (ns/bytes) — conservative, and
    within one log2 bucket of the exact value by construction. 0 when
    the histogram is empty."""
    hist = np.asarray(hist, dtype=np.uint64)
    n = int(hist.sum())
    if n == 0:
        return 0
    want = -(-n * q // 100)  # ceil(q/100 * n)
    cum = 0
    for b, v in enumerate(hist):
        cum += int(v)
        if cum >= want:
            return 1 << (b + 1)
    return 1 << METRICS_BUCKETS


def _cell_label(c) -> str:
    cls = TRACE_OP_CLASSES.get(int(c["cls"]), str(int(c["cls"])))
    route = METRICS_ROUTES.get(int(c["route"]), str(int(c["route"])))
    tenant = bytes(c["tenant"]).split(b"\0", 1)[0].decode(
        errors="replace")
    return f"{cls}|{route}|{int(c['peer'])}|{tenant}"


def latency_table(cells: np.ndarray) -> Dict[str, Dict]:
    """``summary()["latency"]``'s payload: one row per cell keyed
    ``"class|route|peer|tenant"`` with count, mean and conservative
    p50/p90/p99 (bucket upper bounds, ms) plus the bytes side."""
    cells = np.asarray(cells, dtype=METRICS_CELL_DTYPE)
    out: Dict[str, Dict] = {}
    for c in cells:
        n = int(c["count"])
        if n == 0:
            continue
        row = {
            "count": n,
            "mean_ms": round(int(c["lat_sum_ns"]) / n / 1e6, 4),
            "p50_ms": round(hist_percentile(c["lat"], 50) / 1e6, 4),
            "p90_ms": round(hist_percentile(c["lat"], 90) / 1e6, 4),
            "p99_ms": round(hist_percentile(c["lat"], 99) / 1e6, 4),
            "bytes": int(c["bytes_sum"]),
            "p99_bytes": hist_percentile(c["bytes"], 99),
        }
        out[_cell_label(c)] = row
    return out


def latency_text(cells: np.ndarray, title: str = "live latency") -> str:
    """Terminal rendering of :func:`latency_table` (the ``obs top``
    view and the ``obs latency`` report's sibling)."""
    table = latency_table(cells)
    head = (f"{'class|route|peer|tenant':<36} {'count':>8} "
            f"{'mean_ms':>9} {'p50_ms':>9} {'p90_ms':>9} "
            f"{'p99_ms':>9} {'MB':>9}")
    lines = [f"# {title}", head, "-" * len(head)]
    for key in sorted(table):
        r = table[key]
        lines.append(
            f"{key:<36} {r['count']:>8} {r['mean_ms']:>9.3f} "
            f"{r['p50_ms']:>9.3f} {r['p90_ms']:>9.3f} "
            f"{r['p99_ms']:>9.3f} {r['bytes'] / 1e6:>9.2f}")
    if not table:
        lines.append("(no samples)")
    return "\n".join(lines)


def _prom_escape(v: str) -> str:
    """Prometheus exposition label-value escaping: backslash, double
    quote and newline must be escaped or the scraper rejects the whole
    scrape, not just the one series."""
    return v.replace("\\", r"\\").replace('"', r"\"") \
            .replace("\n", r"\n")


def prometheus_text(cells: np.ndarray,
                    prefix: str = "ddstore") -> str:
    """Prometheus exposition text: one classic histogram per cell
    (``<prefix>_op_latency_seconds`` with cumulative ``le`` buckets,
    ``_sum``/``_count``) plus ``<prefix>_op_bytes_total``. Labels:
    class/route/peer/tenant."""
    cells = np.asarray(cells, dtype=METRICS_CELL_DTYPE)
    lines = [
        f"# HELP {prefix}_op_latency_seconds "
        f"Store op latency (log2 buckets).",
        f"# TYPE {prefix}_op_latency_seconds histogram",
    ]
    byte_lines = [
        f"# HELP {prefix}_op_bytes_total Bytes delivered by store ops.",
        f"# TYPE {prefix}_op_bytes_total counter",
    ]
    for c in cells:
        n = int(c["count"])
        if n == 0:
            continue
        cls = TRACE_OP_CLASSES.get(int(c["cls"]), str(int(c["cls"])))
        route = METRICS_ROUTES.get(int(c["route"]),
                                   str(int(c["route"])))
        tenant = _prom_escape(bytes(c["tenant"]).split(b"\0", 1)[0]
                              .decode(errors="replace"))
        labels = (f'class="{cls}",route="{route}",'
                  f'peer="{int(c["peer"])}",tenant="{tenant}"')
        cum = 0
        for b in range(METRICS_BUCKETS):
            v = int(c["lat"][b])
            if v == 0:
                continue
            cum += v
            le = (1 << (b + 1)) / 1e9
            lines.append(f"{prefix}_op_latency_seconds_bucket"
                         f"{{{labels},le=\"{le:g}\"}} {cum}")
        lines.append(f"{prefix}_op_latency_seconds_bucket"
                     f"{{{labels},le=\"+Inf\"}} {n}")
        # Full ns precision (never %g): at 6 significant digits a
        # long-lived sum stops moving between scrapes and
        # rate(..._sum) flatlines while ops are flowing.
        lines.append(f"{prefix}_op_latency_seconds_sum{{{labels}}} "
                     f"{int(c['lat_sum_ns']) / 1e9:.9f}")
        lines.append(f"{prefix}_op_latency_seconds_count{{{labels}}} "
                     f"{n}")
        byte_lines.append(f"{prefix}_op_bytes_total{{{labels}}} "
                          f"{int(c['bytes_sum'])}")
    return "\n".join(lines + byte_lines) + "\n"


def metrics_json(cells: np.ndarray) -> Dict:
    """JSON-serializable dump of the cells: the latency table plus the
    raw bucket arrays (dashboards re-bucket/re-aggregate from these)."""
    cells = np.asarray(cells, dtype=METRICS_CELL_DTYPE)
    out: Dict = {"buckets": METRICS_BUCKETS, "cells": {}}
    for c in cells:
        if int(c["count"]) == 0:
            continue
        out["cells"][_cell_label(c)] = {
            "count": int(c["count"]),
            "lat_sum_ns": int(c["lat_sum_ns"]),
            "lat": [int(v) for v in c["lat"]],
            "bytes_sum": int(c["bytes_sum"]),
            "bytes": [int(v) for v in c["bytes"]],
        }
    return out


def trace_summary(stats: Dict, events: Optional[np.ndarray] = None) -> Dict:
    """The ``summary()["trace"]`` payload: the counter snapshot
    (:func:`ddstore_tpu.binding.trace_stats`) plus ring occupancy and —
    when ``events`` is given — the measured per-(class, route, peer)
    span latency percentiles."""
    out = dict(stats)
    cap = int(out.get("capacity", 0))
    out["ring_occupancy"] = round(int(out.get("live", 0)) / cap, 4) \
        if cap else 0.0
    if events is not None and len(events):
        out["span_latency"] = span_latency(events)
    return out
