"""Device-collective batch fetch: local reads + one ICI all_to_all.

The host path (``DDStore.get_batch`` + ``device_put``) moves every
remote row of a shuffled batch over DCN/TCP/CMA into host RAM and then
copies it to the devices a second time — the r5 profile showed that
host→device hop alone (3.8 ms against a 0.25 ms step) is the whole VAE
pipeline story. The SC'23 reference cannot do better: its fetch *is* a
host-network one-sided read (SURVEY §2.3 names the TPU-native answer as
future work). This module is that answer:

* every host issues one purely **local** ``get_batch`` for the rows it
  owns (the planner partitions the global permuted batch by owner via
  the store's cumulative-row table),
* stages those rows to its devices in one sharded transfer, packed into
  per-destination send blocks,
* and delivers every row to its destination DP shard with an on-device
  ``jax.lax.all_to_all`` row exchange
  (:func:`ddstore_tpu.parallel.shuffle.exchange_rows`), whose ICI
  bandwidth dwarfs the DCN path.

Shapes are static per (batch, mesh, store-world) configuration: each
(source, destination) block is padded to the data-independent capacity
``ceil(per_shard / shards_per_owner)``, so jit compiles the exchange
once and reuses it for every batch regardless of how ownership lands. A
send-count matrix plus an inverse local permutation restore exact batch
order (duplicates included); ragged rows ride the existing ragged pack
(``pad_ragged``) as fixed-width padded rows.

The bytes-moved ledger (``bytes_local_get`` / ``bytes_over_ici`` /
``bytes_over_dcn``) quantifies the divergence from the reference: the
host path pays DCN for every remote row, the collective path pays one
local read plus padded ICI blocks.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

__all__ = ["DeviceFetchPlan", "StagedFetch", "plan_device_fetch",
           "stage_batch", "stage_ragged_batch", "exchange_staged",
           "device_fetch_batch", "device_fetch_ragged_batch",
           "host_bytes_over_dcn"]


class DeviceFetchPlan:
    """Pure-host (numpy) plan for one device-collective fetch.

    Built once per index batch; reusable across co-variables fetched with
    the same indices (data + labels share one plan). All members are
    data-independent in *shape*: ``cap``, ``per_shard`` and the staged
    buffer geometry depend only on (batch, n_shards, owners), so the
    jitted exchange never recompiles across batches.
    """

    __slots__ = ("idx", "n_shards", "n_owners", "per_shard",
                 "shards_per_owner", "cap", "dest", "owner", "src", "slot",
                 "staged_pos", "inv", "send_counts", "owner_positions")

    def __init__(self, idx: np.ndarray, n_shards: int, n_owners: int,
                 per_shard: int, shards_per_owner: int, cap: int,
                 dest: np.ndarray, owner: np.ndarray, src: np.ndarray,
                 slot: np.ndarray, staged_pos: np.ndarray, inv: np.ndarray,
                 send_counts: np.ndarray,
                 owner_positions: List[np.ndarray]):
        self.idx = idx
        self.n_shards = n_shards
        self.n_owners = n_owners
        self.per_shard = per_shard
        self.shards_per_owner = shards_per_owner
        self.cap = cap
        self.dest = dest
        self.owner = owner
        self.src = src
        self.slot = slot
        self.staged_pos = staged_pos
        self.inv = inv
        self.send_counts = send_counts
        self.owner_positions = owner_positions

    @property
    def staged_rows(self) -> int:
        """Global staged-buffer rows: every shard sends ``n_shards``
        blocks of ``cap`` rows."""
        return self.n_shards * self.n_shards * self.cap

    def bytes_ledger(self, row_bytes: int,
                     rank: Optional[int] = None) -> dict:
        """Bytes the collective path moves for one batch of this plan.

        * ``bytes_local_get`` — rows an owner reads from its own shard
          (never crosses the host network).
        * ``bytes_over_ici`` — padded off-diagonal blocks the all_to_all
          exchanges (the diagonal block stays on its own device).
        * ``bytes_over_dcn`` — zero in the per-host deployment (every
          owner stages its own rows: THE point). With ``rank`` given —
          the honest single-controller accounting — rows owned by OTHER
          ranks that this one handle stages still cross the same host
          transport the host path uses, and are reported here instead
          of being relabeled local.
        """
        d, cap = self.n_shards, self.cap
        real = int(self.send_counts.sum()
                   - np.trace(self.send_counts))
        b = int(self.idx.size)
        own = b if rank is None else int((self.owner == rank).sum())
        return {
            "bytes_local_get": own * int(row_bytes),
            "bytes_over_ici": d * (d - 1) * cap * int(row_bytes),
            "bytes_over_dcn": (b - own) * int(row_bytes),
            "rows_over_ici": real,
        }


def plan_device_fetch(row_starts, indices, n_shards: int,
                      cap: Optional[int] = None) -> DeviceFetchPlan:
    """Partition a global permuted index batch by owner and lay out the
    on-device exchange.

    ``row_starts`` is the store's cumulative-row table
    (:meth:`DDStore.row_starts`, length ``owners + 1``); ownership of
    each index is a vectorized binary search over it. The mesh's batch
    axis (``n_shards`` shards) is split contiguously among owners —
    owner ``w`` stages onto shards ``[w*spo, (w+1)*spo)`` — so a host
    only ever writes its own devices' send blocks. Within one
    (owner, destination) group, rows are dealt round-robin across the
    owner's shards: block occupancy is bounded by
    ``cap = ceil(per_shard / spo)`` independent of the batch's ownership
    pattern, which is what keeps the exchange shape static.

    The default ``cap`` is that worst case (one owner holding every row
    a destination wants). Callers whose ownership is statistically
    balanced — a seeded global permutation over evenly-split shards —
    can pass a tighter ``cap`` to shrink the padded exchange; a batch
    that overflows it raises ``ValueError`` (fall back to the host path
    or replan with the default), it is never silently truncated.
    """
    idx = np.ascontiguousarray(indices, dtype=np.int64).reshape(-1)
    starts = np.ascontiguousarray(row_starts, dtype=np.int64)
    b = idx.size
    d = int(n_shards)
    w = len(starts) - 1
    if b == 0:
        raise ValueError("plan_device_fetch: empty index batch")
    if d <= 0 or b % d:
        raise ValueError(f"plan_device_fetch: batch {b} not divisible by "
                         f"{d} shards")
    if w <= 0 or d % w:
        raise ValueError(f"plan_device_fetch: {d} shards not divisible "
                         f"by {w} owners")
    if idx.min() < 0 or idx.max() >= starts[-1]:
        raise IndexError(f"plan_device_fetch: index out of range "
                         f"[0, {int(starts[-1])})")
    per = b // d
    spo = d // w
    if cap is None:
        cap = -(-per // spo)  # ceil: data-independent per-pair capacity
    cap = int(cap)
    if cap <= 0:
        raise ValueError(f"plan_device_fetch: cap must be positive, "
                         f"got {cap}")
    pos = np.arange(b, dtype=np.int64)
    dest = pos // per
    owner = (np.searchsorted(starts, idx, side="right") - 1).astype(np.int64)
    # Rank of each position inside its (owner, dest) group, positions in
    # ascending batch order (stable sort) — deals the group round-robin
    # over the owner's shards and front-packs each block's slots.
    key = owner * d + dest
    order = np.argsort(key, kind="stable")
    sk = key[order]
    group_start = np.flatnonzero(np.r_[True, sk[1:] != sk[:-1]])
    sizes = np.diff(np.r_[group_start, b])
    k_sorted = np.arange(b, dtype=np.int64) - np.repeat(group_start, sizes)
    k = np.empty(b, np.int64)
    k[order] = k_sorted
    src = owner * spo + (k % spo)
    slot = k // spo
    if int(slot.max()) >= cap:
        raise ValueError(
            f"plan_device_fetch: a (src, dest) block needs "
            f"{int(slot.max()) + 1} slots but cap is {cap} — this "
            f"batch's ownership is more skewed than the caller's cap "
            f"allows")
    staged_pos = src * (d * cap) + dest * cap + slot
    inv = (src * cap + slot).astype(np.int32)
    send_counts = np.bincount(src * d + dest,
                              minlength=d * d).reshape(d, d)
    owner_positions = [np.flatnonzero(owner == r) for r in range(w)]
    return DeviceFetchPlan(idx, d, w, per, spo, cap, dest, owner, src,
                           slot, staged_pos, inv, send_counts,
                           owner_positions)


def host_bytes_over_dcn(store, name: str, indices) -> int:
    """Bytes the HOST path would pull over the DCN transport for this
    batch: every requested row whose owner is another rank (the ledger's
    A-side; local rows never leave the host either way)."""
    idx = np.ascontiguousarray(indices, dtype=np.int64).reshape(-1)
    if idx.size == 0:
        return 0
    owner = store.owner_of_rows(name, idx)
    return int((owner != store.rank).sum()) * store.row_nbytes(name)


class StagedFetch:
    """Host half of one device-collective fetch: the plan plus the
    filled send buffer, awaiting :func:`exchange_staged`.

    The split exists for thread discipline: host staging (local reads +
    buffer fill) is safe from any worker thread, but the exchange
    dispatches a COLLECTIVE program — and collective launches from
    multiple Python threads can interleave across the per-device
    executors and deadlock the rendezvous (observed on the CPU backend:
    two in-flight all_to_alls each holding half the device threads).
    All exchanges — and anything else that launches collectives, like
    the train step — must be dispatched from ONE thread;
    ``DeviceLoader`` finalizes staged fetches on the consumer thread for
    exactly this reason.
    """

    __slots__ = ("plan", "staged")

    def __init__(self, plan: DeviceFetchPlan, staged: np.ndarray):
        self.plan = plan
        self.staged = staged


def stage_batch(store, name: str, indices, n_shards: int,
                plan: Optional[DeviceFetchPlan] = None,
                metrics=None,
                rows: Optional[np.ndarray] = None) -> StagedFetch:
    """Host half: partition by owner, read each owner's rows LOCALLY,
    pack them into the padded send buffer. Thread-safe.

    ``rows``, when given, are the batch's rows already in batch order
    (the epoch-readahead window gather): no store reads happen here —
    the rows scatter straight into the send buffer, and only the ICI leg
    is ledgered (the window fetch recorded its transport bytes once,
    dedup included)."""
    m = store._require(name)
    if plan is None:
        plan = plan_device_fetch(store.row_starts(name), indices, n_shards)
    staged = np.zeros((plan.staged_rows,) + m.sample_shape, m.dtype)
    if rows is not None:
        if len(rows) != plan.idx.size:
            raise ValueError(f"stage_batch({name}): {len(rows)} "
                             f"prefetched rows for a {plan.idx.size}-row "
                             f"batch")
        staged[plan.staged_pos] = rows
        if metrics is not None:
            led = plan.bytes_ledger(store.row_nbytes(name),
                                    rank=store.rank)
            metrics.add_bytes(bytes_over_ici=led["bytes_over_ici"],
                              rows_over_ici=led["rows_over_ici"])
        return StagedFetch(plan, staged)
    for w, pw in enumerate(plan.owner_positions):
        if pw.size == 0:
            continue
        # Single-controller runtime: one handle stages every owner's
        # region, and each per-owner get_batch coalesces to single-peer
        # runs on owner w's shard. The true multi-process wiring (each
        # process fetching ONLY its own rank's rows and handing
        # jax.make_array_from_process_local_data just its local shard
        # slice) is not built yet — exchange_staged refuses multi-process
        # meshes loudly rather than silently pulling remote rows here.
        got = store.get_batch(name, plan.idx[pw])
        staged[plan.staged_pos[pw]] = got
    if metrics is not None:
        # rank-aware: other owners' rows staged through THIS handle
        # crossed the host transport and are ledgered as DCN, not
        # relabeled local (see bytes_ledger).
        metrics.add_bytes(**plan.bytes_ledger(store.row_nbytes(name),
                                              rank=store.rank))
    return StagedFetch(plan, staged)


def stage_ragged_batch(store, name: str, indices, n_shards: int,
                       max_len: int,
                       plan: Optional[DeviceFetchPlan] = None,
                       metrics=None) -> Tuple[StagedFetch, np.ndarray]:
    """Host half for a ragged variable: each owner's samples fetched
    locally (the ``add_ragged`` locality invariant keeps index row AND
    values span on one owner) and padded to the static ``max_len`` via
    the existing ragged pack. Returns the staged fetch plus the
    per-sample lengths in batch order."""
    from .ragged import pad_ragged

    index_var = f"{name}/index"
    values_var = f"{name}/values"
    m = store._require(values_var)
    if plan is None:
        plan = plan_device_fetch(store.row_starts(index_var), indices,
                                 n_shards)
    staged = np.zeros((plan.staged_rows, max_len) + m.sample_shape,
                      m.dtype)
    lengths = np.zeros(plan.idx.size, np.int64)
    local_bytes = remote_bytes = 0
    for w, pw in enumerate(plan.owner_positions):
        if pw.size == 0:
            continue
        values, lens = store.get_ragged_batch(name, plan.idx[pw])
        if w == store.rank:  # actual elements, unpadded
            local_bytes += values.size * values.dtype.itemsize
        else:  # staged through this handle: crossed the transport
            remote_bytes += values.size * values.dtype.itemsize
        padded, _mask = pad_ragged(values, lens, max_len)
        staged[plan.staged_pos[pw]] = padded
        lengths[pw] = lens
    if metrics is not None:
        led = plan.bytes_ledger(max_len * store.row_nbytes(values_var),
                                rank=store.rank)
        led["bytes_local_get"] = local_bytes
        led["bytes_over_dcn"] = remote_bytes
        metrics.add_bytes(**led)
    return StagedFetch(plan, staged), lengths


def exchange_staged(sf: StagedFetch, mesh, axis: str = "dp"):
    """Device half: put the send buffer + inverse permutation sharded
    over the batch axis and run the jitted all_to_all exchange. MUST be
    called from the single thread that dispatches every other collective
    program (see :class:`StagedFetch`)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.shuffle import exchange_rows

    if jax.process_count() > 1:
        # The staging half currently fills the GLOBAL send buffer from
        # one handle (single-controller semantics). Under multi-process
        # jax that would both pull remote rows over DCN (defeating the
        # point) and hand make_array_from_process_local_data the wrong
        # (global) shape — refuse loudly; the per-process local-slice
        # wiring is tracked as the next step of this path.
        raise NotImplementedError(
            "device-collective fetch is single-controller only for "
            "now: multi-process staging (per-host local slices) is "
            "not yet wired")
    sharding = NamedSharding(mesh, P(axis))
    staged_dev = jax.make_array_from_process_local_data(sharding,
                                                        sf.staged)
    inv_dev = jax.make_array_from_process_local_data(sharding,
                                                     sf.plan.inv)
    return exchange_rows(staged_dev, inv_dev, mesh=mesh, axis=axis)


def device_fetch_batch(store, name: str, indices, mesh, axis: str = "dp",
                       plan: Optional[DeviceFetchPlan] = None,
                       metrics=None):
    """Fetch arbitrary global rows as a device array sharded over
    ``axis``, moving remote rows over ICI instead of DCN.

    Byte-identical to ``device_put(store.get_batch(name, indices))``
    under the same sharding — duplicates included — but each host reads
    only the rows it owns (one coalesced local ``get_batch``) and the
    cross-host delivery is a single on-device collective. ``plan`` lets
    co-variables fetched with the same indices (data + labels) share one
    planning pass; ``metrics`` (anything with ``add_bytes(**ledger)``,
    e.g. :class:`~ddstore_tpu.utils.metrics.PipelineMetrics`) receives
    the bytes-moved ledger. Single-thread collective dispatch applies
    (see :class:`StagedFetch`); pipelined callers should stage on
    workers and :func:`exchange_staged` on the consumer thread, as
    ``DeviceLoader(device_collective=True)`` does.
    """
    sf = stage_batch(store, name, indices, int(mesh.shape[axis]),
                     plan=plan, metrics=metrics)
    return exchange_staged(sf, mesh, axis)


def device_fetch_ragged_batch(store, name: str, indices, mesh,
                              max_len: int, axis: str = "dp",
                              plan: Optional[DeviceFetchPlan] = None,
                              metrics=None) -> Tuple["object", np.ndarray]:
    """Ragged variant: samples ride the exchange as fixed-width rows via
    the existing ragged pack (``pad_ragged`` to the static ``max_len``).

    Returns ``(padded, lengths)``: ``padded`` is a device array of shape
    ``(batch, max_len, *item)`` sharded over ``axis`` (samples longer
    than ``max_len`` are truncated — same explicit overflow policy as
    ``pad_ragged``), and ``lengths`` is the host-side per-sample length
    vector in batch order (tiny; each owner learns its own lengths from
    its local index rows).
    """
    sf, lengths = stage_ragged_batch(store, name, indices,
                                     int(mesh.shape[axis]), max_len,
                                     plan=plan, metrics=metrics)
    return exchange_staged(sf, mesh, axis), lengths

