"""Ragged-batch utilities: pack and pad variable-length samples into the
static shapes XLA requires.

The reference has no ragged support at all (fixed-width rows with uniform
``disp`` enforced across ranks, /root/reference/include/ddstore.hpp:78-82);
its target workloads (graph neural networks on atomistic datasets,
README.md:200-212) are ragged in reality. This module is the host-side half
of that capability: :meth:`ddstore_tpu.store.DDStore.get_ragged_batch`
returns ``(values, lengths)`` and these functions lower them to dense
padded arrays + masks/segment ids, so the device step compiles once for a
fixed ``max_len``/``budget`` regardless of per-batch raggedness.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = ["pad_ragged", "split_ragged", "segment_ids_from_lengths",
           "pack_ragged"]


def split_ragged(values: np.ndarray, lengths: np.ndarray) -> list:
    """Inverse of concatenation: list of per-sample arrays (views)."""
    out, pos = [], 0
    for l in lengths:
        out.append(values[pos:pos + int(l)])
        pos += int(l)
    return out


def pad_ragged(values: np.ndarray, lengths: np.ndarray, max_len: int,
               pad_value=0) -> Tuple[np.ndarray, np.ndarray]:
    """Dense ``(batch, max_len, *item)`` + boolean mask ``(batch, max_len)``.

    Samples longer than ``max_len`` are truncated (caller picks ``max_len``
    as a dataset-level bound so truncation is the explicit overflow policy,
    not a silent one).
    """
    lengths = np.asarray(lengths, np.int64)
    b = len(lengths)
    item = values.shape[1:]
    out = np.full((b, max_len) + item, pad_value, dtype=values.dtype)
    mask = np.zeros((b, max_len), np.bool_)
    pos = 0
    for i, l in enumerate(lengths):
        l = int(l)
        keep = min(l, max_len)
        out[i, :keep] = values[pos:pos + keep]
        mask[i, :keep] = True
        pos += l
    return out, mask


def segment_ids_from_lengths(lengths: np.ndarray, total: int,
                             pad_segment: Optional[int] = None
                             ) -> np.ndarray:
    """Flat segment ids for ``jax.ops.segment_sum``-style aggregation:
    element j of sample i gets id i; positions past the real elements get
    ``pad_segment`` (default ``len(lengths)``, i.e. one trash segment)."""
    lengths = np.asarray(lengths, np.int64)
    n = int(lengths.sum())
    if total < n:
        raise ValueError(f"total {total} < sum(lengths) {n}")
    if pad_segment is None:
        pad_segment = len(lengths)
    ids = np.full(total, pad_segment, np.int32)
    ids[:n] = np.repeat(np.arange(len(lengths), dtype=np.int32), lengths)
    return ids


def pack_ragged(values: np.ndarray, lengths: np.ndarray, budget: int,
                pad_value=0):
    """Pack concatenated samples into a fixed element ``budget`` (the
    graph-batching scheme: one flat buffer + segment ids, no per-sample
    padding waste). Returns ``(flat, segment_ids, n_fit)`` where ``flat``
    has exactly ``budget`` element rows, ``segment_ids`` marks sample
    membership (padding rows get segment ``len(lengths)``), and ``n_fit``
    is how many whole samples fit — callers requeue the remainder.
    """
    lengths = np.asarray(lengths, np.int64)
    cum = np.cumsum(lengths)
    n_fit = int(np.searchsorted(cum, budget, side="right"))
    if n_fit == 0 and len(lengths):
        # A requeue-the-remainder caller would spin forever on this sample.
        raise ValueError(
            f"pack_ragged: first sample ({int(lengths[0])} elements) "
            f"exceeds budget {budget}")
    used = int(cum[n_fit - 1]) if n_fit else 0
    item = values.shape[1:]
    flat = np.full((budget,) + item, pad_value, dtype=values.dtype)
    flat[:used] = values[:used]
    seg = segment_ids_from_lengths(lengths[:n_fit], budget,
                                   pad_segment=n_fit)
    return flat, seg, n_fit
