"""Graph dataset over the store: ragged shards → packed static batches.

The reference's target workload is GNN training on atomistic datasets too
large for one node's RAM (README.md:200-212) but its store only handles
fixed-width rows and its example is an MNIST VAE. This module completes the
capability: per-rank lists of variable-size graphs are registered as ragged
variables (nodes / edge_index / edge_attr) plus a fixed-width target
variable, any rank fetches any graph one-sidedly, and batches are packed
into fixed node/edge budgets (``models.gnn.GraphBatch``) so the device step
compiles once.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Sequence

import numpy as np

from .ragged import split_ragged


class GraphBatch(NamedTuple):
    """One packed graph block per device slot (leading axis = device).

    Shapes (D = device slots, NB/EB = node/edge budgets, G = graphs per
    slot, F*/T = feature/target dims). Plain NamedTuple → a JAX pytree, so
    it shards and stages through :class:`DeviceLoader` unchanged.
    """
    nodes: Any        # (D, NB, Fn) float — node features, padded rows zero
    edge_src: Any     # (D, EB) int32 — source node index within the slot
    edge_dst: Any     # (D, EB) int32 — destination node index
    edge_attr: Any    # (D, EB, Fe) float
    edge_mask: Any    # (D, EB) bool — False on padding edges
    node_seg: Any     # (D, NB) int32 — graph id in [0, G]; G == padding
    node_mask: Any    # (D, NB) bool — False on padding nodes
    y: Any            # (D, G, T) float — per-graph targets
    graph_mask: Any   # (D, G) bool — False on padding graph slots


class GraphSample(NamedTuple):
    nodes: np.ndarray       # (n, Fn) float32
    edge_index: np.ndarray  # (e, 2) int64 — [src, dst] within the graph
    edge_attr: np.ndarray   # (e, Fe) float32
    y: np.ndarray           # (T,) float32


def synthetic_graphs(rng: np.random.Generator, n: int, fn: int = 8,
                     fe: int = 4, t: int = 1, min_nodes: int = 4,
                     max_nodes: int = 12, stamp: Optional[float] = None
                     ) -> List[GraphSample]:
    """QM9-shaped synthetic molecular graphs with a learnable smooth
    target (graph mean of a fixed nonlinear projection of node features).
    ``stamp`` overrides node features with a constant — the rank-stamp
    oracle of the reference test suite (test/demo.py:37)."""
    proj = np.linspace(-1.0, 1.0, fn, dtype=np.float32)
    out = []
    for _ in range(n):
        nn_ = int(rng.integers(min_nodes, max_nodes + 1))
        nodes = rng.standard_normal((nn_, fn)).astype(np.float32)
        if stamp is not None:
            nodes = np.full((nn_, fn), stamp, np.float32)
        # ring + random chords: connected, ~3 edges/node, both directions
        src = np.arange(nn_, dtype=np.int64)
        ring = np.stack([src, (src + 1) % nn_], axis=1)
        chords = rng.integers(0, nn_, size=(nn_, 2)).astype(np.int64)
        ei = np.concatenate([ring, ring[:, ::-1], chords], axis=0)
        ea = rng.standard_normal((len(ei), fe)).astype(np.float32)
        y = np.tanh(nodes @ proj).mean(keepdims=True).astype(np.float32)
        y = np.repeat(y, t)
        out.append(GraphSample(nodes, ei, ea, y))
    return out


def pack_graph_batch(graphs: Sequence[GraphSample], n_slots: int,
                     graphs_per_slot: int, node_budget: int,
                     edge_budget: int) -> GraphBatch:
    """Pack graphs into ``n_slots`` device slots of fixed budgets.

    Graphs that would overflow a slot's remaining node/edge budget are
    skipped (their slot stays masked) — the explicit overflow policy;
    callers size budgets as ``graphs_per_slot * max_nodes`` to make skips
    impossible for bounded datasets.
    """
    g = graphs_per_slot
    fn = graphs[0].nodes.shape[1]
    fe = graphs[0].edge_attr.shape[1]
    t = graphs[0].y.shape[0]
    D = n_slots
    nodes = np.zeros((D, node_budget, fn), np.float32)
    esrc = np.zeros((D, edge_budget), np.int32)
    edst = np.zeros((D, edge_budget), np.int32)
    eattr = np.zeros((D, edge_budget, fe), np.float32)
    emask = np.zeros((D, edge_budget), np.bool_)
    nseg = np.full((D, node_budget), g, np.int32)
    nmask = np.zeros((D, node_budget), np.bool_)
    y = np.zeros((D, g, t), np.float32)
    gmask = np.zeros((D, g), np.bool_)

    for d in range(D):
        npos = epos = 0
        for k in range(g):
            gi = d * g + k
            if gi >= len(graphs):
                break
            s = graphs[gi]
            nn_, ne = len(s.nodes), len(s.edge_index)
            if npos + nn_ > node_budget or epos + ne > edge_budget:
                continue  # slot stays masked for this graph
            nodes[d, npos:npos + nn_] = s.nodes
            nseg[d, npos:npos + nn_] = k
            nmask[d, npos:npos + nn_] = True
            esrc[d, epos:epos + ne] = s.edge_index[:, 0] + npos
            edst[d, epos:epos + ne] = s.edge_index[:, 1] + npos
            eattr[d, epos:epos + ne] = s.edge_attr
            emask[d, epos:epos + ne] = True
            y[d, k] = s.y
            gmask[d, k] = True
            npos += nn_
            epos += ne
    return GraphBatch(nodes, esrc, edst, eattr, emask, nseg, nmask, y, gmask)


class GraphShardedDataset:
    """Store-backed distributed graph dataset.

    Each rank registers its local list of graphs; the global sample space
    is the concatenation across the store group. ``fetch`` returns a packed
    :class:`GraphBatch` ready for the DP train step, so it plugs straight
    into :class:`ddstore_tpu.data.DeviceLoader` (batch_size must be
    ``n_slots * graphs_per_slot``).
    """

    def __init__(self, store, graphs: Sequence[GraphSample],
                 name: str = "graphs", graphs_per_slot: int = 8,
                 node_budget: Optional[int] = None,
                 edge_budget: Optional[int] = None):
        self.store = store
        self.name = name
        self.graphs_per_slot = int(graphs_per_slot)
        store.add_ragged(f"{name}/nodes", [g.nodes for g in graphs])
        store.add_ragged(f"{name}/edge_index",
                         [g.edge_index.astype(np.int64) for g in graphs])
        store.add_ragged(f"{name}/edge_attr",
                         [g.edge_attr for g in graphs])
        ys = (np.stack([g.y for g in graphs])
              if graphs else np.empty((0, 1), np.float32))
        store.add(f"{name}/y", ys.astype(np.float32))
        # Budgets must be global (identical compile shapes on every rank):
        # agree on the max via the group, like the reference's disp
        # agreement check (ddstore.hpp:78-82) but taking the max.
        ln, le = (max((len(g.nodes) for g in graphs), default=0),
                  max((len(g.edge_index) for g in graphs), default=0))
        maxes = store.group.allgather((ln, le))
        max_nodes = max(m[0] for m in maxes)
        max_edges = max(m[1] for m in maxes)
        self.node_budget = int(node_budget or graphs_per_slot * max_nodes)
        self.edge_budget = int(edge_budget or graphs_per_slot * max_edges)

    def __len__(self) -> int:
        return self.store.ragged_total(f"{self.name}/nodes")

    def fetch_graphs(self, indices) -> List[GraphSample]:
        """Raw per-graph fetch (three batched ragged reads + one fixed)."""
        idx = np.ascontiguousarray(indices, np.int64).reshape(-1)
        nv, nl = self.store.get_ragged_batch(f"{self.name}/nodes", idx)
        ev, el = self.store.get_ragged_batch(f"{self.name}/edge_index", idx)
        av, al = self.store.get_ragged_batch(f"{self.name}/edge_attr", idx)
        ys = self.store.get_batch(f"{self.name}/y", idx)
        nodes = split_ragged(nv, nl)
        eidx = split_ragged(ev, el)
        eattr = split_ragged(av, al)
        return [GraphSample(n, e, a, y)
                for n, e, a, y in zip(nodes, eidx, eattr, ys)]

    def fetch(self, indices) -> GraphBatch:
        graphs = self.fetch_graphs(indices)
        if len(graphs) == 0 or len(graphs) % self.graphs_per_slot:
            # Silently dropping the tail would exclude samples from
            # training and vary the leading dim (recompiles / sharding
            # mismatch); batch sizes must be a multiple of graphs_per_slot
            # (use DeviceLoader's drop_last for ragged tails).
            raise ValueError(
                f"fetch: got {len(graphs)} graphs, need a nonzero multiple "
                f"of graphs_per_slot={self.graphs_per_slot}")
        n_slots = len(graphs) // self.graphs_per_slot
        return pack_graph_batch(graphs, n_slots, self.graphs_per_slot,
                                self.node_budget, self.edge_budget)

    def free(self) -> None:
        for suffix in ("nodes/values", "nodes/index", "edge_index/values",
                       "edge_index/index", "edge_attr/values",
                       "edge_attr/index", "y"):
            self.store.free(f"{self.name}/{suffix}")
