"""Dataset adapters and device-feeding loaders over the store."""

from .dataset import DistributedSampler, ShardedDataset
from .loader import DeviceLoader

__all__ = ["ShardedDataset", "DistributedSampler", "DeviceLoader"]
