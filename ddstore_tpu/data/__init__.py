"""Dataset adapters and device-feeding loaders over the store."""

from .dataset import DistributedSampler, ShardedDataset, nsplit
from .device_fetch import (device_fetch_batch, device_fetch_ragged_batch,
                           host_bytes_over_dcn, plan_device_fetch)
from .permute import FeistelPermutation
from .formats import (find_mnist, load_mnist, load_qm9_dir,
                      molecule_to_graph, read_idx, read_xyz,
                      synthetic_mnist, write_idx, write_xyz)
from .graphs import (GraphBatch, GraphSample, GraphShardedDataset,
                     pack_graph_batch, synthetic_graphs)
from .loader import DeviceLoader
from .ragged import (pack_ragged, pad_ragged, segment_ids_from_lengths,
                     split_ragged)
from .readahead import (EpochReadahead, WindowPlan, plan_epoch_windows,
                        plan_window)

__all__ = ["ShardedDataset", "DistributedSampler", "DeviceLoader", "nsplit",
           "FeistelPermutation",
           "EpochReadahead", "WindowPlan", "plan_window",
           "plan_epoch_windows",
           "plan_device_fetch", "device_fetch_batch",
           "device_fetch_ragged_batch", "host_bytes_over_dcn",
           "pad_ragged", "pack_ragged", "split_ragged",
           "segment_ids_from_lengths", "GraphBatch", "GraphSample",
           "GraphShardedDataset", "pack_graph_batch", "synthetic_graphs",
           "read_idx", "write_idx", "find_mnist", "load_mnist",
           "synthetic_mnist",
           "read_xyz", "write_xyz", "molecule_to_graph", "load_qm9_dir"]
