"""Dataset adapters and device-feeding loaders over the store."""

from .dataset import DistributedSampler, ShardedDataset
from .graphs import (GraphBatch, GraphSample, GraphShardedDataset,
                     pack_graph_batch, synthetic_graphs)
from .loader import DeviceLoader
from .ragged import (pack_ragged, pad_ragged, segment_ids_from_lengths,
                     split_ragged)

__all__ = ["ShardedDataset", "DistributedSampler", "DeviceLoader",
           "pad_ragged", "pack_ragged", "split_ragged",
           "segment_ids_from_lengths", "GraphBatch", "GraphSample",
           "GraphShardedDataset", "pack_graph_batch", "synthetic_graphs"]
