"""Store-backed dataset adapter and the global index sampler.

Parity with the reference's L3 integration (examples/vae/distdataset.py and
the DistributedSampler it relies on, SURVEY §2 C4) with its latent bugs
fixed by construction:

* sample-major indexing — one global row IS one sample (`disp` = flattened
  sample size), fixing the flattened-blob ``disp=1`` trap
  (distdataset.py:63,84 where fetching ``start=idx`` returned float idx,
  not sample idx);
* labels are a co-variable fetched in the same batched read pattern;
* replica-width groups are handled by the store core, not ad-hoc env vars.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..store import DDStore


def nsplit(n: int, parts: int) -> list:
    """Row counts for splitting n rows into `parts` near-equal contiguous
    chunks (reference nsplit, distdataset.py:9-11 — counts, not slices)."""
    base, rem = divmod(n, parts)
    return [base + (1 if i < rem else 0) for i in range(parts)]


class ShardedDataset:
    """Partition a dataset across the store group and serve any sample.

    Each rank passes its FULL local copy (or its slice, with
    ``pre_sharded=True``) of ``data``/``labels``; the adapter takes this
    rank's contiguous chunk, registers both variables, and serves global
    indices ``[0, total)`` from any rank.
    """

    def __init__(self, store: DDStore, data: np.ndarray,
                 labels: Optional[np.ndarray] = None, name: str = "ds",
                 pre_sharded: bool = False):
        self.store = store
        self.name = name
        self._data_var = f"{name}/data"
        self._label_var = f"{name}/labels" if labels is not None else None

        if pre_sharded:
            shard = np.ascontiguousarray(data)
            lshard = None if labels is None else np.ascontiguousarray(labels)
        else:
            counts = nsplit(len(data), store.world)
            begin = int(sum(counts[: store.rank]))
            end = begin + counts[store.rank]
            shard = np.ascontiguousarray(data[begin:end])
            lshard = None if labels is None else np.ascontiguousarray(
                labels[begin:end])
        if labels is not None and len(shard) != len(lshard):
            raise ValueError("data/labels length mismatch")

        store.add(self._data_var, shard)
        if self._label_var:
            store.add(self._label_var, lshard)
        self._total = store.total_rows(self._data_var)

    def __len__(self) -> int:
        return self._total

    @property
    def data_var(self) -> str:
        """Store variable holding the samples — the handle
        :class:`~ddstore_tpu.data.loader.DeviceLoader` uses for the
        device-collective fetch path (``device_collective=True``)."""
        return self._data_var

    @property
    def label_var(self) -> Optional[str]:
        """Co-variable holding the labels (None when label-free)."""
        return self._label_var

    def __getitem__(self, idx: int):
        x = self.store.get(self._data_var, int(idx))[0]
        if self._label_var is None:
            return x
        return x, self.store.get(self._label_var, int(idx))[0]

    def fetch(self, indices: Sequence[int]):
        """Batched fetch — the hot path (one coalesced one-sided read per
        peer instead of the reference's 2 blocking reads per sample,
        SURVEY §3.2)."""
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        x = self.store.get_batch(self._data_var, idx)
        if self._label_var is None:
            return x
        return x, self.store.get_batch(self._label_var, idx)

    def free(self) -> None:
        self.store.free(self._data_var)
        if self._label_var:
            self.store.free(self._label_var)


class DistributedSampler:
    """Deterministic per-epoch partition of the global index space: rank r
    draws indices r, r+world, ... of a seeded permutation, padded by
    wrapping so every rank yields the same count (the property the
    reference leans on torch's DistributedSampler for — equal batch counts
    keep its collective fences aligned, SURVEY §3.3).

    Memory: the permutation is a Feistel bijection evaluated on demand in
    ``block``-sized chunks — a 1e9-row epoch iterates in O(block) memory
    instead of materializing 8 GB of indices per rank (VERDICT r3 weak
    #5). ``mode="dense"`` keeps the materialized ``np.permutation`` path
    (byte-compatible with round-3 orders) and is the default below
    ``DENSE_MAX`` rows, where the array is cheap and Fisher–Yates mixing
    is marginally better.
    """

    # 16M rows = 128 MB of int64 — fine to hold (shared policy constant
    # with the global shuffles, see data/permute.py).
    from .permute import DENSE_MAX as DENSE_MAX

    def __init__(self, total: int, world: int, rank: int,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False, mode: str = "auto",
                 block: int = 1 << 20):
        if not 0 <= rank < world:
            raise ValueError("rank out of range")
        if mode not in ("auto", "dense", "streamed"):
            raise ValueError(f"unknown mode: {mode!r}")
        self.total = total
        self.world = world
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.block = block
        self.mode = mode
        if drop_last:
            self.num_samples = total // world
        else:
            self.num_samples = (total + world - 1) // world

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.num_samples

    def _streamed(self) -> bool:
        return self.mode == "streamed" or (self.mode == "auto"
                                           and self.total > self.DENSE_MAX)

    def _perm(self):
        from .permute import FeistelPermutation
        return FeistelPermutation(self.total, (self.seed, self.epoch))

    def _stream_blocks(self, start: int, stop: int):
        """This rank's indices for global positions [start, stop), in
        O(block) memory. Position p maps to perm(p % total) — identical
        wrap-padding semantics to the dense path's np.resize tiling."""
        perm = self._perm() if self.shuffle else None
        for lo in range(start, stop, self.block * self.world):
            hi = min(stop, lo + self.block * self.world)
            pos = np.arange(lo + self.rank, hi, self.world,
                            dtype=np.int64) % self.total
            yield perm(pos) if perm is not None else pos

    def __iter__(self):
        if self._streamed():
            def gen():
                for chunk in self._stream_blocks(
                        0, self.num_samples * self.world):
                    yield from chunk.tolist()
            return gen()
        if self.shuffle:
            g = np.random.default_rng((self.seed, self.epoch))
            order = g.permutation(self.total)
        else:
            order = np.arange(self.total)
        if self.drop_last:
            order = order[: self.num_samples * self.world]
        else:
            # np.resize tiles the permutation, so padding works even when
            # total < world (every rank still gets num_samples indices).
            order = np.resize(order, self.num_samples * self.world)
        return iter(order[self.rank:: self.world])

    def batches(self, batch_size: int):
        """This rank's epoch as consecutive index arrays of
        ``batch_size`` (the :meth:`DDStore.get_batch` fetch unit; the
        last batch may be short). Streamed mode yields in O(block)
        memory — THE way to iterate a 10^8+-row epoch without ever
        materializing it."""
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got "
                             f"{batch_size}")

        def chunks():
            if self._streamed():
                yield from self._stream_blocks(
                    0, self.num_samples * self.world)
            else:
                yield self.epoch_indices()

        carry = np.empty((0,), np.int64)
        for c in chunks():
            carry = c if carry.size == 0 else np.concatenate([carry, c])
            while carry.size >= batch_size:
                yield carry[:batch_size]
                carry = carry[batch_size:]
        if carry.size:
            yield carry

    def epoch_indices(self) -> np.ndarray:
        """This rank's full epoch as one array (for batched fetching)."""
        if self._streamed():
            chunks = list(self._stream_blocks(
                0, self.num_samples * self.world))
            return np.concatenate(chunks) if chunks else \
                np.empty((0,), np.int64)
        return np.fromiter(iter(self), dtype=np.int64,
                           count=self.num_samples)
