"""Prefetching device loader: store → host batch → sharded device arrays.

The reference's hot loop fetches every sample synchronously inside
``DataLoader.__next__`` with zero prefetch and zero batching
(num_workers=0, two blocking one-sided reads per sample — SURVEY §3.2/§3.3,
called out in §7 as the anti-pattern to fix). Here the loader:

* draws whole batches of indices from the sampler,
* fetches them with one coalesced, multi-peer ``get_batch``,
* stages them to devices with a sharded transfer
  (``jax.make_array_from_process_local_data`` — each DP shard receives its
  slice directly),
* runs fetch+stage on a background thread, `prefetch` batches deep, so
  host I/O overlaps device compute (double buffering by default),
* records the BASELINE.json metrics (device-wait, fetch and stage
  latencies, input-pipeline efficiency).
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

from ..binding import ERR_ADMISSION, ERR_PEER_LOST, DDStoreError
from ..utils.metrics import PipelineMetrics
from ..utils.profile import annotate

try:  # the loader is importable without jax for host-only use
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
except Exception:  # pragma: no cover
    jax = None


class _PendingExchange:
    """A staged collective fetch whose device exchange still needs the
    consumer thread (single-thread collective dispatch discipline)."""

    __slots__ = ("finalize",)

    def __init__(self, finalize: Callable):
        self.finalize = finalize


class DeviceLoader:
    """Iterate device-ready (sharded) batches from a store-backed dataset.

    Parameters
    ----------
    dataset: object with ``fetch(indices) -> array | tuple`` and ``__len__``
        (e.g. :class:`ShardedDataset`), or a bare callable.
    sampler: iterable of global indices for THIS rank's epoch (e.g.
        :class:`DistributedSampler`).
    batch_size: per-process batch size. With a mesh, must divide by the
        number of addressable devices on the batch axis.
    mesh / spec: optional device staging target. If given, batches are
        device arrays sharded over ``spec`` (default: leading dim over
        axis "dp"); if None, numpy batches are yielded (host-only mode).
    prefetch: how many batches are kept in flight ahead of the consumer.
    workers: fetch+stage worker threads. One worker pipelines host IO
        against device compute; more overlap multiple batches' host paths
        with each other — needed to keep small/fast models fed (ctypes
        releases the GIL during store reads, and staging is mostly
        off-GIL transfer work, so threads genuinely parallelize).
        Default (None): 2 for store-backed datasets (whose ``fetch`` is
        thread-safe by construction), 1 for a bare callable unless it
        declares ``thread_safe = True``. Passing an explicit ``workers``
        value is the caller's declaration that ``dataset.fetch`` is safe
        at that concurrency.
    drop_last: drop the trailing partial batch (keeps shapes static for
        jit — recompile-free epochs).
    device_collective: stage batches with the device-collective fetch
        (``data/device_fetch.py``): one purely local ``get_batch`` per
        host + an on-device ``all_to_all`` over ICI delivers every row
        to its destination DP shard — remote rows never cross DCN and
        the batch is device_put exactly once. Requires a mesh, the
        default ``P(axis)`` spec, no host transform, and a store-backed
        dataset exposing ``data_var``; anything else falls back to the
        host path with the reason in ``collective_fallback_reason``.
    readahead_windows: > 0 enables epoch-window readahead
        (``data/readahead.py``): the sampler's whole epoch is sliced
        into windows of ``readahead_window_batches`` batches, each
        window's rows fetched as ONE sorted deduplicated bulk read per
        variable through the native async engine into a preallocated
        staging ring of this many buffers — window N+1 stays in flight
        over the transport while window N is consumed, and per-batch
        delivery is an in-RAM gather. Composes with both the host path
        and ``device_collective`` (window staging happens before the
        ICI exchange). Needs a store-backed dataset (``store`` +
        fixed-width ``data_var``) and a *sized, replayable* sampler
        (two iterations yield identical indices — every
        ``DistributedSampler`` qualifies; a one-shot generator does
        not); otherwise the loader falls back to per-batch fetch with
        the reason in ``readahead_fallback_reason``.
    readahead_window_batches: window size W in batches (default 8).
        Bigger windows coalesce better (denser rows per peer shard →
        longer stripe-shaped runs) at the cost of staging memory:
        ``readahead_windows × W × batch_size`` rows per variable.
    transform: optional host-side function applied to each fetched batch.
        With workers > 1 the transform is serialized under a lock (fetch
        and staging still run in parallel), so stateful transforms — e.g.
        one sharing a np.random.Generator — are race-free by default.
        Note the lock guarantees exclusion, not order: workers reach the
        transform in fetch-completion order, so a shared RNG is consumed
        in a run-dependent sequence — for bit-deterministic augmentation
        pass workers=1. Mark the transform ``thread_safe = True`` (or
        pass ``transform_thread_safe=True``) to let it run concurrently.
    """

    def __init__(self, dataset, sampler: Iterable[int], batch_size: int,
                 mesh: Optional["Mesh"] = None, axis: str = "dp",
                 prefetch: int = 4, drop_last: bool = True,
                 transform: Optional[Callable] = None,
                 spec: Optional["PartitionSpec"] = None,
                 workers: Optional[int] = None,
                 transform_thread_safe: bool = False,
                 device_collective: bool = False,
                 readahead_windows: int = 0,
                 readahead_window_batches: int = 8):
        self.dataset = dataset
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.mesh = mesh
        self.axis = axis
        self.prefetch = max(1, int(prefetch))
        if workers is None:
            # Store-backed datasets expose fetch() whose reads go through
            # the native core (thread-safe by construction), so objects
            # default to 2 workers — but an explicit thread_safe attribute
            # on the dataset wins in either direction; bare callables
            # default to a single worker unless they opt in.
            fetch_safe = getattr(dataset, "thread_safe",
                                 not callable(dataset))
            workers = 2 if fetch_safe else 1
        self.workers = max(1, int(workers))
        self.drop_last = drop_last
        self.transform = transform
        self._transform_lock = None
        if (transform is not None and self.workers > 1
                and not transform_thread_safe
                and not getattr(transform, "thread_safe", False)):
            self._transform_lock = threading.Lock()
        self.metrics = PipelineMetrics()
        # Store-backed datasets expose their DDStore; wiring its planner
        # counters in gives every epoch summary the scatter-read plan view
        # (runs/peer, coalesce ratio, dedup hits) alongside the latencies.
        store = getattr(dataset, "store", None)
        if store is not None and hasattr(store, "plan_stats"):
            self.metrics.set_plan_source(store.plan_stats)
        if store is not None and hasattr(store, "fault_stats"):
            # Epoch summaries carry the fault/retry ledger next to the
            # plan view: summary()["faults"] is how a chaos run proves
            # "faults absorbed, zero give-ups" from the record alone.
            self.metrics.set_fault_source(store.fault_stats)
        if store is not None and hasattr(store, "failover_stats"):
            # Replicated-read failover ledger: summary()["failover"]
            # shows per-epoch reroutes/suspects/mirror traffic — an R>1
            # epoch that lost a rank proves "replicas served, zero
            # give-ups" from the record alone.
            self.metrics.set_failover_source(store.failover_stats)
        if store is not None and hasattr(store, "tenant_stats"):
            # Multi-tenant ledger: summary()["tenants"] carries each
            # tenant's per-epoch quota rejections, admission/deferral
            # counts and read/served traffic — a shared-service epoch
            # proves its QoS behavior from the record alone. Inert
            # (empty) on single-tenant stores.
            self.metrics.set_tenant_source(store.tenant_stats)
        if store is not None and hasattr(store, "trace_summary"):
            # ddtrace: summary()["trace"] carries this epoch's event
            # captures/drops, flight-recorder activity and measured
            # span-latency percentiles whenever tracing is on (inert —
            # and absent from the summary — while it is off). The
            # begin snapshot uses the cheap counters-only source.
            self.metrics.set_trace_source(
                store.trace_summary,
                getattr(store, "trace_stats", None))
        if store is not None and hasattr(store, "integrity_stats"):
            # Integrity ledger: summary()["integrity"] carries this
            # epoch's verified reads/bytes, mismatch/retry/failover
            # ladder activity and scrub results whenever verification
            # or scrubbing is in force (inert — and absent from the
            # summary — while both are off).
            self.metrics.set_integrity_source(store.integrity_stats)
        if store is not None and hasattr(store, "tiering_stats"):
            # Tiered-storage ledger: summary()["tiering"] carries this
            # epoch's hot-cache hit/miss/fill/evict deltas and the
            # cold-tier gauges whenever the cache is armed or a cold
            # variable is registered (inert — and absent from the
            # summary — otherwise).
            self.metrics.set_tiering_source(store.tiering_stats)
        if store is not None and hasattr(store, "metrics_snapshot"):
            # ddmetrics: summary()["latency"] carries this epoch's
            # live p50/p90/p99 per (class, route, peer, tenant) from
            # the always-on native histograms — no tracing required.
            self.metrics.set_latency_source(store.metrics_snapshot)
        if store is not None and hasattr(store, "slo_summary"):
            # SLO monitor: summary()["slo"] carries the per-epoch
            # evaluation/breach ledger; the epoch boundary below
            # evaluates the objectives and fires the scheduler's
            # replan trigger per breached tenant (inert with no SLOs
            # configured).
            self.metrics.set_slo_source(store.slo_summary)
        if store is not None and hasattr(store, "gateway_stats"):
            # Serving gateway: summary()["gateway"] carries this
            # epoch's admission/lease deltas (admitted/deferred/
            # rejected, attach/expiry churn) whenever the gateway is
            # armed (absent from the summary when off).
            self.metrics.set_gateway_source(store.gateway_stats)
        if store is not None and hasattr(store, "lane_bytes"):
            # Per-lane byte deltas land in summary()["bytes_moved"]
            # (lane_bytes / tcp_lanes_used / lane_utilization): whether
            # striped reads actually spread across the lane pool is
            # diagnosable from the epoch record alone.
            self.metrics.set_lane_source(store.lane_bytes)
        # Cost-model scheduler (ddstore_tpu.sched): plans route x lanes
        # x readahead depth x async width jointly from the shared
        # measurement substrate, replacing the knobs' independent
        # tuners whenever it has confident samples. Created even when
        # DDSTORE_SCHED=0 (disabled it never pins anything) so
        # summary()["sched"] always states the enablement — that is the
        # fact the sched bench A/B reads. User env pins freeze their
        # knobs; the planner plans the rest.
        self.sched = None
        if store is not None and hasattr(store, "sched_cells"):
            from ..sched.planner import Scheduler

            nvars = 1 + (1 if getattr(dataset, "label_var", None)
                         else 0)
            # requested_depth 0 = this loader runs no readahead: the
            # scheduler then plans route/lanes only and leaves the
            # depth/width knobs (and the store's other async users)
            # alone.
            self.sched = Scheduler(store, nvars=nvars,
                                   requested_depth=int(readahead_windows))
            self.metrics.set_sched_source(self.sched.snapshot)
        if mesh is not None and jax is None:  # pragma: no cover
            raise RuntimeError("jax unavailable but mesh given")
        # `spec` overrides the default leading-dim-over-`axis` layout, e.g.
        # P("dp", "sp") to stage sequence-sharded token windows directly in
        # the layout the train step's in_shardings demand.
        if spec is None:
            spec = PartitionSpec(axis)
        self._sharding = (NamedSharding(mesh, spec)
                         if mesh is not None else None)
        # Device-collective staging (`device_collective=True`): each
        # host reads only the rows it OWNS (one purely local get_batch),
        # stages them sharded, and an on-device all_to_all over ICI
        # delivers every row to its destination DP shard — the permuted
        # batch never rides DCN or the double host->device bounce. Falls
        # back to the host path automatically when the prerequisites
        # don't hold (no mesh, custom spec/transform, a dataset without
        # store+data_var, or a batch geometry the planner rejects);
        # `collective_fallback_reason` records why.
        self.device_collective = bool(device_collective)
        self.collective_fallback_reason: Optional[str] = None
        self._collective_ready = False
        if self.device_collective:
            self._collective_ready = self._collective_usable(
                dataset, mesh, axis, spec, transform)
        # Epoch-window readahead (`readahead_windows=K`): whole-epoch
        # read planning + bulk window fetches through the native async
        # engine, per-batch delivery as in-RAM gathers. Usability is
        # checked once here; the engine itself is per-epoch (built in
        # __iter__, closed in its finally — mid-epoch teardown waits
        # out and releases every in-flight native read).
        self.readahead_windows = max(0, int(readahead_windows))
        self.readahead_window_batches = max(1,
                                            int(readahead_window_batches))
        self.readahead_fallback_reason: Optional[str] = None
        self._readahead_ready = False
        # Staging ring handed from epoch to epoch (reallocating +
        # re-faulting the window buffers every epoch costs real time).
        self._ra_ring = None
        if self.readahead_windows > 0:
            self._readahead_ready = self._readahead_usable()
        # Mid-epoch degradation latch: once a readahead window fails
        # even its per-batch retry (a TRANSIENT failure — permanent
        # owner death raises instead), every worker of this epoch stops
        # consulting the engine and falls back to per-batch fetch. Reset
        # per epoch — a fresh engine gets a fresh chance. The lock makes
        # the latch-and-count a single step (racing workers must not
        # double-count the degradation event).
        self._ra_degraded = threading.Event()
        self._ra_degrade_mu = threading.Lock()
        # Gateway admission deferrals back off with seeded jitter (the
        # same reproducibility contract as the native retry ladder's
        # DDSTORE_FAULT_SEED); the lock serializes racing prefetch
        # workers over the shared PRNG.
        self._admission_rng = random.Random(
            int(os.environ.get("DDSTORE_FAULT_SEED", "0") or 0))
        self._admission_mu = threading.Lock()

    def _readahead_usable(self) -> bool:
        store = getattr(self.dataset, "store", None)
        data_var = getattr(self.dataset, "data_var", None)
        reason = None
        if store is None or data_var is None:
            reason = "dataset exposes no store/data_var"
        elif store.is_ragged(data_var):
            # The engine itself handles ragged windows, but a ragged
            # dataset's fetch() does sample packing the loader cannot
            # reproduce from raw rows — per-batch path keeps it exact.
            reason = "ragged data_var (dataset.fetch packs samples)"
        elif not hasattr(self.sampler, "__len__"):
            reason = "sampler is not sized"
        elif iter(self.sampler) is self.sampler:
            reason = ("sampler is a one-shot iterator (readahead "
                      "replays the epoch; two iterations must yield "
                      "identical indices)")
        if reason is not None:
            self.readahead_fallback_reason = reason
            return False
        return True

    def _collective_usable(self, dataset, mesh, axis, spec,
                           transform) -> bool:
        reason = None
        store = getattr(dataset, "store", None)
        if mesh is None or jax is None:
            reason = "no mesh/ICI available"
        elif spec != PartitionSpec(axis):
            reason = f"custom spec {spec} (exchange delivers P({axis!r}))"
        elif transform is not None:
            reason = "host-side transform set"
        elif store is None or getattr(dataset, "data_var", None) is None:
            reason = "dataset exposes no store/data_var"
        elif axis not in mesh.shape:
            reason = f"mesh has no {axis!r} axis"
        elif jax.process_count() > 1:
            # Single-controller only for now: multi-process staging
            # (per-host local slices) is not yet wired — see
            # device_fetch.exchange_staged.
            reason = "multi-process mesh (single-controller only)"
        else:
            d = int(mesh.shape[axis])
            if self.batch_size % d:
                reason = (f"batch {self.batch_size} not divisible by "
                          f"{d} shards")
            elif d % store.world:
                reason = (f"{d} shards not divisible by store world "
                          f"{store.world}")
        if reason is not None:
            self.collective_fallback_reason = reason
            return False
        return True

    def _record_host_dcn(self, idx: np.ndarray) -> None:
        """Host-path side of the bytes-moved ledger: rows owned by other
        ranks ride the DCN transport (plus labels when present)."""
        from .device_fetch import host_bytes_over_dcn

        store = getattr(self.dataset, "store", None)
        data_var = getattr(self.dataset, "data_var", None)
        if store is None or data_var is None:
            return
        dcn = host_bytes_over_dcn(store, data_var, idx)
        label_var = getattr(self.dataset, "label_var", None)
        if label_var is not None:
            dcn += host_bytes_over_dcn(store, label_var, idx)
        self.metrics.add_bytes(bytes_over_dcn=dcn)

    def _fetch_collective(self, idx: np.ndarray, seq: int = 0,
                          ra=None):
        """Host half of the collective staging, on a WORKER thread:
        plan + local reads + send-buffer fill. Returns a thunk the
        consumer thread runs to dispatch the exchange — collective
        program launches from concurrent threads interleave across the
        per-device executors and deadlock (see
        ``device_fetch.StagedFetch``), so the exchange must ride the
        same thread as the train step. Raises ValueError for geometries
        the planner rejects (caller falls back per batch). With a
        readahead engine (``ra``), the send buffers are filled from the
        staged window instead of per-owner store reads — window staging
        happens per host BEFORE the ICI exchange."""
        from .device_fetch import (exchange_staged, plan_device_fetch,
                                   stage_batch)

        store = self.dataset.store
        data_var = self.dataset.data_var
        d = int(self.mesh.shape[self.axis])
        with self.metrics.fetch.timed(), annotate("ddstore:device_fetch"):
            plan = plan_device_fetch(store.row_starts(data_var), idx, d)
            # Consume the window delivery only once the plan is viable —
            # a ValueError above falls back to the host path, which will
            # consume this seq itself.
            rows = ra.batch_rows(seq, idx=idx) if ra is not None else []
            staged = [stage_batch(store, data_var, idx, d, plan=plan,
                                  metrics=self.metrics,
                                  rows=rows[0] if rows else None)]
            label_var = getattr(self.dataset, "label_var", None)
            if label_var is not None:
                # Labels share the plan: same indices, same shard split
                # (ShardedDataset registers both with one nsplit).
                staged.append(stage_batch(
                    store, label_var, idx, d, plan=plan,
                    metrics=self.metrics,
                    rows=rows[1] if len(rows) > 1 else None))

        def finalize():
            with self.metrics.stage.timed(), \
                    annotate("ddstore:device_exchange"):
                out = [exchange_staged(sf, self.mesh, self.axis)
                       for sf in staged]
            return out[0] if len(out) == 1 else tuple(out)

        return _PendingExchange(finalize)

    # -- internals ---------------------------------------------------------

    def _index_batches(self) -> Iterator[np.ndarray]:
        it = iter(self.sampler)
        while True:
            idx = list(itertools.islice(it, self.batch_size))
            if not idx:
                return
            if len(idx) < self.batch_size and self.drop_last:
                return
            yield np.asarray(idx, dtype=np.int64)

    def _admission_backoff(self, e: BaseException) -> None:
        """Honor a serving-gateway retry-after hint: one bounded,
        seeded-jitter sleep before this batch falls to the per-batch
        path. Deferral is flow control, not failure — no ladder latch,
        no replan. Jitter is drawn from a loader-local PRNG seeded off
        ``DDSTORE_FAULT_SEED`` so chaos runs stay reproducible."""
        hint_ms = int(getattr(e, "retry_after_ms", 0) or 0)
        sleep_s = min(max(hint_ms, 1), 1000) / 1000.0
        with self._admission_mu:
            sleep_s *= 0.5 + self._admission_rng.random()
        time.sleep(sleep_s)

    def _degrade_readahead(self, e: BaseException) -> None:
        """Latch the per-epoch readahead degradation (idempotent across
        racing workers — first failure wins) and record the reason
        chain."""
        with self._ra_degrade_mu:
            if self._ra_degraded.is_set():
                return
            self._ra_degraded.set()
            self.readahead_fallback_reason = f"degraded mid-epoch: {e}"
            self.metrics.add_fault_event(readahead_degraded=1)
        if self.sched is not None:
            # Ladder engagement is a regime change: replan (outside the
            # latch lock — the replan takes the scheduler's own lock).
            self.sched.on_degradation("readahead")

    def _fetch(self, idx: np.ndarray, seq: int = 0, ra=None):
        if ra is not None and self._ra_degraded.is_set():
            ra = None
        if self._collective_ready:
            try:
                return self._fetch_collective(idx, seq, ra)
            except ValueError:
                # A geometry this batch can't satisfy (e.g. a short
                # trailing batch with drop_last=False): host path for
                # this batch only.
                pass
            except DDStoreError as e:
                # Degradation ladder, collective rung: a TRANSIENT
                # staging failure (native retries + the engine's window
                # retry already ran) drops THIS batch to the host path
                # below. Permanent owner death is fatal — surface it
                # (it names the dead owner; elastic.recover is next).
                if e.code == ERR_PEER_LOST:
                    if self.sched is not None:
                        self.sched.on_degradation("peer_lost")
                    raise
                if e.code == ERR_ADMISSION:
                    # Defer, not peer-lost: the serving gateway shed
                    # this read to protect another tenant's SLO.
                    # Nothing died and nothing is broken — honor the
                    # retry-after hint, retry THIS batch per-batch, and
                    # leave the epoch's readahead/collective machinery
                    # armed (no degradation latch, no replan trigger).
                    self.metrics.add_fault_event(
                        admission_deferred_batches=1)
                    self._admission_backoff(e)
                    ra = None  # this batch only; the latch stays clear
                else:
                    if self.collective_fallback_reason is None:
                        self.collective_fallback_reason = \
                            f"degraded mid-epoch: {e}"
                    self.metrics.add_fault_event(
                        collective_batch_fallbacks=1)
                    if self.sched is not None:
                        self.sched.on_degradation("collective")
                    if ra is not None:
                        # The engine raised before any window delivery
                        # for this seq (batch_rows fails before marking
                        # delivered), so the host path must not consult
                        # it either — it would re-raise the same error.
                        self._degrade_readahead(e)
                        ra = None
        with self.metrics.fetch.timed(), annotate("ddstore:fetch"):
            batch = None
            if ra is not None:
                try:
                    # Window delivery: an in-RAM gather from the staged
                    # window (the engine recorded the transport-side
                    # bytes once per window, dedup included — no
                    # per-batch DCN accounting here).
                    batch = ra.get_batch(seq, idx=idx)
                except DDStoreError as e:
                    # Ladder, readahead rung: transient window failure
                    # that survived the engine's own per-batch retry —
                    # the rest of the epoch runs per-batch. Fatal codes
                    # surface.
                    if e.code == ERR_PEER_LOST:
                        if self.sched is not None:
                            self.sched.on_degradation("peer_lost")
                        raise
                    if e.code == ERR_ADMISSION:
                        # Defer, not peer-lost: back off per the
                        # gateway's hint and serve this one batch from
                        # the host path — the readahead engine stays
                        # armed for the rest of the epoch.
                        self.metrics.add_fault_event(
                            admission_deferred_batches=1)
                        self._admission_backoff(e)
                    else:
                        self._degrade_readahead(e)
            if batch is None:
                batch = (self.dataset(idx) if callable(self.dataset)
                         else self.dataset.fetch(idx))
                self._record_host_dcn(idx)
        if self.transform is not None:
            if self._transform_lock is not None:
                with self._transform_lock:
                    batch = self.transform(batch)
            else:
                batch = self.transform(batch)
        if self._sharding is None:
            return batch
        with self.metrics.stage.timed(), annotate("ddstore:stage"):
            put = lambda x: jax.make_array_from_process_local_data(
                self._sharding, np.ascontiguousarray(x))
            # tree_map preserves container types (tuples, NamedTuple
            # batches like GraphBatch, dicts) while staging every leaf.
            return jax.tree_util.tree_map(put, batch)

    def _make_readahead(self):
        """Per-epoch readahead engine over a SECOND, independent replay
        of the sampler (the engine verifies both replays agree batch by
        batch). None when readahead is off or fell back."""
        if not self._readahead_ready:
            return None
        from .readahead import EpochReadahead

        # Check the ring OUT for this iterator (restored at teardown):
        # two overlapping iterators of one loader must never share
        # staging buffers — the second allocates its own.
        ring, self._ra_ring = self._ra_ring, None
        # The DEPTH knob is the scheduler's: the user's readahead_windows
        # is the requested ceiling (and the ring budget); the planner
        # may run shallower when the core budget says deeper windows
        # cannot fetch concurrently anyway. DDSTORE_READAHEAD_DEPTH
        # pins it.
        depth = self.readahead_windows
        if self.sched is not None:
            depth = self.sched.planned_depth(self.readahead_windows)
        return EpochReadahead(
            self.dataset.store, self.dataset.data_var,
            self._index_batches(),
            label_var=getattr(self.dataset, "label_var", None),
            window_batches=self.readahead_window_batches,
            depth=depth, metrics=self.metrics,
            ring=ring, sched=self.sched)

    def __iter__(self):
        # Ordered worker pool: index batches are submitted in order and
        # futures consumed in submission order, so parallel fetch+stage
        # never reorders the epoch's batch stream. Early exit (break) is
        # safe: shutdown waits for in-flight fetches, then the readahead
        # engine's close() releases every in-flight async read, so a
        # subsequent store teardown can't race either.
        self.metrics.epoch_start()
        self._ra_degraded.clear()  # fresh epoch, fresh engine, fresh chance
        # Liveness sweep at the epoch boundary: newly suspected peers
        # fire the store's peer listeners (the scheduler replans its
        # routes/lanes off the dead peer BEFORE this epoch's plan is
        # applied below, instead of at the first deadline burn).
        check_health = getattr(getattr(self.dataset, "store", None),
                               "check_health", None)
        if check_health is not None:
            try:
                check_health()
            except Exception:
                pass  # liveness polling must never fail an epoch
        if self.sched is not None:
            # Epoch-boundary replan BEFORE the engine is built: the
            # planned depth/width govern this epoch's ring and
            # admission, and the route/lane pins land before the first
            # fetch.
            self.sched.on_epoch()
        ex = ThreadPoolExecutor(max_workers=self.workers,
                                thread_name_prefix="ddstore-loader")
        futs = deque()
        ra = self._make_readahead()
        try:
            it = enumerate(self._index_batches())
            for seq, idx in itertools.islice(it, self.prefetch):
                futs.append(ex.submit(self._fetch, idx, seq, ra))
            while futs:
                t0 = time.perf_counter()
                item = futs.popleft().result()
                if isinstance(item, _PendingExchange):
                    # Collective dispatch happens HERE, on the consumer
                    # thread — the only thread launching collective
                    # programs (the train step is its other client).
                    item = item.finalize()
                self.metrics.wait.record(time.perf_counter() - t0)
                nxt = next(it, None)
                if nxt is not None:
                    futs.append(ex.submit(self._fetch, nxt[1], nxt[0],
                                          ra))
                yield item
        finally:
            for f in futs:
                f.cancel()
            if ra is not None:
                # Wake any worker blocked on a window BEFORE joining the
                # pool: shutdown(wait=True) on a worker waiting for a
                # ring slot that will never free would deadlock.
                ra.close()
                self._ra_ring = ra.ring  # reuse next epoch
            ex.shutdown(wait=True)
            # SLO evaluation at the epoch boundary ("per epoch
            # window"), BEFORE the metrics freeze so this epoch's
            # summary()["slo"] carries its own verdict. A breach has
            # already dumped the flight recorder natively; here it
            # closes the observe->react loop by replanning the
            # breached tenant's routes/lanes/shares.
            self._check_slos()
            self._check_admission_pressure()
            self.metrics.epoch_end()

    def _check_slos(self) -> None:
        """Evaluate the store's tenant latency SLOs over the epoch
        window that just ended and fire one scheduler replan per
        breached tenant (the PR 6 degradation path). Inert — one cheap
        native call returning nothing — while no SLOs are configured;
        never fails the epoch."""
        store = getattr(self.dataset, "store", None)
        if store is None or not hasattr(store, "evaluate_slos"):
            return
        try:
            breaches = store.evaluate_slos()
        except Exception:
            return  # observability must never fail an epoch
        if self.sched is not None:
            for b in breaches:
                self.sched.on_degradation(f"slo:{b['tenant']}")

    def _check_admission_pressure(self) -> None:
        """Feed the epoch's gateway deferred/rejected deltas to the
        planner as defer pressure (one replan, not one per deferral —
        admission events inside the epoch only sleep and retry). Inert
        with the gateway off; never fails the epoch."""
        if self.sched is None:
            return
        try:
            gw = self.metrics.gateway_summary()
            deferred = int(gw.get("deferred", 0))
            rejected = int(gw.get("rejected", 0))
        except Exception:
            return  # observability must never fail an epoch
        if deferred or rejected:
            self.sched.on_admission_pressure(deferred, rejected)

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else \
            (n + self.batch_size - 1) // self.batch_size
