"""Prefetching device loader: store → host batch → sharded device arrays.

The reference's hot loop fetches every sample synchronously inside
``DataLoader.__next__`` with zero prefetch and zero batching
(num_workers=0, two blocking one-sided reads per sample — SURVEY §3.2/§3.3,
called out in §7 as the anti-pattern to fix). Here the loader:

* draws whole batches of indices from the sampler,
* fetches them with one coalesced, multi-peer ``get_batch``,
* stages them to devices with a sharded transfer
  (``jax.make_array_from_process_local_data`` — each DP shard receives its
  slice directly),
* runs fetch+stage on a background thread, `prefetch` batches deep, so
  host I/O overlaps device compute (double buffering by default),
* records the BASELINE.json metrics (device-wait, fetch and stage
  latencies, input-pipeline efficiency).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

from ..utils.metrics import PipelineMetrics
from ..utils.profile import annotate

try:  # the loader is importable without jax for host-only use
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
except Exception:  # pragma: no cover
    jax = None


class DeviceLoader:
    """Iterate device-ready (sharded) batches from a store-backed dataset.

    Parameters
    ----------
    dataset: object with ``fetch(indices) -> array | tuple`` and ``__len__``
        (e.g. :class:`ShardedDataset`), or a bare callable.
    sampler: iterable of global indices for THIS rank's epoch (e.g.
        :class:`DistributedSampler`).
    batch_size: per-process batch size. With a mesh, must divide by the
        number of addressable devices on the batch axis.
    mesh / spec: optional device staging target. If given, batches are
        device arrays sharded over ``spec`` (default: leading dim over
        axis "dp"); if None, numpy batches are yielded (host-only mode).
    prefetch: how many batches are kept in flight ahead of the consumer.
    workers: fetch+stage worker threads. One worker pipelines host IO
        against device compute; more overlap multiple batches' host paths
        with each other — needed to keep small/fast models fed (ctypes
        releases the GIL during store reads, and staging is mostly
        off-GIL transfer work, so threads genuinely parallelize).
        Default (None): 2 for store-backed datasets (whose ``fetch`` is
        thread-safe by construction), 1 for a bare callable unless it
        declares ``thread_safe = True``. Passing an explicit ``workers``
        value is the caller's declaration that ``dataset.fetch`` is safe
        at that concurrency.
    drop_last: drop the trailing partial batch (keeps shapes static for
        jit — recompile-free epochs).
    transform: optional host-side function applied to each fetched batch.
        With workers > 1 the transform is serialized under a lock (fetch
        and staging still run in parallel), so stateful transforms — e.g.
        one sharing a np.random.Generator — are race-free by default.
        Note the lock guarantees exclusion, not order: workers reach the
        transform in fetch-completion order, so a shared RNG is consumed
        in a run-dependent sequence — for bit-deterministic augmentation
        pass workers=1. Mark the transform ``thread_safe = True`` (or
        pass ``transform_thread_safe=True``) to let it run concurrently.
    """

    def __init__(self, dataset, sampler: Iterable[int], batch_size: int,
                 mesh: Optional["Mesh"] = None, axis: str = "dp",
                 prefetch: int = 4, drop_last: bool = True,
                 transform: Optional[Callable] = None,
                 spec: Optional["PartitionSpec"] = None,
                 workers: Optional[int] = None,
                 transform_thread_safe: bool = False):
        self.dataset = dataset
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.mesh = mesh
        self.axis = axis
        self.prefetch = max(1, int(prefetch))
        if workers is None:
            # Store-backed datasets expose fetch() whose reads go through
            # the native core (thread-safe by construction), so objects
            # default to 2 workers — but an explicit thread_safe attribute
            # on the dataset wins in either direction; bare callables
            # default to a single worker unless they opt in.
            fetch_safe = getattr(dataset, "thread_safe",
                                 not callable(dataset))
            workers = 2 if fetch_safe else 1
        self.workers = max(1, int(workers))
        self.drop_last = drop_last
        self.transform = transform
        self._transform_lock = None
        if (transform is not None and self.workers > 1
                and not transform_thread_safe
                and not getattr(transform, "thread_safe", False)):
            self._transform_lock = threading.Lock()
        self.metrics = PipelineMetrics()
        # Store-backed datasets expose their DDStore; wiring its planner
        # counters in gives every epoch summary the scatter-read plan view
        # (runs/peer, coalesce ratio, dedup hits) alongside the latencies.
        store = getattr(dataset, "store", None)
        if store is not None and hasattr(store, "plan_stats"):
            self.metrics.set_plan_source(store.plan_stats)
        if mesh is not None and jax is None:  # pragma: no cover
            raise RuntimeError("jax unavailable but mesh given")
        # `spec` overrides the default leading-dim-over-`axis` layout, e.g.
        # P("dp", "sp") to stage sequence-sharded token windows directly in
        # the layout the train step's in_shardings demand.
        if spec is None:
            spec = PartitionSpec(axis)
        self._sharding = (NamedSharding(mesh, spec)
                         if mesh is not None else None)

    # -- internals ---------------------------------------------------------

    def _index_batches(self) -> Iterator[np.ndarray]:
        it = iter(self.sampler)
        while True:
            idx = list(itertools.islice(it, self.batch_size))
            if not idx:
                return
            if len(idx) < self.batch_size and self.drop_last:
                return
            yield np.asarray(idx, dtype=np.int64)

    def _fetch(self, idx: np.ndarray):
        with self.metrics.fetch.timed(), annotate("ddstore:fetch"):
            batch = (self.dataset(idx) if callable(self.dataset)
                     else self.dataset.fetch(idx))
        if self.transform is not None:
            if self._transform_lock is not None:
                with self._transform_lock:
                    batch = self.transform(batch)
            else:
                batch = self.transform(batch)
        if self._sharding is None:
            return batch
        with self.metrics.stage.timed(), annotate("ddstore:stage"):
            put = lambda x: jax.make_array_from_process_local_data(
                self._sharding, np.ascontiguousarray(x))
            # tree_map preserves container types (tuples, NamedTuple
            # batches like GraphBatch, dicts) while staging every leaf.
            return jax.tree_util.tree_map(put, batch)

    def __iter__(self):
        # Ordered worker pool: index batches are submitted in order and
        # futures consumed in submission order, so parallel fetch+stage
        # never reorders the epoch's batch stream. Early exit (break) is
        # safe: shutdown waits for in-flight fetches, so a subsequent
        # store teardown can't race them.
        self.metrics.epoch_start()
        ex = ThreadPoolExecutor(max_workers=self.workers,
                                thread_name_prefix="ddstore-loader")
        futs = deque()
        try:
            it = self._index_batches()
            for idx in itertools.islice(it, self.prefetch):
                futs.append(ex.submit(self._fetch, idx))
            while futs:
                t0 = time.perf_counter()
                item = futs.popleft().result()
                self.metrics.wait.record(time.perf_counter() - t0)
                nxt = next(it, None)
                if nxt is not None:
                    futs.append(ex.submit(self._fetch, nxt))
                yield item
        finally:
            for f in futs:
                f.cancel()
            ex.shutdown(wait=True)
            self.metrics.epoch_end()

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else \
            (n + self.batch_size - 1) // self.batch_size
