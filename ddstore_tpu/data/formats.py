"""Real dataset file formats: MNIST idx and QM9-style xyz.

The reference trains on actual MNIST via torchvision's downloader
(/root/reference/examples/vae/vae-ddp.py:202-216); this environment has no
network, so the loaders here read the standard on-disk formats directly
(drop the canonical files in a directory and point the examples at it) and
each has a writer so tests and offline runs can produce bit-faithful
fixtures.

* MNIST idx (yann.lecun.com layout): big-endian magic 0x0801 (labels,
  1-D) / 0x0803 (images, 3-D), optionally gzipped.
* QM9 xyz (quantum-chemistry molecules — the atomistic workload DDStore
  was built for, README.md:200-212): per-molecule text blocks
  ``natoms\\n<comment with float properties>\\n<symbol x y z ...>*``.
  Molecules become :class:`GraphSample`s with one-hot element node
  features, radius-graph edges, and a chosen comment-line property as the
  regression target.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .graphs import GraphSample

_IDX_MAGIC_LABELS = 0x0801
_IDX_MAGIC_IMAGES = 0x0803

# QM9's element set; unknown symbols raise (a corrupt file must not train).
QM9_ELEMENTS = ("H", "C", "N", "O", "F")


def _open(path: str, mode: str):
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    return open(path, mode)


def read_idx(path: str) -> np.ndarray:
    """Read an idx-format array (images uint8 (N, R, C); labels (N,))."""
    with _open(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        # Layout: two zero bytes, dtype byte (0x08 = ubyte), ndim byte.
        if magic >> 16 != 0 or ((magic >> 8) & 0xFF) != 0x08:
            raise ValueError(f"{path}: bad idx magic {magic:#x}")
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = f.read(int(np.prod(dims)))
    arr = np.frombuffer(data, dtype=np.uint8)
    if arr.size != int(np.prod(dims)):
        raise ValueError(f"{path}: truncated idx payload")
    return arr.reshape(dims)


def write_idx(path: str, arr: np.ndarray) -> None:
    """Write uint8 idx (inverse of read_idx; .gz suffix gzips)."""
    arr = np.ascontiguousarray(arr, dtype=np.uint8)
    magic = 0x0800 | arr.ndim
    with _open(path, "wb") as f:
        f.write(struct.pack(">I", magic))
        f.write(struct.pack(">" + "I" * arr.ndim, *arr.shape))
        f.write(arr.tobytes())


_MNIST_FILES = {
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def find_mnist(data_dir: str, split: str = "train"
               ) -> Optional[Tuple[str, str]]:
    """Locate the canonical MNIST pair in ``data_dir`` (plain or .gz)."""
    img_name, lbl_name = _MNIST_FILES[split]
    for suffix in ("", ".gz"):
        img = os.path.join(data_dir, img_name + suffix)
        lbl = os.path.join(data_dir, lbl_name + suffix)
        if os.path.exists(img) and os.path.exists(lbl):
            return img, lbl
    return None


def load_mnist(data_dir: str, split: str = "train", normalize: bool = True
               ) -> Tuple[np.ndarray, np.ndarray]:
    """(images (N, 784), labels (N,) int32) from the standard idx files.

    ``normalize=True`` gives float32 in [0,1] (the normalization
    torchvision's ToTensor applies in the reference's pipeline,
    vae-ddp.py:204-209). ``normalize=False`` keeps the raw uint8 pixels
    — the TPU-first hot path: the store holds and the loader stages 4x
    fewer bytes, and the model dequantizes on device with identical
    numerics (uint8/255 is exactly what ToTensor computes)."""
    found = find_mnist(data_dir, split)
    if found is None:
        raise FileNotFoundError(
            f"no MNIST idx files for split {split!r} under {data_dir}")
    img_path, lbl_path = found
    images = read_idx(img_path)
    labels = read_idx(lbl_path)
    if images.ndim != 3 or labels.ndim != 1 or len(images) != len(labels):
        raise ValueError(f"MNIST shape mismatch: {images.shape} vs "
                         f"{labels.shape}")
    flat = images.reshape(len(images), -1)
    if normalize:
        flat = flat.astype(np.float32) / 255.0
    return flat, labels.astype(np.int32)


def synthetic_mnist(n: int, seed: int = 0
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped data for offline environments: blurry
    class-conditioned blobs as uint8 pixels (the real idx files' dtype),
    same on every rank (like a shared download). One generator shared by
    the example and the bench so both always train on identical data;
    stored raw, dequantized on device (see models/vae._dequantize)."""
    g = np.random.default_rng(seed)
    labels = g.integers(0, 10, size=n).astype(np.int32)
    centers = g.random((10, 784), dtype=np.float32)
    x = centers[labels] * 0.8 + 0.2 * g.random((n, 784), dtype=np.float32)
    return np.round(x * 255.0).astype(np.uint8), labels


# ---------------------------------------------------------------------------
# QM9 xyz
# ---------------------------------------------------------------------------


def _parse_float(tok: str) -> float:
    # QM9 files occasionally use Mathematica-style "1.23*^-5" exponents.
    return float(tok.replace("*^", "e"))


def _block_start(line: str) -> Optional[int]:
    """natoms header = a single bare-integer token; anything else (QM9's
    frequency/SMILES/InChI trailer lines, blank padding) is not one."""
    toks = line.split()
    if len(toks) != 1:
        return None
    try:
        return int(toks[0])
    except ValueError:
        return None


def read_xyz(path: str) -> List[Tuple[List[str], np.ndarray, np.ndarray]]:
    """Parse one xyz file that may hold many molecule blocks. Returns
    [(symbols, coords (n,3) float32, props (P,) float32), ...]; props are
    the float tokens of the comment line (empty if none parse).

    Handles the real QM9 layout (dsgdb9nsd_*.xyz): per-atom Mulliken
    charge columns are ignored, and the three trailer lines after the atom
    block (harmonic frequencies, SMILES, InChI) are skipped — a new block
    only starts at a bare-integer natoms line."""
    mols = []
    with _open(path, "rt") as f:
        lines = [ln.rstrip("\n") for ln in f]
    i = 0
    while i < len(lines):
        n = _block_start(lines[i])
        if n is None:
            if mols:  # trailer junk between/after blocks
                i += 1
                continue
            if not lines[i].strip():
                i += 1
                continue
            raise ValueError(
                f"{path}: expected natoms header at line {i + 1}, got "
                f"{lines[i]!r}")
        comment = lines[i + 1] if i + 1 < len(lines) else ""
        props = []
        for tok in comment.replace("\t", " ").split():
            try:
                props.append(_parse_float(tok))
            except ValueError:
                continue
        symbols, coords = [], []
        for ln in lines[i + 2: i + 2 + n]:
            parts = ln.replace("\t", " ").split()
            symbols.append(parts[0])
            coords.append([_parse_float(p) for p in parts[1:4]])
        if len(symbols) != n:
            raise ValueError(f"{path}: truncated molecule block at line {i}")
        mols.append((symbols, np.asarray(coords, np.float32),
                     np.asarray(props, np.float32)))
        i += 2 + n
    return mols


def write_xyz(path: str, mols: Sequence[Tuple[Sequence[str], np.ndarray,
                                              Sequence[float]]]) -> None:
    """Inverse of read_xyz (fixtures / offline preprocessing)."""
    with _open(path, "wt") as f:
        for symbols, coords, props in mols:
            f.write(f"{len(symbols)}\n")
            f.write("\t".join(f"{p:.8f}" for p in props) + "\n")
            for s, xyz in zip(symbols, np.asarray(coords)):
                f.write(f"{s}\t" + "\t".join(f"{c:.8f}" for c in xyz) + "\n")


def molecule_to_graph(symbols: Sequence[str], coords: np.ndarray,
                      props: np.ndarray, *, target_index: int = 0,
                      cutoff: float = 1.7) -> GraphSample:
    """Molecule → GraphSample: one-hot element (+ normalized coords) node
    features, bidirectional radius-graph edges with [distance] attributes,
    target = props[target_index]. ``cutoff`` (Å) ~ covalent bonds at 1.7."""
    n = len(symbols)
    fn = len(QM9_ELEMENTS) + 3
    nodes = np.zeros((n, fn), np.float32)
    for i, s in enumerate(symbols):
        try:
            nodes[i, QM9_ELEMENTS.index(s)] = 1.0
        except ValueError:
            raise ValueError(f"unknown element {s!r} (expected one of "
                             f"{QM9_ELEMENTS})") from None
    center = coords - coords.mean(axis=0, keepdims=True)
    nodes[:, len(QM9_ELEMENTS):] = center

    src, dst, dists = [], [], []
    for i in range(n):
        d = np.linalg.norm(coords - coords[i], axis=1)
        for j in np.nonzero((d > 0) & (d <= cutoff))[0]:
            src.append(i)
            dst.append(int(j))
            dists.append(d[j])
    edge_index = np.stack([np.asarray(src, np.int64),
                           np.asarray(dst, np.int64)], axis=1) \
        if src else np.zeros((0, 2), np.int64)
    edge_attr = np.asarray(dists, np.float32)[:, None] \
        if dists else np.zeros((0, 1), np.float32)
    if target_index >= len(props):
        raise ValueError(f"target_index {target_index} out of range for "
                         f"{len(props)} properties")
    y = np.asarray([props[target_index]], np.float32)
    return GraphSample(nodes, edge_index, edge_attr, y)


def load_qm9_dir(data_dir: str, *, target_index: int = 0,
                 cutoff: float = 1.7, limit: Optional[int] = None
                 ) -> List[GraphSample]:
    """Read every .xyz/.xyz.gz under ``data_dir`` (sorted for rank
    determinism) into GraphSamples."""
    paths = sorted(
        os.path.join(data_dir, f) for f in os.listdir(data_dir)
        if f.endswith((".xyz", ".xyz.gz")))
    if not paths:
        raise FileNotFoundError(f"no .xyz files under {data_dir}")
    out: List[GraphSample] = []
    for p in paths:
        for symbols, coords, props in read_xyz(p):
            out.append(molecule_to_graph(symbols, coords, props,
                                         target_index=target_index,
                                         cutoff=cutoff))
            if limit is not None and len(out) >= limit:
                return out
    return out
