"""Epoch-window readahead: plan whole-epoch reads, fetch them as bulk
stripes, hide the transport behind compute.

The training hot path is "any rank reads any row" under a
``DistributedSampler`` permutation — but the permutation for the WHOLE
epoch is known before the first batch is fetched, and neither the
reference nor the per-batch scatter engine exploits that: even after
coalescing, a per-batch scatter read tops out well below the bulk-stripe
path (r05: cma_batch 5.04 vs cma_stripe 9.56 GB/s), because a single
batch's rows are sparse in every peer's shard, so runs stay short. This
module closes that gap by planning over a *window* of W batches at once:

* :func:`plan_window` merges the window's batches into one sorted,
  deduplicated row list — W× denser in each peer's shard, so the native
  scatter planner coalesces it into a few long, offset-sorted,
  stripe-shaped runs per peer (and every run is *direct*: sorted input
  means output order == shard order, no scratch staging);
* :class:`EpochReadahead` keeps a ring of ``depth`` preallocated window
  staging buffers filled through the native async engine
  (``store.get_batch_async`` → ``dds_get_batch_async`` on the store's
  background pool) — window N+1 is always in flight over the transport
  while window N is consumed, hiding DCN latency behind compute;
* per-batch delivery is a cheap in-RAM gather from the staged window
  (exact request order; duplicate rows are fetched once per window and
  replicated by the gather; ragged samples ride the existing two-round
  ragged fetch per window and are re-split per batch).

``DeviceLoader(readahead_windows=K)`` wires this under both the host
path and the device-collective path (window staging happens before the
ICI exchange); the engine is also usable standalone over a raw store —
that is what the bench's readahead A/B phase drives.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..binding import (DEFAULT_OP_DEADLINE_S, ERR_PEER_LOST,
                       ERR_TRANSPORT, DDStoreError, trace_emit,
                       trace_enabled, trace_flight, trace_new_span)

__all__ = ["WindowPlan", "plan_window", "plan_epoch_windows",
           "EpochReadahead"]


class WindowPlan:
    """Pure-numpy plan for one readahead window of consecutive batches.

    ``rows`` is the window's sorted, deduplicated row set — the shape the
    native scatter planner coalesces best (sorted input also makes every
    run *direct*, reading straight into the staging buffer). ``gather``
    maps each requested position (batches concatenated in epoch order)
    to its row's slot in ``rows``; ``bounds[b]:bounds[b+1]`` is batch
    ``b``'s span, so per-batch delivery is ``staged[gather[lo:hi]]`` —
    duplicates (within AND across the window's batches) are fetched once
    and replicated by the gather.
    """

    __slots__ = ("rows", "gather", "bounds", "batches", "owner",
                 "run_starts", "runs_per_peer")

    def __init__(self, rows: np.ndarray, gather: np.ndarray,
                 bounds: np.ndarray, batches: List[np.ndarray],
                 owner: np.ndarray, run_starts: np.ndarray,
                 runs_per_peer: np.ndarray):
        self.rows = rows
        self.gather = gather
        self.bounds = bounds
        self.batches = batches
        self.owner = owner          # owner rank of each unique row
        self.run_starts = run_starts  # first index of each coalesced run
        self.runs_per_peer = runs_per_peer  # runs landing on each rank

    @property
    def n_batches(self) -> int:
        return len(self.batches)

    @property
    def n_requested(self) -> int:
        """Rows requested by the window's batches (duplicates counted)."""
        return int(self.bounds[-1])

    @property
    def dup_rows(self) -> int:
        """Duplicate requests served by the in-RAM gather instead of a
        second fetch (dedup ACROSS the whole window, not per batch)."""
        return self.n_requested - int(self.rows.size)

    @property
    def n_runs(self) -> int:
        """Contiguous stripe-shaped runs the window fetch decomposes
        into (matches the native planner: sorted dedup'd rows coalesce
        identically on both sides of the boundary)."""
        return int(self.run_starts.size)

    def batch_slice(self, b: int) -> np.ndarray:
        """Gather indices (into ``rows``/the staged buffer) for batch
        ``b``, in that batch's exact request order."""
        return self.gather[int(self.bounds[b]):int(self.bounds[b + 1])]


def plan_window(row_starts, batches: Sequence) -> WindowPlan:
    """Plan one window: merge ``batches`` (index arrays, epoch order)
    into the sorted-unique fetch list plus the per-batch gather map, and
    derive the run decomposition against the owner table ``row_starts``
    (:meth:`DDStore.row_starts`)."""
    bl = [np.ascontiguousarray(b, dtype=np.int64).reshape(-1)
          for b in batches]
    if not bl or not sum(b.size for b in bl):
        raise ValueError("plan_window: empty window")
    cat = np.concatenate(bl)
    starts = np.ascontiguousarray(row_starts, dtype=np.int64)
    if cat.min() < 0 or cat.max() >= starts[-1]:
        raise IndexError(f"plan_window: index out of range "
                         f"[0, {int(starts[-1])})")
    rows, gather = np.unique(cat, return_inverse=True)
    bounds = np.concatenate(
        ([0], np.cumsum([b.size for b in bl]))).astype(np.int64)
    owner = (np.searchsorted(starts, rows, side="right") - 1).astype(
        np.int64)
    # A run breaks where rows stop being adjacent or the owner changes —
    # the same decomposition the native scatter planner arrives at, so
    # runs_per_peer here IS the per-window transport fan-out.
    brk = np.r_[True, (np.diff(rows) != 1) | (owner[1:] != owner[:-1])]
    run_starts = np.flatnonzero(brk).astype(np.int64)
    runs_per_peer = np.bincount(owner[run_starts],
                                minlength=len(starts) - 1)
    return WindowPlan(rows, gather.astype(np.int64), bounds, bl, owner,
                      run_starts, runs_per_peer)


def plan_epoch_windows(row_starts, batches: Iterable,
                       window_batches: int) -> List[WindowPlan]:
    """Slice an epoch's batch stream into windows of ``window_batches``
    and plan each (the eager helper — the engine plans lazily)."""
    if window_batches <= 0:
        raise ValueError(f"window_batches must be positive, got "
                         f"{window_batches}")
    it = iter(batches)
    plans = []
    while True:
        chunk = list(itertools.islice(it, window_batches))
        if not chunk:
            return plans
        plans.append(plan_window(row_starts, chunk))


#: Per-process engine id counter: each engine's cache-prefetch window
#: ids live in their own 2^32 range, so two engines (or two epochs)
#: sharing one store can never alias each other's hot-cache entries.
_ENGINE_IDS = itertools.count(1)


class _Window:
    __slots__ = ("plan", "slot", "handles", "bufs", "ragged", "futures",
                 "delivered", "ready", "ready_mu", "t_issue", "span",
                 "wnum", "warmed")

    def __init__(self, plan: WindowPlan, slot: int):
        self.plan = plan
        self.slot = slot
        self.span = 0   # ddtrace span id of this window (0 = untraced)
        self.wnum = 0   # global window number
        self.warmed = False  # hot-cache prefetch issued at plan time
        self.handles: Dict[str, object] = {}   # var -> AsyncBatchRead
        self.bufs: Dict[str, np.ndarray] = {}  # var -> staged view
        self.futures: Dict[str, object] = {}   # var -> Future (ragged)
        self.ragged: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]] \
            = {}                               # var -> (values, lens, offs)
        self.delivered = 0
        self.ready = threading.Event()
        self.ready_mu = threading.Lock()
        self.t_issue = 0.0


class EpochReadahead:
    """Background window-fetch engine over a store variable (plus an
    optional co-variable sharing the same indices, e.g. labels).

    ``batches`` is the epoch's batch stream for THIS rank (index
    arrays, consumed lazily W at a time). The engine keeps up to
    ``depth`` windows staged or in flight: each window's sorted-unique
    row list is issued as ONE native async ``get_batch`` per variable
    into a preallocated ring buffer, and consumers call
    :meth:`get_batch`/:meth:`batch_rows` with the global batch number —
    strictly increasing consumption (the loader's contract) recycles
    ring slots and triggers the next window's issue.

    Ragged variables ride the existing ragged fetch (two batched rounds
    per window on a background thread) and are re-split per batch —
    same bulk-window shape on the wire, same per-batch delivery
    contract as :meth:`DDStore.get_ragged_batch`.

    Teardown (:meth:`close`, also the loader's mid-epoch cancellation
    path) blocks until every in-flight native read has completed and
    releases every ticket — ``store.async_pending()`` is 0 afterwards.
    """

    def __init__(self, store, data_var: str, batches: Iterable,
                 label_var: Optional[str] = None, window_batches: int = 8,
                 depth: int = 2, metrics=None,
                 max_window_rows: Optional[int] = None,
                 ring: Optional[Dict[str, List[np.ndarray]]] = None,
                 sched=None):
        if window_batches <= 0:
            raise ValueError("window_batches must be positive")
        if depth <= 0:
            raise ValueError("depth must be positive")
        self.store = store
        self.window_batches = int(window_batches)
        self.depth = int(depth)
        self.metrics = metrics
        # Cost-model scheduler (sched/planner.Scheduler): each window's
        # fetch leg feeds its host-side measurement substrate. The
        # epoch's first window is marked `cold` — it pays ring
        # first-touch and lane dials, the host-side analogue of the
        # native tuners' dial-tainted windows.
        self.sched = sched
        self._windows_fed = 0
        self._batch_iter: Iterator = iter(batches)
        self._vars = [data_var] + ([label_var] if label_var else [])
        self._ragged = {v: store.is_ragged(v) for v in self._vars}
        anchor = f"{data_var}/index" if self._ragged[data_var] else data_var
        self._row_starts = store.row_starts(anchor)
        self._row_bytes = {
            v: store.row_nbytes(f"{v}/index" if self._ragged[v] else v)
            for v in self._vars}
        # Fixed-width variables sharing the anchor's owner table ride
        # the O(runs) native path (read_runs_async): the planner's run
        # lists execute verbatim, no native re-plan over 10^5+ rows. A
        # co-variable with a different row partition (not the
        # ShardedDataset case) falls back to get_batch_async.
        self._use_runs = {
            v: (not self._ragged[v]
                and np.array_equal(store.row_starts(v),
                                   self._row_starts))
            for v in self._vars}

        # Preallocated staging ring: depth buffers per fixed-width var,
        # each sized for the worst case (no duplicates in the window).
        # Memory cost = depth × Σ_var max_window_rows × row_bytes — the
        # knob README documents. Ragged windows allocate per fetch (the
        # element total is data-dependent).
        self._max_rows = int(max_window_rows) if max_window_rows else None
        self._ring: Dict[str, List[np.ndarray]] = {}
        # `ring`: staging buffers handed over from a previous engine
        # (the loader reuses them epoch to epoch). Worth real time on
        # first-touch-expensive kernels: a fresh 2x64 MB ring faults in
        # page by page DURING the first windows' fetch writes otherwise.
        self._provided_ring = ring
        self._exec = None
        if any(self._ragged.values()):
            from concurrent.futures import ThreadPoolExecutor
            self._exec = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="ddstore-readahead")

        # Tiered-storage warming: when the store's hot-row cache is
        # armed (DDSTORE_TIER_CACHE_BYTES > 0), the issuer plans up to
        # `_prefetch` windows AHEAD of issue and hands each plan's row
        # list to store.cache_prefetch — a free lookahead (the plan
        # exists before the window is issued), so by the time window w
        # is issued its cold rows are already staged in RAM and the
        # window read is an in-RAM gather. Eviction is keyed on window
        # consumption (_mark_delivered). Window ids are scoped per
        # engine so epochs/engines never alias entries.
        self._warm = False
        self._prefetch = 0
        self._wid_base = next(_ENGINE_IDS) << 32
        self._warmed: set = set()
        if hasattr(store, "tiering_stats") and \
                hasattr(store, "cache_prefetch"):
            try:
                self._warm = int(store.tiering_stats().get(
                    "cache_max_bytes", 0)) > 0
            except Exception:  # noqa: BLE001 — advisory capability probe
                self._warm = False
        if self._warm:
            self._prefetch = self._default_prefetch()
            self._warm = self._prefetch > 0

        self._planned: "deque" = deque()  # (wnum, plan) awaiting issue
        self._plan_next = 0               # next window number to plan
        self._iter_done = False           # batch iterator exhausted

        self._mu = threading.Lock()
        self._cond = threading.Condition(self._mu)
        # Serializes degraded-window refetches: each one sets the
        # store's shared retry-deadline override, and two windows
        # failing concurrently (depth >= 2 under chaos, out-of-order
        # loader workers) would otherwise clobber each other's budget
        # mid-refetch — one window's floor aborting the other's healthy
        # retry, or one's clear handing the other a fresh full
        # deadline. Refetches contend for the same faulty peers anyway;
        # running them one at a time costs nothing correct.
        self._refetch_mu = threading.Lock()
        self._win: Dict[int, _Window] = {}
        self._next_issue = 0
        # Ring-slot recycling keys on IN-ORDER consumption: concurrent
        # loader workers can finish window w+1's gathers before window
        # w's last one, so a plain consumed-count would let window
        # w+depth overwrite w's still-live slot. `_floor` is the lowest
        # not-fully-consumed window; window w may issue only when
        # w < floor + depth (its slot's previous owner, w - depth, is
        # then provably consumed).
        self._floor = 0
        self._done_wins: set = set()
        self._exhausted = False
        self._closed = False
        self._error: Optional[BaseException] = None
        # Window planning (sort/unique over W batches of indices) is
        # real work — on a dedicated issuer thread it overlaps batch
        # consumption like the fetches themselves do, instead of
        # stalling the consumer that happened to deliver a window's
        # last batch.
        self._issuer = threading.Thread(target=self._issue_loop,
                                        name="ddstore-readahead-plan",
                                        daemon=True)
        self._issuer.start()

    # -- issue ------------------------------------------------------------

    def _alloc_ring(self, first_plan: WindowPlan) -> None:
        # Sized on first issue: the worst-case window is W × the first
        # window's batch size (all batches full, zero duplicates). An
        # explicit max_window_rows overrides (e.g. a caller with known
        # short batches).
        per_batch = max(int(b.size) for b in first_plan.batches)
        cap = self._max_rows or per_batch * self.window_batches
        prov = self._provided_ring or {}
        for v in self._vars:
            if self._ragged[v]:
                continue
            m = self.store._require(v)
            bufs = prov.get(v)
            if (bufs and len(bufs) >= self.depth
                    and all(b.dtype == m.dtype
                            and tuple(b.shape[1:]) == m.sample_shape
                            and b.shape[0] >= cap for b in bufs)):
                self._ring[v] = list(bufs[: self.depth])
                continue
            self._ring[v] = [
                np.empty((cap,) + m.sample_shape, m.dtype)
                for _ in range(self.depth)]
            for b in self._ring[v]:
                # Eager first-touch on the issuer thread: one memset
                # pass now instead of a page fault per 4 KiB inside the
                # timed fetch writes (gVisor faults are expensive).
                b.fill(0)
        self._max_rows = cap

    def _default_prefetch(self) -> int:
        """Requested warm-ahead depth: the DDSTORE_TIER_PREFETCH_DEPTH
        pin, else 2 (the scheduler refines it against the cache budget
        and its per-tier cells once the first plan's geometry is
        known)."""
        v = os.environ.get("DDSTORE_TIER_PREFETCH_DEPTH", "").strip()
        if v:
            try:
                return max(0, int(v))
            except ValueError:
                pass
        return 2

    def _refine_prefetch(self, plan: WindowPlan) -> None:
        """First plan: let the cost-model scheduler size the warm-ahead
        depth from the real window geometry vs the cache budget (and
        its measured hot-hit / cold-miss cells). A user pin wins inside
        planned_prefetch; sched-less engines keep the default."""
        if self.sched is None or \
                not hasattr(self.sched, "planned_prefetch"):
            return
        wbytes = sum(int(plan.rows.size) * rb
                     for rb in self._row_bytes.values())
        try:
            cache = int(self.store.tiering_stats().get(
                "cache_max_bytes", 0))
            self._prefetch = max(0, int(self.sched.planned_prefetch(
                self._prefetch, wbytes, cache, self.depth)))
        except Exception:  # noqa: BLE001 — advisory sizing only
            pass
        if self._prefetch == 0:
            self._warm = False

    def _warm_window(self, w: int, plan: WindowPlan) -> bool:
        """Hand window ``w``'s row list to the store's hot cache
        (advisory: any failure just leaves the window cold)."""
        warmed = False
        for v in self._vars:
            if self._ragged[v]:
                continue
            try:
                self.store.cache_prefetch(v, plan.rows,
                                          window=self._wid_base + w)
                warmed = True
            except Exception:  # noqa: BLE001 — reads stay correct cold
                return warmed
        if warmed:
            self._warmed.add(w)
        return warmed

    def _issue_loop(self) -> None:
        """Issuer thread: PLAN up to ``1 + prefetch`` windows ahead
        (warming the hot cache with each plan's row list the moment it
        exists) and ISSUE the head window as soon as its ring slot's
        previous owner (window ``w - depth``) is consumed. Planning and
        warming happen OUTSIDE the engine lock — consumers gathering
        from staged windows never wait on a sort."""
        while True:
            with self._mu:
                while True:
                    if self._closed or self._error is not None:
                        return
                    cap = 1 + (self._prefetch if self._warm else 0)
                    can_plan = (not self._iter_done
                                and len(self._planned) < cap)
                    can_issue = (bool(self._planned) and self._next_issue
                                 < self._floor + self.depth)
                    if can_plan or can_issue:
                        break
                    if self._iter_done and not self._planned:
                        self._exhausted = True
                        self._cond.notify_all()
                        return
                    self._cond.wait()
            try:
                # Issue first (the fetch should be in flight before the
                # next plan's sort runs), then top up the plan buffer.
                if can_issue:
                    if not self._issue_one():
                        return
                elif not self._plan_one():
                    continue  # iterator exhausted: loop decides the end
            except BaseException as e:  # noqa: BLE001
                with self._mu:
                    self._error = e
                    self._cond.notify_all()
                return

    def _plan_one(self) -> bool:
        """Plan (and cache-warm) the next window; False when the batch
        iterator is exhausted."""
        chunk = list(itertools.islice(self._batch_iter,
                                      self.window_batches))
        if not chunk:
            with self._mu:
                self._iter_done = True
                self._cond.notify_all()
            return False
        plan = plan_window(self._row_starts, chunk)
        if not self._ring and not all(self._ragged.values()):
            self._alloc_ring(plan)
        w = self._plan_next
        self._plan_next = w + 1
        if self._warm:
            if w == 0:
                self._refine_prefetch(plan)
            if self._warm:
                self._warm_window(w, plan)
        with self._mu:
            self._planned.append((w, plan))
            self._cond.notify_all()
        return True

    def _issue_one(self) -> bool:
        """Issue the head planned window into its ring slot; False when
        the engine closed mid-issue (tickets already released) or the
        issue failed (error latched)."""
        with self._mu:
            w, plan = self._planned.popleft()
        win = None
        try:
            win = _Window(plan, w % self.depth)
            n = int(plan.rows.size)
            if self._max_rows is not None and n > self._max_rows:
                raise ValueError(
                    f"readahead window {w} needs {n} staging rows "
                    f"but the ring was sized for {self._max_rows} "
                    f"(batches grew mid-epoch?)")
            win.wnum = w
            win.warmed = w in self._warmed
            if trace_enabled():
                # ddtrace: one span per window — issue/ready/stall
                # events group under it in the merged trace, next
                # to the native async-read spans its fetches mint.
                rank = int(getattr(self.store, "rank", -1))
                win.span = trace_new_span(rank)
                trace_emit("window_issue", win.span, rank, w, n,
                           sum(n * rb
                               for rb in self._row_bytes.values()))
            win.t_issue = time.monotonic()
            for v in self._vars:
                if self._ragged[v]:
                    win.futures[v] = self._exec.submit(
                        self._fetch_ragged, v, plan.rows)
                else:
                    buf = self._ring[v][win.slot][:n]
                    if self._use_runs[v]:
                        tgt, soff, doff, nb = self._runs_for(v, plan)
                        win.handles[v] = self.store.read_runs_async(
                            v, buf, tgt, soff, doff, nb)
                    else:
                        win.handles[v] = self.store.get_batch_async(
                            v, plan.rows, out=buf)
                    win.bufs[v] = buf
        except BaseException as e:  # noqa: BLE001
            # A partially-issued window (e.g. the label variable's
            # issue raised after the data read went in flight) must
            # not leak its tickets: the window was never registered
            # in _win, so close() cannot release them — and a leaked
            # in-flight read would keep writing into a ring buffer a
            # caller may hand to the next epoch's engine.
            if win is not None:
                for h in win.handles.values():
                    h.release()
                for f in win.futures.values():
                    try:
                        f.result()
                    except BaseException:  # noqa: BLE001
                        pass
            with self._mu:
                self._error = e
                self._cond.notify_all()
            return False
        with self._mu:
            if self._closed:
                # close() ran mid-issue: this window is not in
                # _win, so release its reads here.
                handles = list(win.handles.values())
            else:
                self._win[w] = win
                self._next_issue = w + 1
                handles = None
            self._cond.notify_all()
        if handles is not None:
            for h in handles:
                h.release()
            return False
        return True

    def _runs_for(self, var: str, plan: WindowPlan):
        """The window's coalesced runs as native byte spans: targets,
        source offsets (within each owner's shard), destination offsets
        (dense pack in sorted-row order — gather indices match), and
        lengths."""
        rb = self._row_bytes[var]
        rs = plan.run_starts
        lens = np.diff(np.r_[rs, plan.rows.size])
        tgt = plan.owner[rs]
        src_off = (plan.rows[rs] - self._row_starts[tgt]) * rb
        return tgt, src_off, rs * rb, lens * rb

    def _fetch_ragged(self, var: str, rows: np.ndarray):
        """Ragged window fetch on the background thread; the completion
        timestamp feeds the producer-idle accounting."""
        out = self.store.get_ragged_batch(var, rows)
        return out, time.monotonic()

    # -- readiness / accounting -------------------------------------------

    def _ensure_ready(self, win: _Window) -> None:
        if win.ready.is_set():
            return
        with win.ready_mu:
            if win.ready.is_set():
                return
            t0 = time.monotonic()
            try:
                done_ts = self._wait_window(win)
            except DDStoreError as e:
                if e.code not in (ERR_TRANSPORT, ERR_PEER_LOST):
                    # Data error (out of range, missing var): retrying
                    # cannot fix it. Latch so every consumer fails fast.
                    with self._mu:
                        self._error = e
                        self._cond.notify_all()
                    raise
                # Liveness sweep first: with shard replication in force
                # the window read normally fails over INSIDE the native
                # layer and never reaches this branch, but a loss that
                # did surface here should latch the suspect view before
                # the refetch — its get_batch chunks then short-circuit
                # the dead owner straight onto replicas (only the lost
                # rows reroute; live owners' chunks read normally), so
                # the window completes without another ladder burn.
                check = getattr(self.store, "check_health", None)
                if check is not None:
                    try:
                        check()
                    except Exception:  # noqa: BLE001
                        pass  # liveness polling must not mask the retry
                # Degraded mode: the bulk window fetch failed after the
                # native layer's own retries — retry ONCE at per-batch
                # granularity before surfacing. The refetch shares the
                # WINDOW's OP_DEADLINE budget rather than getting a
                # fresh one: against a permanently dead owner the window
                # give-up already burned ~1x the deadline, and a fresh
                # per-chunk budget would double the time to the
                # classified kErrPeerLost raise. Whatever the window
                # left over (floored so a transient blip still gets a
                # real retry) is the refetch's whole allowance. The
                # override is per-STORE (other ranks'/stores' budgets
                # in this process are untouched) and cleared on every
                # exit path; stores without the knob (test proxies)
                # just run the refetch on the full budget.
                deadline = DEFAULT_OP_DEADLINE_S
                try:
                    deadline = float(
                        os.environ.get("DDSTORE_OP_DEADLINE_S", "")
                        or DEFAULT_OP_DEADLINE_S)
                except ValueError:
                    pass
                set_deadline = getattr(self.store, "set_retry_deadline",
                                       None)
                try:
                    with self._refetch_mu:
                        # Remaining budget computed INSIDE the lock:
                        # waiting behind another window's refetch is
                        # part of this window's elapsed time.
                        elapsed = time.monotonic() - win.t_issue
                        remaining = max(min(2.0, 0.25 * deadline),
                                        deadline - elapsed)
                        try:
                            if set_deadline is not None:
                                set_deadline(remaining)
                            done_ts = self._refetch_window(win)
                        finally:
                            if set_deadline is not None:
                                set_deadline(0.0)
                except DDStoreError as e2:
                    # Window give-up: the bulk fetch AND its per-batch
                    # refetch both failed — snapshot every thread's
                    # last events before surfacing (the native layer
                    # already snapshotted on a surfaced kErrPeerLost;
                    # this covers the plain-transport give-up too).
                    if trace_enabled():
                        trace_flight("window_giveup",
                                     int(getattr(self.store, "rank",
                                                 -1)))
                    with self._mu:
                        self._error = e2
                        self._cond.notify_all()
                    raise
            t1 = time.monotonic()
            self._account(win, stall_s=t1 - t0,
                          idle_s=max(0.0, t0 - done_ts),
                          fetch_s=max(0.0, done_ts - win.t_issue))
            win.ready.set()

    def _wait_window(self, win: _Window) -> float:
        """Wait out every variable's window fetch; returns the latest
        completion timestamp. On ANY failure every still-pending native
        ticket is released before the error propagates (``async_pending``
        contributed by this window is 0 afterwards — no worker is left
        writing into a ring buffer the retry path is about to refill)."""
        done_ts = win.t_issue
        try:
            for v in self._vars:
                if self._ragged[v]:
                    (values, lens), ts = win.futures[v].result()
                    offs = np.concatenate(
                        ([0], np.cumsum(lens))).astype(np.int64)
                    win.ragged[v] = (values, lens, offs)
                    done_ts = max(done_ts, ts)
                else:
                    h = win.handles[v]
                    h.wait()  # fills the ring buffer, releases the ticket
                    if h.done_mono_s:
                        done_ts = max(done_ts, h.done_mono_s)
            return done_ts
        except BaseException:
            for h in win.handles.values():
                h.release()  # idempotent; blocks until the worker is out
            # Ragged futures are the same hazard in executor form: an
            # orphaned in-flight window fetch would keep hammering the
            # (possibly faulty) peers concurrently with the retry's
            # fresh fetch. Await them too; their own errors are
            # subsumed by the one propagating.
            for f in win.futures.values():
                try:
                    f.result()
                except BaseException:  # noqa: BLE001
                    pass
            raise

    def _refetch_window(self, win: _Window) -> float:
        """Per-batch-granularity retry of a transiently failed window:
        re-fetch every variable's sorted row list in ``n_batches``
        synchronous chunks straight into the staging buffers. A chunk
        failure propagates (already classified/augmented by the store
        layer — kErrPeerLost names the dead owner and the lost rows)."""
        m = self.metrics
        if m is not None and hasattr(m, "add_fault_event"):
            m.add_fault_event(windows_retried=1)
        rows = win.plan.rows
        nchunks = max(1, win.plan.n_batches)
        refetches = 0
        for v in self._vars:
            if self._ragged[v]:
                (values, lens), _ = self._fetch_ragged(v, rows)
                offs = np.concatenate(
                    ([0], np.cumsum(lens))).astype(np.int64)
                win.ragged[v] = (values, lens, offs)
                refetches += 1
                continue
            buf = win.bufs[v]
            for span in np.array_split(np.arange(rows.size), nchunks):
                if span.size == 0:
                    continue
                lo, hi = int(span[0]), int(span[-1]) + 1
                self.store.get_batch(v, rows[lo:hi], out=buf[lo:hi])
                refetches += 1
        if m is not None and hasattr(m, "add_fault_event"):
            m.add_fault_event(window_batch_refetches=refetches)
        return time.monotonic()

    def _account(self, win: _Window, stall_s: float, idle_s: float,
                 fetch_s: float) -> None:
        wbytes = sum(int(win.plan.rows.size) * rb
                     for rb in self._row_bytes.values())
        if win.span:
            rank = int(getattr(self.store, "rank", -1))
            trace_emit("window_ready", win.span, rank, win.wnum,
                       wbytes, int(fetch_s * 1e6))
            if stall_s > 1e-4:
                trace_emit("window_stall", win.span, rank, win.wnum, 0,
                           int(stall_s * 1e6))
        if self.sched is not None and fetch_s > 0.0:
            self.sched.observe_window(wbytes, fetch_s,
                                      cold=self._windows_fed == 0)
            if self._warm and hasattr(self.sched, "observe_tier"):
                # Per-tier read cells: a warmed window's fetch leg is
                # the hot-hit regime (in-RAM gather), an unwarmed one
                # the cold-miss regime — the cost model plans the
                # prefetch depth from exactly these two cells.
                self.sched.observe_tier(wbytes, fetch_s,
                                        warmed=win.warmed,
                                        cold=self._windows_fed == 0)
            self._windows_fed += 1
        m = self.metrics
        if m is None or not hasattr(m, "add_window"):
            return
        plan = win.plan
        rank = self.store.rank
        remote = plan.owner[plan.run_starts] != rank
        remote_rows = int((plan.owner != rank).sum())
        nbytes = sum(int(plan.rows.size) * rb
                     for rb in self._row_bytes.values())
        m.add_window(
            rows_requested=plan.n_requested,
            rows_unique=int(plan.rows.size),
            dup_rows=plan.dup_rows,
            runs=plan.n_runs,
            remote_runs=int(remote.sum()),
            peer_lists=int((plan.runs_per_peer
                            [np.arange(len(plan.runs_per_peer)) != rank]
                            > 0).sum()),
            window_bytes=nbytes,
            wait_s=stall_s, idle_s=idle_s, fetch_s=fetch_s)
        if hasattr(m, "add_bytes"):
            # Transport-side ledger, once per window: remote-owned
            # unique rows cross DCN (per-batch fetch would have moved
            # them again for every duplicate).
            dcn = sum(remote_rows * rb for rb in self._row_bytes.values())
            m.add_bytes(bytes_over_dcn=dcn)

    # -- consume ----------------------------------------------------------

    def _window_for(self, seq: int) -> Tuple[_Window, int]:
        w, b = divmod(int(seq), self.window_batches)
        with self._mu:
            while (w >= self._next_issue and not self._exhausted
                   and not self._closed and self._error is None):
                # Our window's ring slot is still owned by an earlier
                # window — wait for consumption to free it.
                self._cond.wait()
            if self._error is not None:
                raise self._error
            if self._closed:
                raise RuntimeError("readahead engine closed")
            win = self._win.get(w)
            if win is None:
                raise IndexError(f"batch {seq}: window {w} not available "
                                 f"(epoch exhausted or already consumed)")
        self._ensure_ready(win)
        return win, b

    def _verify(self, win: _Window, b: int, idx) -> None:
        # The engine replays the sampler independently of the loader; a
        # sampler that is not replay-deterministic would silently deliver
        # the wrong rows — make that loud instead.
        if idx is not None and not np.array_equal(
                np.asarray(idx, dtype=np.int64).reshape(-1),
                win.plan.batches[b]):
            raise RuntimeError(
                "readahead: sampler replay diverged from the loader's "
                "batch stream (the sampler must be replayable: two "
                "iterations yielding identical indices)")

    def _mark_delivered(self, seq: int) -> None:
        w = int(seq) // self.window_batches
        evict = None
        with self._mu:
            win = self._win.get(w)
            if win is None:
                return
            win.delivered += 1
            if win.delivered >= win.plan.n_batches:
                del self._win[w]
                self._done_wins.add(w)
                while self._floor in self._done_wins:
                    self._done_wins.discard(self._floor)
                    self._floor += 1
                # Eviction keyed on window CONSUMPTION: the warmed
                # entries served their window's fetch; the budget goes
                # back to the windows streaming in behind it.
                if w in self._warmed:
                    self._warmed.discard(w)
                    evict = self._wid_base + w
                self._cond.notify_all()  # wake the issuer (slot freed)
        if evict is not None:
            try:
                self.store.cache_evict(evict)
            except Exception:  # noqa: BLE001 — eviction is advisory
                pass

    def get_batch(self, seq: int, idx=None):
        """Deliver batch ``seq`` (global batch number) from its staged
        window: data rows, or ``(data, labels)`` with a co-variable —
        the same contract as ``ShardedDataset.fetch``. For a ragged
        data variable, returns ``(values, lengths)`` like
        ``get_ragged_batch``. ``idx``, when given, is checked against
        the engine's replay of the sampler."""
        win, b = self._window_for(seq)
        self._verify(win, b, idx)
        out = tuple(self._gather(win, v, b) for v in self._vars)
        self._mark_delivered(seq)
        return out[0] if len(out) == 1 else out

    def batch_rows(self, seq: int, idx=None) -> List[np.ndarray]:
        """Deliver batch ``seq`` as raw row arrays, one per variable, in
        batch order — the device-collective path's staging source (rows
        land in the padded send buffer instead of a host batch)."""
        win, b = self._window_for(seq)
        self._verify(win, b, idx)
        out = [self._gather(win, v, b) for v in self._vars]
        self._mark_delivered(seq)
        return out

    def _gather(self, win: _Window, var: str, b: int):
        sel = win.plan.batch_slice(b)
        if not self._ragged[var]:
            # take() over fancy indexing: same semantics, measurably
            # faster row gather on this hot path.
            return win.bufs[var].take(sel, axis=0)
        values, lens, offs = win.ragged[var]
        out_lens = lens[sel]
        total = int(out_lens.sum())
        if total == 0:
            return (np.empty((0,) + values.shape[1:], values.dtype),
                    out_lens.astype(np.int64))
        prefix = np.concatenate(([0], np.cumsum(out_lens)[:-1]))
        pos = (np.repeat(offs[sel] - prefix, out_lens)
               + np.arange(total, dtype=np.int64))
        return values.take(pos, axis=0), out_lens.astype(np.int64)

    @property
    def ring(self) -> Dict[str, List[np.ndarray]]:
        """The staging buffers, for handoff to the next epoch's engine
        (``EpochReadahead(..., ring=prev.ring)``) — skips reallocation
        AND refaulting of the (potentially large) windows. Only read
        this after :meth:`close`."""
        return dict(self._ring)

    # -- teardown ----------------------------------------------------------

    def close(self) -> None:
        """Cancel the epoch: block until every in-flight native read has
        finished, release every ticket, wake blocked consumers. After
        close, ``store.async_pending()`` contributed by this engine is
        0. Idempotent."""
        with self._mu:
            if self._closed:
                return
            self._closed = True
            wins = list(self._win.values())
            self._win.clear()
            self._cond.notify_all()
        # The issuer may be mid-plan/issue: it observes _closed at
        # registration time and releases its own window's reads.
        self._issuer.join()
        for win in wins:
            for h in win.handles.values():
                h.release()
        if self._exec is not None:
            self._exec.shutdown(wait=True)
        # Drop every hot-cache entry this engine warmed (consumed
        # windows already evicted themselves; this sweeps the planned-
        # ahead tail of a cancelled epoch, returning its quota bytes).
        for w in sorted(self._warmed):
            try:
                self.store.cache_evict(self._wid_base + w)
            except Exception:  # noqa: BLE001 — advisory teardown sweep
                pass
        self._warmed.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
