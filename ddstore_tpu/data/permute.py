"""O(1)-memory seeded index permutations (the billion-row index plane).

``np.random.permutation(total)`` materializes 8 bytes x total per rank
per epoch — 8 GB at the BASELINE config-5 scale of 1e9 rows (VERDICT r3
weak #5). A Feistel network over the index bits gives the same contract
(a deterministic seeded bijection on ``[0, n)``) as pure arithmetic:
``perm(i)`` for any ``i`` in O(1) memory, vectorized over blocks, so
samplers and shuffles stream an epoch instead of allocating it.

Construction: split the index into two halves of ``k`` bits (domain
``4^k`` is the smallest power of 4 >= n), run a 4-round Feistel with a
splitmix-style round function keyed per round from the seed, and
cycle-walk any output >= n back through the network (walk length is
geometric with mean < 4 since the domain is < 4n). Bijectivity on the
power-of-2 domain is structural (Feistel), so cycle-walking restricted
to [0, n) is bijective too — the standard format-preserving-encryption
argument.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["FeistelPermutation", "seeded_perm_slice", "DENSE_MAX"]

# Below this row count a materialized np.permutation is cheap (128 MB of
# int64 at the threshold) and Fisher–Yates mixing is marginally better;
# above it the Feistel bijection evaluates slices on demand. THE single
# policy constant for DistributedSampler and the global shuffles.
DENSE_MAX = 1 << 24


def seeded_perm_slice(total: int, begin: int, end: int, seed,
                      rng: Optional[np.random.Generator] = None
                      ) -> np.ndarray:
    """``perm[begin:end]`` of a seeded global permutation of ``total``
    rows, in O(end - begin) memory when total is large. Identical
    (total, seed) => identical permutation on every rank. An explicit
    ``rng`` forces the dense path (callers who pass one expect
    np.permutation semantics)."""
    if rng is not None or total <= DENSE_MAX:
        g = rng or np.random.default_rng(seed)
        return g.permutation(total)[begin:end]
    return FeistelPermutation(total, seed)(
        np.arange(begin, end, dtype=np.int64))

_M1 = np.uint64(0x9E3779B97F4A7C15)
_M2 = np.uint64(0xBF58476D1CE4E5B9)
_M3 = np.uint64(0x94D049BB133111EB)


def _mix(x: np.ndarray, key: np.uint64) -> np.ndarray:
    """splitmix64-style avalanche of x under key (vectorized uint64)."""
    x = (x + key) * _M1
    x ^= x >> np.uint64(29)
    x *= _M2
    x ^= x >> np.uint64(32)
    x *= _M3
    x ^= x >> np.uint64(31)
    return x


class FeistelPermutation:
    """Seeded bijection on ``[0, n)``; ``perm(idx)`` is vectorized and
    allocates only O(len(idx)).

    Identical (n, seed) => identical permutation on every rank — the
    property DistributedSampler and the global shuffles rely on.
    """

    def __init__(self, n: int, seed, rounds: int = 4):
        if n <= 0:
            raise ValueError(f"n must be positive, got {n}")
        self.n = int(n)
        # Half-width: smallest k with 4^k >= n (so the domain is < 4n and
        # cycle-walking terminates quickly).
        k = 1
        while (1 << (2 * k)) < self.n:
            k += 1
        self._k = np.uint64(k)
        self._mask = np.uint64((1 << k) - 1)
        g = np.random.default_rng(seed)
        self._keys = [np.uint64(v) for v in
                      g.integers(0, 1 << 63, size=rounds, dtype=np.int64)]

    def _walk_once(self, x: np.ndarray) -> np.ndarray:
        l, r = x >> self._k, x & self._mask
        for key in self._keys:
            l, r = r, l ^ (_mix(r, key) & self._mask)
        return (l << self._k) | r

    def __call__(self, idx) -> np.ndarray:
        x = np.asarray(idx, dtype=np.uint64)
        scalar = x.ndim == 0
        x = np.atleast_1d(x)
        if x.size and int(x.max()) >= self.n:
            raise IndexError(f"index out of range for permutation over "
                             f"[0, {self.n})")
        out = self._walk_once(x)
        # Cycle-walk: values that left [0, n) re-enter the network until
        # they land inside. Restriction of a bijection to an invariant
        # cycle structure — still a bijection on [0, n).
        bad = out >= self.n
        while bad.any():
            out[bad] = self._walk_once(out[bad])
            bad = out >= self.n
        res = out.astype(np.int64)
        return res[0] if scalar else res
