"""Shared tiering/index-plane soak harness (BASELINE config-5 scale).

One implementation consumed by BOTH the bench (`bench.py` soak phase)
and the regression test (`tests/test_tiering.py`) so the two can never
measure different things: a sparse mmap-backed shard at 10^8-row scale,
sentinel rows pinning read correctness at far offsets, a Feistel-sampled
partial epoch of batched gets, and RSS accounting that must track pages
touched — never the row count (the reference copies every shard into
RAM at registration, ddstore.hpp:43-49)."""

from __future__ import annotations

import itertools
import os
import shutil
import tempfile
import time
from typing import Optional

import numpy as np

__all__ = ["mmap_soak"]


def _vm_rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    raise RuntimeError("no VmRSS in /proc/self/status")


def _sentinel(r: int) -> np.ndarray:
    return np.asarray([r & 0x7FFFFFFF, (r * 31) & 0x7FFFFFFF], np.int32)


def mmap_soak(rows: int = 100_000_000, batch: int = 65536,
              nbatches: int = 64, directory: Optional[str] = None,
              budget_s: Optional[float] = None) -> dict:
    """Run the soak; returns a dict of measurements:

    * ``rows`` / ``rows_sampled`` — shard size and rows actually fetched
    * ``rows_per_s`` — batched-get throughput of the sampled epoch
    * ``batches_run`` — batches completed (< ``nbatches`` when
      ``budget_s`` cut the epoch short; throughput stays valid — it is
      rows-fetched over time-spent either way)
    * ``rss_add_delta_mb`` — RSS growth across ``add_mmap`` (must be
      ~0: registration must not copy the shard)
    * ``rss_delta_mb`` — RSS growth across the whole soak (bounded by
      pages touched, at most the file size — not by row count)
    * ``sentinels_ok`` — far-offset reads returned the stamped bytes

    ``budget_s`` bounds the SAMPLED-EPOCH wall time: on a slow box
    (cold page cache, sandboxed I/O) the fixed iteration count can
    outlive a caller's harness timeout, and a killed soak reports
    nothing; a budget-truncated one reports everything it measured.
    """
    from .. import DDStore
    from ..data import DistributedSampler

    d = directory or tempfile.mkdtemp()
    path = os.path.join(d, "edges.bin")
    try:
        with open(path, "wb") as f:
            f.truncate(rows * 8)  # sparse: 2 x int32 rows, read as zeros
            stamps = list(range(0, rows, max(1, rows // 63)))[:63] \
                + [rows - 1]
            for r in stamps:
                f.seek(r * 8)
                f.write(_sentinel(r).tobytes())
        with DDStore(backend="local") as s:
            rss0 = _vm_rss_mb()
            s.add_mmap("edges", path, np.int32, (2,))
            rss_add = _vm_rss_mb() - rss0
            assert s.total_rows("edges") == rows
            got = s.get_batch("edges", stamps)
            ok = bool((got == np.stack([_sentinel(r)
                                        for r in stamps])).all())
            sampler = DistributedSampler(rows, world=1, rank=0, seed=7,
                                         mode="streamed")
            t0 = time.perf_counter()
            n = nb = 0
            for b in itertools.islice(sampler.batches(batch), nbatches):
                out = s.get_batch("edges", b)
                assert out.shape == (len(b), 2)
                n += len(b)
                nb += 1
                if budget_s is not None \
                        and time.perf_counter() - t0 > budget_s:
                    break
            dt = time.perf_counter() - t0
            return {"rows": rows, "rows_sampled": n,
                    "rows_per_s": n / dt,
                    "batches_run": nb,
                    "rss_add_delta_mb": rss_add,
                    "rss_delta_mb": _vm_rss_mb() - rss0,
                    "sentinels_ok": ok}
    finally:
        if directory is None:
            shutil.rmtree(d, ignore_errors=True)
        else:
            try:
                os.unlink(path)
            except OSError:
                pass
