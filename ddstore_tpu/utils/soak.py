"""Shared tiering/index-plane soak harness (BASELINE config-5 scale).

One implementation consumed by BOTH the bench (`bench.py` soak phase)
and the regression test (`tests/test_tiering.py`) so the two can never
measure different things: a sparse mmap-backed shard at 10^8-row scale,
sentinel rows pinning read correctness at far offsets, a Feistel-sampled
partial epoch of batched gets, and RSS accounting that must track pages
touched — never the row count (the reference copies every shard into
RAM at registration, ddstore.hpp:43-49)."""

from __future__ import annotations

import itertools
import os
import shutil
import tempfile
import time
from typing import Optional

import numpy as np

__all__ = ["mmap_soak"]


def _vm_rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    raise RuntimeError("no VmRSS in /proc/self/status")


def _sentinel(r: int) -> np.ndarray:
    return np.asarray([r & 0x7FFFFFFF, (r * 31) & 0x7FFFFFFF], np.int32)


def mmap_soak(rows: int = 100_000_000, batch: int = 65536,
              nbatches: int = 64, directory: Optional[str] = None,
              budget_s: Optional[float] = None,
              fault_spec: Optional[str] = None,
              fault_seed: int = 7) -> dict:
    """Run the soak; returns a dict of measurements:

    * ``rows`` / ``rows_sampled`` — shard size and rows actually fetched
    * ``rows_per_s`` — batched-get throughput of the sampled epoch
    * ``batches_run`` — batches completed (< ``nbatches`` when
      ``budget_s`` cut the epoch short; throughput stays valid — it is
      rows-fetched over time-spent either way)
    * ``rss_add_delta_mb`` — RSS growth across ``add_mmap`` (must be
      ~0: registration must not copy the shard)
    * ``rss_delta_mb`` — RSS growth across the whole soak (bounded by
      pages touched, at most the file size — not by row count)
    * ``sentinels_ok`` — far-offset reads returned the stamped bytes

    ``budget_s`` bounds the SAMPLED-EPOCH wall time: on a slow box
    (cold page cache, sandboxed I/O) the fixed iteration count can
    outlive a caller's harness timeout, and a killed soak reports
    nothing; a budget-truncated one reports everything it measured.

    ``fault_spec`` switches the soak to its CHAOS mode: the shard is
    split across a 2-rank in-process group (a single-rank store never
    touches the transport, so there would be nothing to inject into),
    the deterministic injector is armed with the spec, and EVERY
    sampled batch is verified byte-identical against a direct mapping
    of the backing files. Adds ``faults_ok`` (all batches byte-exact),
    ``fault_injected`` / ``fault_retries`` / ``fault_giveups`` to the
    result — the "epoch completes byte-identical under transient
    faults" proof at tiering scale.

    A spec containing a ``corrupt:`` arm additionally runs the soak in
    its INTEGRITY mode: checksum verification is enabled on both ranks
    (runtime configure — no env plumbing) and the group runs at
    ``DDSTORE_REPLICATION=2`` so the verify ladder's replica rung can
    absorb ANY corruption rate (at R=1 a primary whose one retry is
    also corrupted correctly surfaces ``ERR_CORRUPT`` — honest, but
    the soak's job is to prove end-to-end REPAIR). Mirrors fill before
    the injector arms, so they hold clean bytes; note the R×RAM cost
    at large ``rows``. The byte-identity check then proves: 0
    give-ups, 0 silent mismatches. Adds ``corrupt_injected`` /
    ``corrupt_detected`` / ``corrupt_errors`` to the result.
    """
    if fault_spec is not None:
        return _mmap_soak_chaos(rows, batch, nbatches, directory,
                                budget_s, fault_spec, fault_seed)
    from .. import DDStore
    from ..data import DistributedSampler

    d = directory or tempfile.mkdtemp()
    path = os.path.join(d, "edges.bin")
    try:
        with open(path, "wb") as f:
            f.truncate(rows * 8)  # sparse: 2 x int32 rows, read as zeros
            stamps = list(range(0, rows, max(1, rows // 63)))[:63] \
                + [rows - 1]
            for r in stamps:
                f.seek(r * 8)
                f.write(_sentinel(r).tobytes())
        with DDStore(backend="local") as s:
            rss0 = _vm_rss_mb()
            s.add_mmap("edges", path, np.int32, (2,))
            rss_add = _vm_rss_mb() - rss0
            assert s.total_rows("edges") == rows
            got = s.get_batch("edges", stamps)
            ok = bool((got == np.stack([_sentinel(r)
                                        for r in stamps])).all())
            sampler = DistributedSampler(rows, world=1, rank=0, seed=7,
                                         mode="streamed")
            t0 = time.perf_counter()
            n = nb = 0
            for b in itertools.islice(sampler.batches(batch), nbatches):
                out = s.get_batch("edges", b)
                assert out.shape == (len(b), 2)
                n += len(b)
                nb += 1
                if budget_s is not None \
                        and time.perf_counter() - t0 > budget_s:
                    break
            dt = time.perf_counter() - t0
            return {"rows": rows, "rows_sampled": n,
                    "rows_per_s": n / dt,
                    "batches_run": nb,
                    "rss_add_delta_mb": rss_add,
                    "rss_delta_mb": _vm_rss_mb() - rss0,
                    "sentinels_ok": ok}
    finally:
        if directory is None:
            shutil.rmtree(d, ignore_errors=True)
        else:
            try:
                os.unlink(path)
            except OSError:
                pass


def _mmap_soak_chaos(rows: int, batch: int, nbatches: int,
                     directory: Optional[str], budget_s: Optional[float],
                     fault_spec: str, fault_seed: int) -> dict:
    """Chaos variant of the soak (see ``mmap_soak(fault_spec=...)``):
    2-rank ThreadGroup over two sparse mmap shards, deterministic fault
    injection on the transport path (absorbed by the store's transient-
    retry layer), every batch verified byte-identical against the
    backing files themselves."""
    import threading
    import uuid

    from .. import DDStore, ThreadGroup
    from ..binding import fault_configure
    from ..data import DistributedSampler

    half = rows // 2
    counts = (half, rows - half)
    d = directory or tempfile.mkdtemp()
    paths = [os.path.join(d, f"edges{r}.bin") for r in range(2)]
    name = uuid.uuid4().hex
    stamps = list(range(0, rows, max(1, rows // 63)))[:63] + [rows - 1]
    # A corrupt: arm needs the verify machinery on BOTH ranks (the
    # owner serves its sum table, the reader verifies) — otherwise the
    # flipped bytes would flow silently into the delivered batches and
    # the byte-identity check would fail by design.
    corrupt_mode = "corrupt" in fault_spec
    repl_backup = os.environ.get("DDSTORE_REPLICATION")
    if corrupt_mode:
        os.environ["DDSTORE_REPLICATION"] = "2"
    result: dict = {}
    errors: list = []
    done = threading.Event()

    def serve_rank1():
        try:
            g = ThreadGroup(name, 1, 2)
            with DDStore(g, backend="local") as s1:
                if corrupt_mode:
                    s1.integrity_configure(verify=1)
                s1.add_mmap("edges", paths[1], np.int32, (2,))
                # Serve until rank 0 finishes; the with-exit close()
                # pairs with rank 0's (barriers are matched by tag, so
                # no extra collectives may run on one side only).
                done.wait(600)
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))
            done.set()

    try:
        for r, (p, n) in enumerate(zip(paths, counts)):
            lo = 0 if r == 0 else half
            with open(p, "wb") as f:
                f.truncate(n * 8)
                for g in stamps:
                    if lo <= g < lo + n:
                        f.seek((g - lo) * 8)
                        f.write(_sentinel(g).tobytes())
        t1 = threading.Thread(target=serve_rank1, daemon=True)
        t1.start()
        g0 = ThreadGroup(name, 0, 2)
        with DDStore(g0, backend="local") as s:
            if corrupt_mode:
                s.integrity_configure(verify=1)
            rss0 = _vm_rss_mb()
            s.add_mmap("edges", paths[0], np.int32, (2,))
            assert s.total_rows("edges") == rows
            # Direct read-only views of BOTH backing files: the ground
            # truth every fetched batch is compared against.
            vm = [np.memmap(p, dtype=np.int32, mode="r",
                            shape=(n, 2)) for p, n in zip(paths, counts)]

            def expected(idx):
                out = np.empty((len(idx), 2), np.int32)
                m0 = idx < half
                out[m0] = vm[0][idx[m0]]
                out[~m0] = vm[1][idx[~m0] - half]
                return out

            fault_configure(fault_spec, fault_seed)
            try:
                fs0 = s.fault_stats()
                is0 = s.integrity_stats() if corrupt_mode else {}
                got = s.get_batch("edges", stamps)
                ok = bool((got == np.stack([_sentinel(r)
                                            for r in stamps])).all())
                sampler = DistributedSampler(rows, world=1, rank=0,
                                             seed=7, mode="streamed")
                faults_ok = True
                t0 = time.perf_counter()
                n = nb = 0
                for b in itertools.islice(sampler.batches(batch),
                                          nbatches):
                    out = s.get_batch("edges", b)
                    faults_ok = faults_ok and bool(
                        (out == expected(np.asarray(b))).all())
                    n += len(b)
                    nb += 1
                    if budget_s is not None \
                            and time.perf_counter() - t0 > budget_s:
                        break
                dt = time.perf_counter() - t0
                fs = s.fault_stats()
                is1 = s.integrity_stats() if corrupt_mode else {}
            finally:
                fault_configure("", 0)
            done.set()
            result = {
                "rows": rows, "rows_sampled": n,
                "rows_per_s": n / dt,
                "batches_run": nb,
                "rss_delta_mb": _vm_rss_mb() - rss0,
                "sentinels_ok": ok,
                "faults_ok": faults_ok,
                "fault_injected": (fs["injected_reset"]
                                   + fs["injected_trunc"]
                                   + fs["injected_delay"]
                                   + fs["injected_stall"]
                                   - (fs0["injected_reset"]
                                      + fs0["injected_trunc"]
                                      + fs0["injected_delay"]
                                      + fs0["injected_stall"])),
                "fault_retries": (fs["retry_attempts"]
                                  - fs0["retry_attempts"]),
                "fault_giveups": fs["retry_giveups"] - fs0["retry_giveups"],
            }
            if corrupt_mode:
                result["corrupt_injected"] = (
                    fs.get("injected_corrupt", 0)
                    - fs0.get("injected_corrupt", 0))
                result["corrupt_detected"] = (
                    is1.get("verify_mismatches", 0)
                    - is0.get("verify_mismatches", 0))
                result["corrupt_errors"] = (
                    is1.get("corrupt_errors", 0)
                    - is0.get("corrupt_errors", 0))
        t1.join(60)
        if errors:
            raise RuntimeError(f"chaos soak rank 1 failed: {errors}")
        return result
    finally:
        done.set()
        if corrupt_mode:
            if repl_backup is None:
                os.environ.pop("DDSTORE_REPLICATION", None)
            else:
                os.environ["DDSTORE_REPLICATION"] = repl_backup
        if directory is None:
            shutil.rmtree(d, ignore_errors=True)
        else:
            for p in paths:
                try:
                    os.unlink(p)
                except OSError:
                    pass
