"""Observability the reference lacks entirely (SURVEY §5: its only tracing
is commented-out printf): per-get latency histograms and the
input-pipeline-efficiency metric that is the BASELINE.json north star
(≥95% efficiency == near-zero device stall)."""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class LatencyHistogram:
    """Streaming latency recorder with percentile summaries. Thread-safe:
    the loader's worker pool records fetch/stage latencies concurrently."""

    def __init__(self, name: str = "latency", max_samples: int = 1 << 16):
        self.name = name
        self.max_samples = max_samples
        self._samples: List[float] = []
        self._mu = threading.Lock()
        self.count = 0
        self.total = 0.0

    def record(self, seconds: float) -> None:
        with self._mu:
            self.count += 1
            self.total += seconds
            if len(self._samples) < self.max_samples:
                self._samples.append(seconds)
            else:  # reservoir sampling keeps percentiles honest on long runs
                import random
                j = random.randrange(self.count)
                if j < self.max_samples:
                    self._samples[j] = seconds

    def timed(self):
        """Context manager: ``with hist.timed(): ...``"""
        return _Timer(self)

    def percentile(self, q: float) -> float:
        if not self._samples:
            return 0.0
        xs = sorted(self._samples)
        k = min(len(xs) - 1, max(0, int(round(q / 100 * (len(xs) - 1)))))
        return xs[k]

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def summary(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "mean_s": self.mean,
            "p50_s": self.percentile(50),
            "p95_s": self.percentile(95),
            "p99_s": self.percentile(99),
        }


class _Timer:
    def __init__(self, hist: LatencyHistogram):
        self.hist = hist

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.record(time.perf_counter() - self.t0)


def plan_stats_delta(begin: Dict, end: Dict) -> Dict:
    """Per-window scatter-planner statistics from two cumulative
    ``plan_stats()`` snapshots (the counters are monotone since store
    creation). The derived ratios are recomputed from the deltas — NOT
    diffed — so a window's coalesce ratio describes that window's
    batches, not the whole store lifetime:

    * ``plan_coalesce_ratio`` — unique rows fetched per transport run
      (1.0 = nothing coalesced; higher = fewer, larger segments).
    * ``plan_runs_per_peer_list`` — remote runs per per-peer request
      issued (the fan-out each transport call carries).
    """
    out = {}
    for k in ("plan_batches", "plan_rows", "plan_runs", "plan_local_runs",
              "plan_peer_lists", "plan_dedup_hits", "plan_scratch_runs",
              "plan_scratch_bytes"):
        out[k] = int(end.get(k, 0)) - int(begin.get(k, 0))
    uniq = out["plan_rows"] - out["plan_dedup_hits"]
    out["plan_coalesce_ratio"] = \
        uniq / out["plan_runs"] if out["plan_runs"] else 0.0
    out["plan_runs_per_peer_list"] = \
        (out["plan_runs"] - out["plan_local_runs"]) / out["plan_peer_lists"] \
        if out["plan_peer_lists"] else 0.0
    return out


class PipelineMetrics:
    """Input-pipeline efficiency: fraction of wall-clock the device did NOT
    wait on data. The loader records how long each ``__next__`` blocked
    (`wait`); the training loop's total span is everything else (compute +
    dispatch). efficiency = 1 - wait/total.

    With a plan source attached (``set_plan_source`` — the loader wires
    its dataset's ``DDStore.plan_stats`` automatically), the summary also
    carries the epoch's scatter-read planner statistics: how well the
    fetch path coalesced/deduped this epoch's batches."""

    #: ledger counters accepted by :meth:`add_bytes` (anything else is
    #: rejected loudly — a typo'd counter must not vanish silently)
    BYTE_KEYS = ("bytes_local_get", "bytes_over_ici", "bytes_over_dcn",
                 "rows_over_ici")

    def __init__(self, plan_source: Optional[Callable[[], Dict]] = None):
        self.wait = LatencyHistogram("device_wait")
        self.fetch = LatencyHistogram("host_fetch")
        self.stage = LatencyHistogram("device_put")
        self._t_start: Optional[float] = None
        self._t_end: Optional[float] = None
        self._plan_source = plan_source
        self._plan_begin: Optional[Dict] = None
        self._plan_end: Optional[Dict] = None
        # Bytes-moved ledger (device-collective fetch vs host path):
        # which link carried this epoch's sample bytes. Guarded — the
        # loader's worker pool records from several threads.
        self._bytes_mu = threading.Lock()
        self._bytes: Dict[str, int] = {k: 0 for k in self.BYTE_KEYS}

    def set_plan_source(self, source: Optional[Callable[[], Dict]]) -> None:
        """Attach a zero-arg callable returning cumulative planner
        counters (``DDStore.plan_stats``). Snapshotted at epoch
        boundaries; ``summary()`` reports the per-epoch delta."""
        self._plan_source = source

    def _snap_plan(self) -> Optional[Dict]:
        if self._plan_source is None:
            return None
        try:
            return dict(self._plan_source())
        except Exception:
            # A closed/torn-down store must not sink epoch accounting.
            return None

    def add_bytes(self, **counters: int) -> None:
        """Fold one fetch's bytes-moved ledger into the epoch totals
        (``bytes_local_get`` / ``bytes_over_ici`` / ``bytes_over_dcn``
        [+ ``rows_over_ici``] — the device-collective A/B ledger)."""
        with self._bytes_mu:
            for k, v in counters.items():
                if k not in self._bytes:
                    raise KeyError(f"unknown byte counter {k!r}; "
                                   f"expected one of {self.BYTE_KEYS}")
                self._bytes[k] += int(v)

    def bytes_moved(self) -> Dict[str, int]:
        with self._bytes_mu:
            return dict(self._bytes)

    def epoch_start(self) -> None:
        self._t_start = time.perf_counter()
        self._plan_begin = self._snap_plan()
        self._plan_end = None
        with self._bytes_mu:
            self._bytes = {k: 0 for k in self.BYTE_KEYS}

    def epoch_end(self) -> None:
        self._t_end = time.perf_counter()
        self._plan_end = self._snap_plan()

    @property
    def total_s(self) -> float:
        if self._t_start is None:
            return 0.0
        end = self._t_end if self._t_end is not None else time.perf_counter()
        return end - self._t_start

    @property
    def efficiency(self) -> float:
        total = self.total_s
        if total <= 0:
            return 1.0
        return max(0.0, 1.0 - self.wait.total / total)

    def summary(self) -> Dict:
        out = {
            "input_pipeline_efficiency": self.efficiency,
            "total_s": self.total_s,
            "device_wait": self.wait.summary(),
            "host_fetch": self.fetch.summary(),
            "device_put": self.stage.summary(),
        }
        if self._plan_begin is not None:
            # Mid-epoch summary: diff against the live counters.
            end = self._plan_end if self._plan_end is not None \
                else self._snap_plan()
            if end is not None:
                out["scatter_plan"] = plan_stats_delta(self._plan_begin, end)
        moved = self.bytes_moved()
        if any(moved.values()):
            out["bytes_moved"] = moved
        return out
