"""Observability the reference lacks entirely (SURVEY §5: its only tracing
is commented-out printf): per-get latency histograms and the
input-pipeline-efficiency metric that is the BASELINE.json north star
(≥95% efficiency == near-zero device stall)."""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


class LatencyHistogram:
    """Streaming latency recorder with percentile summaries. Thread-safe:
    the loader's worker pool records fetch/stage latencies concurrently."""

    def __init__(self, name: str = "latency", max_samples: int = 1 << 16):
        self.name = name
        self.max_samples = max_samples
        self._samples: List[float] = []
        self._mu = threading.Lock()
        self.count = 0
        self.total = 0.0

    def record(self, seconds: float) -> None:
        with self._mu:
            self.count += 1
            self.total += seconds
            if len(self._samples) < self.max_samples:
                self._samples.append(seconds)
            else:  # reservoir sampling keeps percentiles honest on long runs
                import random
                j = random.randrange(self.count)
                if j < self.max_samples:
                    self._samples[j] = seconds

    def timed(self):
        """Context manager: ``with hist.timed(): ...``"""
        return _Timer(self)

    def percentile(self, q: float) -> float:
        if not self._samples:
            return 0.0
        xs = sorted(self._samples)
        k = min(len(xs) - 1, max(0, int(round(q / 100 * (len(xs) - 1)))))
        return xs[k]

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def summary(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "mean_s": self.mean,
            "p50_s": self.percentile(50),
            "p95_s": self.percentile(95),
            "p99_s": self.percentile(99),
        }


class _Timer:
    def __init__(self, hist: LatencyHistogram):
        self.hist = hist

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.record(time.perf_counter() - self.t0)


def plan_stats_delta(begin: Dict, end: Dict) -> Dict:
    """Per-window scatter-planner statistics from two cumulative
    ``plan_stats()`` snapshots (the counters are monotone since store
    creation). The derived ratios are recomputed from the deltas — NOT
    diffed — so a window's coalesce ratio describes that window's
    batches, not the whole store lifetime:

    * ``plan_coalesce_ratio`` — unique rows fetched per transport run
      (1.0 = nothing coalesced; higher = fewer, larger segments).
    * ``plan_runs_per_peer_list`` — remote runs per per-peer request
      issued (the fan-out each transport call carries).
    """
    out = {}
    for k in ("plan_batches", "plan_rows", "plan_runs", "plan_local_runs",
              "plan_peer_lists", "plan_dedup_hits", "plan_scratch_runs",
              "plan_scratch_bytes"):
        out[k] = int(end.get(k, 0)) - int(begin.get(k, 0))
    uniq = out["plan_rows"] - out["plan_dedup_hits"]
    out["plan_coalesce_ratio"] = \
        uniq / out["plan_runs"] if out["plan_runs"] else 0.0
    out["plan_runs_per_peer_list"] = \
        (out["plan_runs"] - out["plan_local_runs"]) / out["plan_peer_lists"] \
        if out["plan_peer_lists"] else 0.0
    return out


class PipelineMetrics:
    """Input-pipeline efficiency: fraction of wall-clock the device did NOT
    wait on data. The loader records how long each ``__next__`` blocked
    (`wait`); the training loop's total span is everything else (compute +
    dispatch). efficiency = 1 - wait/total.

    With a plan source attached (``set_plan_source`` — the loader wires
    its dataset's ``DDStore.plan_stats`` automatically), the summary also
    carries the epoch's scatter-read planner statistics: how well the
    fetch path coalesced/deduped this epoch's batches."""

    #: ledger counters accepted by :meth:`add_bytes` (anything else is
    #: rejected loudly — a typo'd counter must not vanish silently)
    BYTE_KEYS = ("bytes_local_get", "bytes_over_ici", "bytes_over_dcn",
                 "rows_over_ici")

    #: per-window readahead counters accepted by :meth:`add_window`
    WINDOW_KEYS = ("rows_requested", "rows_unique", "dup_rows", "runs",
                   "remote_runs", "peer_lists", "window_bytes")

    #: degraded-mode events accepted by :meth:`add_fault_event` — the
    #: pipeline-level half of the fault story (the native half comes
    #: from the fault source):
    #:   windows_retried          readahead windows re-fetched at
    #:                            per-batch granularity after a
    #:                            transient window-fetch failure
    #:   window_batch_refetches   per-batch refetch requests those
    #:                            retries issued
    #:   readahead_degraded       engines abandoned mid-epoch (loader
    #:                            fell back to per-batch fetch)
    #:   collective_batch_fallbacks  device-collective batches that fell
    #:                            back to the host path on a transient
    #:                            staging failure
    FAULT_EVENT_KEYS = ("windows_retried", "window_batch_refetches",
                        "readahead_degraded", "collective_batch_fallbacks",
                        "admission_deferred_batches")

    def __init__(self, plan_source: Optional[Callable[[], Dict]] = None):
        self.wait = LatencyHistogram("device_wait")
        self.fetch = LatencyHistogram("host_fetch")
        self.stage = LatencyHistogram("device_put")
        # Readahead window accounting: how long the consumer stalled on
        # an unfinished window fetch vs how long staged windows sat
        # ready ahead of need (the overlap headroom), plus the fetch
        # leg's own wall time (issue -> transport completion — the
        # number comparable to bulk-stripe bandwidth).
        self.ra_wait = LatencyHistogram("readahead_consumer_wait")
        self.ra_idle = LatencyHistogram("readahead_producer_idle")
        self.ra_fetch = LatencyHistogram("readahead_window_fetch")
        self._t_start: Optional[float] = None
        self._t_end: Optional[float] = None
        self._plan_source = plan_source
        self._plan_begin: Optional[Dict] = None
        self._plan_end: Optional[Dict] = None
        # Bytes-moved ledger (device-collective fetch vs host path):
        # which link carried this epoch's sample bytes. Guarded — the
        # loader's worker pool records from several threads.
        self._bytes_mu = threading.Lock()
        self._bytes: Dict[str, int] = {k: 0 for k in self.BYTE_KEYS}
        # Per-lane byte ledger (multi-lane TCP transport): a cumulative
        # per-lane-bytes source (DDStore.lane_bytes) snapshotted at
        # epoch boundaries; bytes_moved() reports the per-epoch delta
        # plus the derived lane utilization.
        self._lane_source: Optional[Callable[[], List[int]]] = None
        self._lane_begin: Optional[List[int]] = None
        self._lane_end: Optional[List[int]] = None
        self._ra_mu = threading.Lock()
        self._ra: Dict[str, int] = {k: 0 for k in self.WINDOW_KEYS}
        self._ra_windows = 0
        # Fault accounting: a cumulative-counter source (DDStore.
        # fault_stats — injector draws + native retry layers) snapshotted
        # at epoch boundaries, plus pipeline-level degradation events.
        self._fault_source: Optional[Callable[[], Dict]] = None
        self._fault_begin: Optional[Dict] = None
        self._fault_end: Optional[Dict] = None
        self._fault_mu = threading.Lock()
        self._fault_events: Dict[str, int] = \
            {k: 0 for k in self.FAULT_EVENT_KEYS}
        # Replicated-read failover ledger: a cumulative-counter source
        # (DDStore.failover_stats) snapshotted at epoch boundaries —
        # summary()["failover"] is how an epoch record proves "peer
        # died, replicas served, zero give-ups" on its own.
        self._failover_source: Optional[Callable[[], Dict]] = None
        self._failover_begin: Optional[Dict] = None
        self._failover_end: Optional[Dict] = None
        # (bytes, fetch_s) per window, for the honest per-window best
        # bandwidth (bounded: one entry per window, windows are O(epoch
        # batches / W)).
        self._ra_fetch_samples: List[Tuple[int, float]] = []
        # Cost-model scheduler snapshot source (Scheduler.snapshot):
        # summary()["sched"] is how a bench record explains WHY each
        # transport knob was set this epoch.
        self._sched_source: Optional[Callable[[], Dict]] = None
        # Per-tenant ledger source (DDStore.tenant_stats): snapshotted
        # at epoch boundaries, summary()["tenants"] carries the
        # per-tenant deltas (quota rejections, admissions/deferrals,
        # read/served traffic) plus the live gauges.
        self._tenant_source: Optional[Callable[[], Dict]] = None
        self._tenant_begin: Optional[Dict] = None
        self._tenant_end: Optional[Dict] = None
        # ddtrace source (DDStore.trace_summary): summary()["trace"]
        # carries per-epoch captured/dropped/flight deltas plus the
        # measured span-latency percentiles while tracing is on.
        self._trace_source: Optional[Callable[[], Dict]] = None
        self._trace_counters_source: Optional[Callable[[], Dict]] = None
        self._trace_begin: Optional[Dict] = None
        self._trace_end: Optional[Dict] = None
        # Integrity source (DDStore.integrity_stats): snapshotted at
        # epoch boundaries — summary()["integrity"] is how an epoch
        # record proves "every remote byte verified, N mismatches
        # caught and repaired, zero silent corruption" on its own.
        self._integrity_source: Optional[Callable[[], Dict]] = None
        self._integrity_begin: Optional[Dict] = None
        self._integrity_end: Optional[Dict] = None
        # Tiering source (DDStore.tiering_stats): snapshotted at epoch
        # boundaries — summary()["tiering"] is how an epoch record
        # proves "the hot cache served N% of the window bytes, the
        # cold tier held the rest" on its own.
        self._tiering_source: Optional[Callable[[], Dict]] = None
        self._tiering_begin: Optional[Dict] = None
        self._tiering_end: Optional[Dict] = None
        # ddmetrics source (DDStore.metrics_snapshot — the RAW cell
        # array, not a dict: histograms delta bucket-wise, percentiles
        # don't). summary()["latency"] reports this epoch's live
        # p50/p90/p99 per (class, route, peer, tenant) with tracing
        # off — the always-on latency surface.
        self._latency_source: Optional[Callable[[], object]] = None
        self._latency_begin = None
        self._latency_end = None
        # SLO source (DDStore.slo_summary): summary()["slo"] carries
        # the monitor's per-epoch evaluation/breach deltas plus the
        # last evaluation's breach list.
        self._slo_source: Optional[Callable[[], Dict]] = None
        self._slo_begin: Optional[Dict] = None
        self._slo_end: Optional[Dict] = None
        # Serving-gateway source (DDStore.gateway_stats):
        # summary()["gateway"] carries per-epoch admission/lease deltas
        # (admitted/deferred/rejected, attach/expiry churn) with the
        # session/drain gauges live.
        self._gateway_source: Optional[Callable[[], Dict]] = None
        self._gateway_begin: Optional[Dict] = None
        self._gateway_end: Optional[Dict] = None

    def set_plan_source(self, source: Optional[Callable[[], Dict]]) -> None:
        """Attach a zero-arg callable returning cumulative planner
        counters (``DDStore.plan_stats``). Snapshotted at epoch
        boundaries; ``summary()`` reports the per-epoch delta."""
        self._plan_source = source

    def _snap_plan(self) -> Optional[Dict]:
        if self._plan_source is None:
            return None
        try:
            return dict(self._plan_source())
        except Exception:
            # A closed/torn-down store must not sink epoch accounting.
            return None

    def set_fault_source(self, source: Optional[Callable[[], Dict]]) -> None:
        """Attach a zero-arg callable returning cumulative fault/retry
        counters (``DDStore.fault_stats``). Snapshotted at epoch
        boundaries; ``summary()["faults"]`` reports the per-epoch delta
        alongside the pipeline's own degradation events."""
        self._fault_source = source

    def _snap_faults(self) -> Optional[Dict]:
        if self._fault_source is None:
            return None
        try:
            return dict(self._fault_source())
        except Exception:
            return None

    def add_fault_event(self, **counters: int) -> None:
        """Fold pipeline-level degraded-mode events into the epoch totals
        (:data:`FAULT_EVENT_KEYS`; unknown keys are rejected loudly)."""
        with self._fault_mu:
            for k, v in counters.items():
                if k not in self._fault_events:
                    raise KeyError(f"unknown fault event {k!r}; "
                                   f"expected one of {self.FAULT_EVENT_KEYS}")
                self._fault_events[k] += int(v)

    def fault_summary(self) -> Dict:
        """Per-epoch fault view: native injector/retry counter deltas
        (when a source is attached) + pipeline degradation events."""
        out: Dict = {}
        if self._fault_begin is not None:
            end = self._fault_end if self._fault_end is not None \
                else self._snap_faults()
            if end is not None:
                for k in end:
                    if k == "last_error_peer":
                        out[k] = int(end[k])
                    else:
                        # Clamped at 0: fault_configure() mid-epoch
                        # resets the process-global injector counters
                        # below the epoch baseline, and a negative
                        # "injections this epoch" is nonsense.
                        out[k] = max(0, int(end[k]) - int(
                            self._fault_begin.get(k, 0)))
        with self._fault_mu:
            out.update(self._fault_events)
        return out

    #: gauge keys of the failover source (reported raw, never delta'd —
    #: keep in sync with binding.FAILOVER_GAUGE_KEYS).
    FAILOVER_GAUGES = ("replication", "hb_active", "suspected_now")

    def set_failover_source(self,
                            source: Optional[Callable[[], Dict]]) -> None:
        """Attach a zero-arg callable returning cumulative failover /
        heartbeat counters (``DDStore.failover_stats``). Snapshotted at
        epoch boundaries; ``summary()["failover"]`` reports per-epoch
        deltas (gauges raw)."""
        self._failover_source = source

    def _snap_failover(self) -> Optional[Dict]:
        if self._failover_source is None:
            return None
        try:
            return dict(self._failover_source())
        except Exception:
            return None

    def failover_summary(self) -> Dict:
        """Per-epoch failover view: counter deltas + the live gauges."""
        out: Dict = {}
        if self._failover_begin is None:
            return out
        end = self._failover_end if self._failover_end is not None \
            else self._snap_failover()
        if end is None:
            return out
        for k in end:
            if k in self.FAILOVER_GAUGES:
                out[k] = int(end[k])
            else:
                out[k] = max(0, int(end[k]) - int(
                    self._failover_begin.get(k, 0)))
        return out

    #: gauge keys of the tenant source (reported raw, never delta'd —
    #: keep in sync with binding.TENANT_GAUGE_KEYS).
    TENANT_GAUGES = ("quota_bytes", "quota_vars", "bytes", "vars",
                     "snapshot_pins", "share")

    def set_tenant_source(self,
                          source: Optional[Callable[[], Dict]]) -> None:
        """Attach a zero-arg callable returning the per-tenant ledger
        (``DDStore.tenant_stats`` — ``{tenant: {counter: value}}``).
        Snapshotted at epoch boundaries; ``summary()["tenants"]``
        reports per-tenant per-epoch deltas (gauges raw) — how an
        epoch record proves "the capped tenant was rejected/deferred,
        the others kept their throughput" on its own."""
        self._tenant_source = source

    def _snap_tenants(self) -> Optional[Dict]:
        if self._tenant_source is None:
            return None
        try:
            return {t: dict(v) for t, v in self._tenant_source().items()}
        except Exception:
            return None

    def tenant_summary(self) -> Dict:
        """Per-epoch tenant view: counter deltas + the live gauges,
        one row per tenant (tenants appearing mid-epoch delta against
        an implicit zero baseline)."""
        out: Dict = {}
        if self._tenant_begin is None:
            return out
        end = self._tenant_end if self._tenant_end is not None \
            else self._snap_tenants()
        if end is None:
            return out
        for tenant, row in end.items():
            begin = self._tenant_begin.get(tenant, {})
            trow: Dict = {}
            for k, v in row.items():
                if k in self.TENANT_GAUGES:
                    trow[k] = int(v)
                else:
                    trow[k] = max(0, int(v) - int(begin.get(k, 0)))
            out[tenant] = trow
        return out

    #: gauge keys of the trace source (reported raw, never delta'd —
    #: keep in sync with binding.TRACE_STAT_KEYS's gauge subset plus
    #: the derived ring_occupancy); "span_latency" (a dict) also
    #: passes through live.
    TRACE_GAUGES = ("enabled", "ring_events", "threads", "capacity",
                    "live", "ring_occupancy", "flight_events")

    def set_trace_source(self, source: Optional[Callable[[], Dict]],
                         counters_source: Optional[Callable[[], Dict]]
                         = None) -> None:
        """Attach a zero-arg callable returning the ddtrace payload
        (``DDStore.trace_summary`` — monotone captured/dropped/flight/
        span counters + ring gauges + measured span-latency
        percentiles). Snapshotted at epoch boundaries;
        ``summary()["trace"]`` reports per-epoch counter deltas with
        the gauges and percentile table live. ``counters_source``, when
        given (``DDStore.trace_stats``), is used for the BEGIN
        snapshot: it only needs the counter scalars, and the full
        source's ring dump + percentile pass would run per epoch start
        for nothing."""
        self._trace_source = source
        self._trace_counters_source = counters_source or source

    def _snap_trace(self, begin: bool = False) -> Optional[Dict]:
        src = self._trace_counters_source if begin else self._trace_source
        if src is None:
            return None
        try:
            return dict(src())
        except Exception:
            return None

    def trace_summary(self) -> Dict:
        """Per-epoch trace view: events captured/dropped this epoch,
        flight-recorder activity, ring occupancy, and the measured
        per-(class, route, peer) span latency percentiles."""
        out: Dict = {}
        if self._trace_begin is None:
            return out
        end = self._trace_end if self._trace_end is not None \
            else self._snap_trace()
        if end is None:
            return out
        for k, v in end.items():
            if k in self.TRACE_GAUGES or k == "span_latency":
                out[k] = v
            else:
                out[k] = max(0, int(v) - int(self._trace_begin.get(k, 0)))
        return out

    #: gauge keys of the integrity source (reported raw, never delta'd
    #: — keep in sync with binding.INTEGRITY_GAUGE_KEYS).
    INTEGRITY_GAUGES = ("verify_mode", "sums_tables", "last_corrupt_peer")

    def set_integrity_source(self,
                             source: Optional[Callable[[], Dict]]) -> None:
        """Attach a zero-arg callable returning cumulative integrity
        counters (``DDStore.integrity_stats``). Snapshotted at epoch
        boundaries; ``summary()["integrity"]`` reports per-epoch deltas
        (gauges raw)."""
        self._integrity_source = source

    def _snap_integrity(self) -> Optional[Dict]:
        if self._integrity_source is None:
            return None
        try:
            return dict(self._integrity_source())
        except Exception:
            return None

    def integrity_summary(self) -> Dict:
        """Per-epoch integrity view: counter deltas + the live gauges."""
        out: Dict = {}
        if self._integrity_begin is None:
            return out
        end = self._integrity_end if self._integrity_end is not None \
            else self._snap_integrity()
        if end is None:
            return out
        for k in end:
            if k in self.INTEGRITY_GAUGES:
                out[k] = int(end[k])
            else:
                out[k] = max(0, int(end[k]) - int(
                    self._integrity_begin.get(k, 0)))
        return out

    #: gauge keys of the tiering source (reported raw, never delta'd —
    #: keep in sync with binding.TIERING_GAUGE_KEYS).
    TIERING_GAUGES = ("cache_max_bytes", "cache_bytes", "cache_entries",
                      "cold_vars", "cold_bytes")

    def set_tiering_source(self,
                           source: Optional[Callable[[], Dict]]) -> None:
        """Attach a zero-arg callable returning cumulative tiering
        counters (``DDStore.tiering_stats``). Snapshotted at epoch
        boundaries; ``summary()["tiering"]`` reports per-epoch deltas
        (gauges raw) plus the derived ``cache_hit_rate`` — hit bytes
        over consulted bytes, the number the tiered bench gates on."""
        self._tiering_source = source

    def _snap_tiering(self) -> Optional[Dict]:
        if self._tiering_source is None:
            return None
        try:
            return dict(self._tiering_source())
        except Exception:
            return None

    def tiering_summary(self) -> Dict:
        """Per-epoch tiering view: counter deltas + the live gauges +
        the epoch's byte-weighted cache hit rate."""
        out: Dict = {}
        if self._tiering_begin is None:
            return out
        end = self._tiering_end if self._tiering_end is not None \
            else self._snap_tiering()
        if end is None:
            return out
        for k in end:
            if k in self.TIERING_GAUGES:
                out[k] = int(end[k])
            else:
                out[k] = max(0, int(end[k]) - int(
                    self._tiering_begin.get(k, 0)))
        consulted = out.get("cache_hit_bytes", 0) + \
            out.get("cache_miss_bytes", 0)
        out["cache_hit_rate"] = round(
            out.get("cache_hit_bytes", 0) / consulted, 4) \
            if consulted else 0.0
        return out

    def set_latency_source(self,
                           source: Optional[Callable[[], object]]) \
            -> None:
        """Attach a zero-arg callable returning the live histogram
        cell array (``DDStore.metrics_snapshot``). Snapshotted at
        epoch boundaries; ``summary()["latency"]`` reports THIS
        epoch's per-cell count/mean/p50/p90/p99 (bucket-wise delta,
        then percentiles — the only order that is correct)."""
        self._latency_source = source

    def _snap_latency(self):
        if self._latency_source is None:
            return None
        try:
            return self._latency_source()
        except Exception:
            return None

    def latency_summary(self) -> Dict:
        """Per-epoch live-latency view: the epoch's histogram delta
        rendered as ``obs.latency_table`` rows keyed
        ``"class|route|peer|tenant"``."""
        if self._latency_begin is None and self._latency_source is None:
            return {}
        end = self._latency_end if self._latency_end is not None \
            else self._snap_latency()
        if end is None:
            return {}
        from ..obs import diff_metrics, latency_table

        try:
            return latency_table(diff_metrics(self._latency_begin, end))
        except Exception:
            return {}

    #: gauge keys of the SLO source (reported raw, never delta'd —
    #: keep in sync with binding.SLO_GAUGE_KEYS); "last_breaches" (a
    #: list) also passes through live.
    SLO_GAUGES = ("rules", "window_ms", "last_breach_tenant_slot")

    def set_slo_source(self,
                       source: Optional[Callable[[], Dict]]) -> None:
        """Attach a zero-arg callable returning the SLO monitor's
        payload (``DDStore.slo_summary``). Snapshotted at epoch
        boundaries; ``summary()["slo"]`` reports per-epoch
        evaluation/breach deltas with the gauges and the last breach
        list live."""
        self._slo_source = source

    def _snap_slo(self) -> Optional[Dict]:
        if self._slo_source is None:
            return None
        try:
            return dict(self._slo_source())
        except Exception:
            return None

    def slo_summary(self) -> Dict:
        """Per-epoch SLO view: evaluations/breaches this epoch plus
        the configured-rule gauges and the most recent breach list."""
        out: Dict = {}
        if self._slo_begin is None:
            return out
        end = self._slo_end if self._slo_end is not None \
            else self._snap_slo()
        if end is None:
            return out
        for k, v in end.items():
            if k in self.SLO_GAUGES or k == "last_breaches":
                out[k] = v
            else:
                out[k] = max(0, int(v) - int(self._slo_begin.get(k, 0)))
        return out

    #: gauge keys of the gateway source (reported raw, never delta'd —
    #: keep in sync with binding.GATEWAY_GAUGE_KEYS).
    GATEWAY_GAUGES = ("enabled", "sessions", "draining", "inflight",
                      "deferred_now", "last_retry_after_ms")

    def set_gateway_source(self,
                           source: Optional[Callable[[], Dict]]) -> None:
        """Attach a zero-arg callable returning the serving gateway's
        counters (``DDStore.gateway_stats``). Snapshotted at epoch
        boundaries; ``summary()["gateway"]`` reports per-epoch
        admission/lease deltas with the session and drain gauges
        live."""
        self._gateway_source = source

    def _snap_gateway(self) -> Optional[Dict]:
        if self._gateway_source is None:
            return None
        try:
            return dict(self._gateway_source())
        except Exception:
            return None

    def gateway_summary(self) -> Dict:
        """Per-epoch gateway view: attach/detach/expiry churn and
        admission verdict deltas (admitted/deferred/rejected/
        drain_sheds), plus the live session/drain gauges."""
        out: Dict = {}
        if self._gateway_begin is None:
            return out
        end = self._gateway_end if self._gateway_end is not None \
            else self._snap_gateway()
        if end is None:
            return out
        for k, v in end.items():
            if k in self.GATEWAY_GAUGES:
                out[k] = v
            else:
                out[k] = max(0, int(v) - int(self._gateway_begin.get(k, 0)))
        return out

    def set_sched_source(self, source: Optional[Callable[[], Dict]]) \
            -> None:
        """Attach a zero-arg callable returning the cost-model
        scheduler's state (``Scheduler.snapshot``): the joint plan
        (route/lanes/depth/width per class), its predicted vs measured
        throughput, the user pins and the replan triggers. Reported
        live in ``summary()["sched"]`` — the loader wires its scheduler
        in automatically."""
        self._sched_source = source

    def set_lane_source(self,
                        source: Optional[Callable[[], List[int]]]) -> None:
        """Attach a zero-arg callable returning cumulative per-lane byte
        totals (``DDStore.lane_bytes``). Snapshotted at epoch
        boundaries; ``bytes_moved()`` then carries ``lane_bytes`` (the
        per-epoch per-lane deltas), ``tcp_lanes_used`` and
        ``lane_utilization`` (delta evenness across the lanes that
        moved bytes: 1.0 = perfectly balanced stripes)."""
        self._lane_source = source

    def _snap_lanes(self) -> Optional[List[int]]:
        if self._lane_source is None:
            return None
        try:
            snap = [int(v) for v in self._lane_source()]
        except Exception:
            return None
        # A backend without lanes (the local transport) reports an
        # empty list: treat it as "no source" so its epoch records
        # don't grow dead lane keys.
        return snap or None

    def add_bytes(self, **counters: int) -> None:
        """Fold one fetch's bytes-moved ledger into the epoch totals
        (``bytes_local_get`` / ``bytes_over_ici`` / ``bytes_over_dcn``
        [+ ``rows_over_ici``] — the device-collective A/B ledger)."""
        with self._bytes_mu:
            for k, v in counters.items():
                if k not in self._bytes:
                    raise KeyError(f"unknown byte counter {k!r}; "
                                   f"expected one of {self.BYTE_KEYS}")
                self._bytes[k] += int(v)

    def bytes_moved(self) -> Dict:
        with self._bytes_mu:
            out: Dict = dict(self._bytes)
        if self._lane_begin is not None:
            # Frozen at epoch_end like the plan/fault snapshots (the
            # next epoch's readahead issuer starts prefetching before
            # the caller reads the summary — a live snapshot would leak
            # its bytes into this epoch's delta); live only mid-epoch.
            end = self._lane_end if self._lane_end is not None \
                else self._snap_lanes()
            if end is not None:
                begin = self._lane_begin
                delta = [max(0, e - (begin[i] if i < len(begin) else 0))
                         for i, e in enumerate(end)]
                used = sum(1 for d in delta if d > 0)
                peak = max(delta, default=0)
                out["lane_bytes"] = delta
                out["tcp_lanes_used"] = used
                # Evenness across the lanes that actually carried bytes:
                # balanced round-robin stripes read ~1.0; a batch that
                # fit one lane reads 1.0 with tcp_lanes_used == 1.
                out["lane_utilization"] = round(
                    sum(delta) / (used * peak), 4) if used and peak \
                    else 0.0
        return out

    def add_window(self, *, wait_s: float, idle_s: float,
                   fetch_s: float = 0.0, **counters: int) -> None:
        """Fold one readahead window's accounting into the epoch totals:
        ``wait_s`` = consumer stall on the window's fetch, ``idle_s`` =
        how long the staged window sat ready before first touch,
        ``fetch_s`` = the fetch leg's issue→completion wall time, plus
        the :data:`WINDOW_KEYS` counters (rows/dups/runs/peers/bytes)."""
        self.ra_wait.record(wait_s)
        self.ra_idle.record(idle_s)
        self.ra_fetch.record(fetch_s)
        with self._ra_mu:
            self._ra_windows += 1
            if len(self._ra_fetch_samples) < (1 << 16):
                self._ra_fetch_samples.append(
                    (int(counters.get("window_bytes", 0)), fetch_s))
            for k, v in counters.items():
                if k not in self._ra:
                    raise KeyError(f"unknown window counter {k!r}; "
                                   f"expected one of {self.WINDOW_KEYS}")
                self._ra[k] += int(v)

    def readahead_summary(self) -> Dict:
        """Per-epoch readahead view: window totals plus the derived
        per-window rates (runs/peer/window is THE transport fan-out a
        window fetch pays) and the stall/idle milliseconds."""
        with self._ra_mu:
            n = self._ra_windows
            out: Dict = {"windows": n}
            out.update(self._ra)
            samples = list(self._ra_fetch_samples)
        out["consumer_wait_ms"] = round(self.ra_wait.total * 1e3, 3)
        out["producer_idle_ms"] = round(self.ra_idle.total * 1e3, 3)
        # Transport-leg bandwidth of the window fetches themselves
        # (issue -> completion), independent of delivery/gather time.
        # The mean is the overlapped steady state (fetch competes with
        # the previous window's delivery for cores/memory bandwidth);
        # `_best` is the fastest window — typically the first of an
        # epoch, fetched with nothing else running — the uncontended
        # transport capability, measured the same way a bulk-stripe
        # benchmark is.
        out["window_fetch_gbps"] = round(
            out["window_bytes"] / self.ra_fetch.total / 1e9, 3) \
            if self.ra_fetch.total > 0 else 0.0
        best = max((b / s for b, s in samples if s > 0 and b > 0),
                   default=0.0)
        if best:
            # Per-window best: each window's OWN bytes over its own
            # fetch time (mean-bytes / min-time would overstate it
            # whenever a short trailing window posts the minimum).
            out["window_fetch_gbps_best"] = round(best / 1e9, 3)
        if n:
            out["runs_per_window"] = round(out["runs"] / n, 2)
            out["runs_per_peer_per_window"] = round(
                out["remote_runs"] / out["peer_lists"], 2) \
                if out["peer_lists"] else 0.0
            out["dedup_fraction"] = round(
                out["dup_rows"] / out["rows_requested"], 4) \
                if out["rows_requested"] else 0.0
        return out

    def epoch_start(self) -> None:
        self._t_start = time.perf_counter()
        self._plan_begin = self._snap_plan()
        self._plan_end = None
        self._fault_begin = self._snap_faults()
        self._fault_end = None
        self._failover_begin = self._snap_failover()
        self._failover_end = None
        self._tenant_begin = self._snap_tenants()
        self._tenant_end = None
        self._trace_begin = self._snap_trace(begin=True)
        self._trace_end = None
        self._integrity_begin = self._snap_integrity()
        self._integrity_end = None
        self._tiering_begin = self._snap_tiering()
        self._tiering_end = None
        self._latency_begin = self._snap_latency()
        self._latency_end = None
        self._slo_begin = self._snap_slo()
        self._slo_end = None
        self._gateway_begin = self._snap_gateway()
        self._gateway_end = None
        self._lane_begin = self._snap_lanes()
        self._lane_end = None
        with self._bytes_mu:
            self._bytes = {k: 0 for k in self.BYTE_KEYS}
        with self._ra_mu:
            self._ra = {k: 0 for k in self.WINDOW_KEYS}
            self._ra_windows = 0
            self._ra_fetch_samples = []
        with self._fault_mu:
            self._fault_events = {k: 0 for k in self.FAULT_EVENT_KEYS}
        self.ra_wait = LatencyHistogram("readahead_consumer_wait")
        self.ra_idle = LatencyHistogram("readahead_producer_idle")
        self.ra_fetch = LatencyHistogram("readahead_window_fetch")

    def epoch_end(self) -> None:
        self._t_end = time.perf_counter()
        self._plan_end = self._snap_plan()
        self._fault_end = self._snap_faults()
        self._failover_end = self._snap_failover()
        self._tenant_end = self._snap_tenants()
        self._trace_end = self._snap_trace()
        self._integrity_end = self._snap_integrity()
        self._tiering_end = self._snap_tiering()
        self._latency_end = self._snap_latency()
        self._slo_end = self._snap_slo()
        self._gateway_end = self._snap_gateway()
        self._lane_end = self._snap_lanes()

    @property
    def total_s(self) -> float:
        if self._t_start is None:
            return 0.0
        end = self._t_end if self._t_end is not None else time.perf_counter()
        return end - self._t_start

    @property
    def efficiency(self) -> float:
        total = self.total_s
        if total <= 0:
            return 1.0
        return max(0.0, 1.0 - self.wait.total / total)

    def summary(self) -> Dict:
        out = {
            "input_pipeline_efficiency": self.efficiency,
            "total_s": self.total_s,
            "device_wait": self.wait.summary(),
            "host_fetch": self.fetch.summary(),
            "device_put": self.stage.summary(),
        }
        if self._plan_begin is not None:
            # Mid-epoch summary: diff against the live counters.
            end = self._plan_end if self._plan_end is not None \
                else self._snap_plan()
            if end is not None:
                out["scatter_plan"] = plan_stats_delta(self._plan_begin, end)
        moved = self.bytes_moved()
        if any(moved.get(k, 0) for k in self.BYTE_KEYS) \
                or moved.get("tcp_lanes_used", 0):
            out["bytes_moved"] = moved
        if self._ra_windows:
            out["readahead"] = self.readahead_summary()
        faults = self.fault_summary()
        # Included whenever a fault source is wired (even all-zero: "no
        # faults this epoch" is itself the result a chaos A/B reads) or
        # any degradation event fired.
        if self._fault_begin is not None or any(faults.values()):
            out["faults"] = faults
        fo = self.failover_summary()
        # Included when replication is actually in force (an R>1 epoch
        # with zero failovers is the "nobody died" result a failover
        # A/B reads) or any failover/suspicion activity fired under R=1
        # heartbeat-only setups.
        if fo and (fo.get("replication", 1) > 1
                   or fo.get("hb_active", 0)
                   or any(v for k, v in fo.items()
                          if k not in self.FAILOVER_GAUGES)):
            out["failover"] = fo
        tn = self.tenant_summary()
        # Included when any tenant beyond the bare default is known, or
        # any tenant activity fired — a multi-tenant epoch's record
        # shows quota/QoS behavior on its own; single-tenant default
        # epochs stay unchanged.
        if tn and (set(tn) != {""} or
                   any(v for k, v in tn.get("", {}).items()
                       if k not in self.TENANT_GAUGES)):
            out["tenants"] = tn
        tr = self.trace_summary()
        # Included while tracing records (the whole payload is the
        # result a trace A/B reads) or if anything was captured this
        # epoch; untraced epochs stay byte-identical.
        if tr and (tr.get("enabled") or tr.get("captured", 0)):
            out["trace"] = tr
        ig = self.integrity_summary()
        # Included while verification/scrubbing is in force (an all-zero
        # mismatch row is the "every byte verified clean" result an
        # integrity A/B reads) or if any counter moved; unverified
        # epochs stay byte-identical.
        if ig and (ig.get("verify_mode")
                   or any(v for k, v in ig.items()
                          if k not in self.INTEGRITY_GAUGES)):
            out["integrity"] = ig
        tg = self.tiering_summary()
        # Included while the hot cache is armed or any cold-tier
        # variable is registered (an all-zero hit row is the "nothing
        # warmed this epoch" result the tiered A/B reads) or if any
        # counter moved; untiered epochs stay byte-identical.
        if tg and (tg.get("cache_max_bytes", 0) > 0
                   or tg.get("cold_vars", 0) > 0
                   or any(v for k, v in tg.items()
                          if k not in self.TIERING_GAUGES
                          and k != "cache_hit_rate")):
            out["tiering"] = tg
        lat = self.latency_summary()
        # Included whenever any cell recorded this epoch: the live
        # latency surface is THE always-on observability product —
        # absent only when metrics are disabled or nothing ran.
        if lat:
            out["latency"] = lat
        slo = self.slo_summary()
        # Included while any objective is configured (an all-zero
        # breach row is the "every tenant met its SLO" result the slo
        # bench reads) or any monitor activity fired.
        if slo and (slo.get("rules", 0) > 0
                    or slo.get("evaluations", 0)
                    or slo.get("breaches", 0)):
            out["slo"] = slo
        gw = self.gateway_summary()
        # Included while the gateway is on (an all-zero verdict row is
        # the "nothing was deferred" result the gateway bench reads) or
        # any session/admission activity fired this epoch.
        if gw and (gw.get("enabled", 0)
                   or gw.get("attaches", 0) or gw.get("admitted", 0)
                   or gw.get("deferred", 0) or gw.get("rejected", 0)):
            out["gateway"] = gw
        if self._sched_source is not None:
            # Live (not epoch-frozen): the plan is a current-state view,
            # and a disabled scheduler's {"enabled": False} is itself
            # the A/B fact the sched bench reads.
            try:
                out["sched"] = dict(self._sched_source())
            except Exception:
                pass  # a torn-down store must not sink the summary
        return out
