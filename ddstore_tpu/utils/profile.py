"""JAX profiler integration (SURVEY §5: the reference's only tracing is
commented-out printf; the rebuild pairs the host-side latency histograms
in :mod:`.metrics` with device-side traces).

``trace(logdir)`` captures a TensorBoard/XProf trace of everything inside
the block — XLA device ops, host callbacks, and any :func:`annotate`d
host-side phases — viewable with ``tensorboard --logdir`` or xprof.
``annotate(name)`` marks host-side spans (store fetches, staging) so they
line up against device activity on the trace timeline; it is a cheap
no-op when no trace is active, so the data layer can annotate
unconditionally.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

__all__ = ["trace", "annotate", "step_annotate"]


@contextlib.contextmanager
def trace(logdir: str, *, create_perfetto_link: bool = False
          ) -> Iterator[None]:
    """Capture a JAX profiler trace of the enclosed block into
    ``logdir`` (TensorBoard ``plugins/profile`` layout)."""
    import jax

    jax.profiler.start_trace(logdir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str, **kwargs):
    """Named host-side span on the profiler timeline (zero-cost when no
    trace is active). Usable as context manager or decorator."""
    import jax

    return jax.profiler.TraceAnnotation(name, **kwargs)


def step_annotate(step: int, name: str = "train_step"):
    """Step-scoped annotation: groups device ops under one training step
    in the trace viewer's step-time analysis."""
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step)
