"""Metrics, checkpointing, and small helpers."""

from .checkpoint import (load_shard, restore_train_state, save_shard,
                         save_train_state, save_train_state_async)
from .metrics import LatencyHistogram, PipelineMetrics
from .profile import annotate, step_annotate, trace

__all__ = ["LatencyHistogram", "PipelineMetrics", "save_train_state",
           "save_train_state_async",
           "restore_train_state", "save_shard", "load_shard",
           "trace", "annotate", "step_annotate"]
