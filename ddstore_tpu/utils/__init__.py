"""Metrics, logging, and small helpers."""

from .metrics import LatencyHistogram, PipelineMetrics

__all__ = ["LatencyHistogram", "PipelineMetrics"]
