"""Checkpoint / resume: train state via orbax, store shards via raw files.

The reference has no checkpointing at all — not for the store (data is
reloaded from source each run, SURVEY §5) and not for its example model.
Here both halves are covered:

* :func:`save_train_state` / :func:`restore_train_state` — any pytree of
  arrays (the models' ``TrainState`` NamedTuples) through orbax's
  StandardCheckpointer (async-safe, multihost-aware).
* :func:`save_shard` / :func:`load_shard` — a store variable's LOCAL
  shard to/from a per-rank binary file plus a JSON sidecar; restore is a
  collective ``add`` (or an mmap-backed ``add_mmap`` to come back in
  tiered mode). This turns ``init``+``update`` incremental population
  (reference ddstore.hpp:110-195) into durable resume.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import numpy as np

__all__ = ["save_train_state", "save_train_state_async",
           "restore_train_state", "save_shard", "load_shard"]


def _ckpt_path(path: str) -> str:
    return os.path.abspath(path)


def save_train_state(path: str, state: Any) -> None:
    """Write a pytree of arrays (blocking)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(_ckpt_path(path), state, force=True)


class AsyncSave:
    """Handle for an in-flight async checkpoint: ``wait()`` blocks until
    the write is durable and releases the checkpointer. The handle keeps
    the checkpointer alive — dropping it without ``wait()`` risks a
    partial checkpoint at process exit."""

    def __init__(self, ckptr):
        self._ckptr = ckptr

    def wait(self) -> None:
        if self._ckptr is not None:
            self._ckptr.wait_until_finished()
            self._ckptr.close()
            self._ckptr = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()


def save_train_state_async(path: str, state: Any) -> AsyncSave:
    """Start writing a pytree checkpoint WITHOUT blocking the train loop:
    device arrays are snapshotted to host, then serialized on background
    threads while training continues (orbax AsyncCheckpointer). Call
    ``.wait()`` (or use as a context manager) before the next save to the
    same path or before process exit."""
    import orbax.checkpoint as ocp

    ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    ckptr.save(_ckpt_path(path), args=ocp.args.StandardSave(state),
               force=True)
    return AsyncSave(ckptr)


def restore_train_state(path: str, like: Any) -> Any:
    """Read a pytree checkpoint; ``like`` supplies structure/shardings
    (pass the freshly-created state — restored arrays adopt its
    shardings, so resume works on any mesh of the same shape)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(_ckpt_path(path), target=like)


def save_shard(store, name: str, directory: str,
               chunk_rows: int = 65536) -> str:
    """Write this rank's shard of ``name`` to ``<dir>/<name>.r<rank>.bin``
    with a JSON sidecar. Local-only IO; call on every rank.

    Every rank first removes rank files in ITS directory that the
    CURRENT world cannot produce (rank index >= world). Without this, a
    save at a smaller world leaves sidecars from an earlier larger-world
    save behind, and a later resume at that larger world would silently
    mix generations: ranks whose stale file "matches" their rank load
    old bytes while the rest re-shard the new ones. Only files no live
    rank owns are unlinked (``.w*`` elastic-restore scratch is spared —
    a peer may hold a live mmap on it — and is never read by
    :func:`load_shard`), so this cannot race concurrent writes; on a
    shared dir the ranks' unlinks race only each other (idempotent).
    Caveat: with NODE-LOCAL directories, files on nodes that left the
    job can obviously not be cleaned — shrink-then-regrow resumes need
    a shared directory (or empty dirs on the regrown nodes) to be safe;
    :func:`load_shard` detects the mix via rank 0's sidecar only when
    it can see it."""
    m = store._require(name)
    begin, end = store.my_row_range(name)
    os.makedirs(directory, exist_ok=True)
    _unlink_stale(directory, name, store.world)
    stem = os.path.join(directory,
                        f"{name.replace('/', '_')}.r{store.rank}")
    with open(stem + ".bin", "wb") as f:
        for s in range(begin, end, chunk_rows):
            store.get(name, s, min(chunk_rows, end - s)).tofile(f)
    with open(stem + ".json", "w") as f:
        json.dump({"dtype": m.dtype.str, "sample_shape": list(m.sample_shape),
                   "nrows": end - begin, "rank": store.rank,
                   "world": store.world}, f)
    return stem + ".bin"


def _stem(directory: str, name: str, rank: int) -> str:
    return os.path.join(directory, f"{name.replace('/', '_')}.r{rank}")


def _unlink_stale(directory: str, name: str, world: int) -> None:
    """Remove checkpoint rank files for ``name`` with rank index >=
    ``world`` — files the current world can never rewrite. ``.w*``
    elastic-restore scratch is deliberately NOT touched: a live rank may
    be mmap-ing it as its tiered backing file (unlink under a remote
    NFS mmap risks SIGBUS), and load_shard never reads ``.w*`` paths,
    so stale ones are inert."""
    import re

    prefix = re.escape(name.replace("/", "_"))
    pat = re.compile(rf"^{prefix}\.r(\d+)\.(bin|json)$")
    for fn in os.listdir(directory):
        mm = pat.match(fn)
        if mm and int(mm.group(1)) >= world:
            try:
                os.unlink(os.path.join(directory, fn))
            except FileNotFoundError:
                pass


def load_shard(store, name: str, directory: str, *,
               mmap: bool = False, rank: Optional[int] = None) -> None:
    """Collective: re-register ``name`` from files written by
    :func:`save_shard`. ``mmap=True`` restores in tiered (file-backed,
    read-only) mode; otherwise the shard is copied back into RAM.
    ``rank`` overrides which rank's file this process loads.

    **Elastic resume**: when the checkpoint was written by a DIFFERENT
    world size (the sidecars record it), each rank re-partitions the
    saved global row space with the same ``nsplit`` rule the dataset
    layer uses and reads exactly its byte ranges out of whichever saved
    files cover them — train on 4 ranks, crash, resume on 2 (or 8) and
    every global row is served unchanged. This closes SURVEY §5's
    "elastic recovery: none". (Explicit ``rank=`` keeps the old manual
    override and skips the re-split.)
    """
    r = store.rank if rank is None else rank
    stem = _stem(directory, name, r)
    if rank is None:
        # Every sidecar records the world it was saved under. Rank 0's
        # is AUTHORITATIVE — rank 0 participates in every save, so on a
        # shared dir its sidecar is always the latest generation, while
        # this rank's own file could be a stale leftover that
        # save_shard's cleanup predates. Fall back to the own sidecar
        # only when r0's is absent (node-local, non-shared dirs).
        probe = _stem(directory, name, 0)
        if not os.path.exists(probe + ".json"):
            probe = stem
        with open(probe + ".json") as f:
            saved_world = json.load(f)["world"]
        if saved_world != store.world:
            _load_shard_resharded(store, name, directory, saved_world,
                                  mmap=mmap)
            return
    with open(stem + ".json") as f:
        meta = json.load(f)
    if rank is None and meta["world"] != store.world:
        # Own sidecar from a different generation than rank 0's: mixed
        # checkpoint directory. Refusing beats serving stale bytes.
        raise RuntimeError(
            f"{stem}.json was saved at world={meta['world']} but rank 0's"
            f" sidecar says world={store.world}: mixed checkpoint "
            f"generations in {directory}")
    dtype = np.dtype(meta["dtype"])
    sample_shape = tuple(meta["sample_shape"])
    if mmap:
        store.add_mmap(name, stem + ".bin", dtype, sample_shape)
    else:
        nrows = meta["nrows"]
        arr = (np.fromfile(stem + ".bin", dtype=dtype)
               .reshape((nrows,) + sample_shape)) if nrows else \
            np.empty((0,) + sample_shape, dtype)
        store.add(name, arr)


def _load_shard_resharded(store, name: str, directory: str,
                          saved_world: int, *, mmap: bool) -> None:
    """Re-split a saved checkpoint across the CURRENT world size: this
    rank's target row range (same near-equal contiguous split the
    dataset adapter uses) is assembled from the saved files that overlap
    it — np.memmap reads touch only the needed pages, so a resume moves
    each byte once."""
    from ..data.dataset import nsplit

    metas = []
    for i in range(saved_world):
        with open(_stem(directory, name, i) + ".json") as f:
            metas.append(json.load(f))
        if metas[-1]["world"] != saved_world:
            # A sidecar from a different save generation (e.g. a save
            # that died between ranks): assembling it with the others
            # would serve rows from two checkpoints as one dataset.
            raise RuntimeError(
                f"{_stem(directory, name, i)}.json was saved at world="
                f"{metas[-1]['world']} but rank 0's sidecar says world="
                f"{saved_world}: mixed checkpoint generations in "
                f"{directory}")
    dtype = np.dtype(metas[0]["dtype"])
    sample_shape = tuple(metas[0]["sample_shape"])
    total = sum(m["nrows"] for m in metas)
    counts = nsplit(total, store.world)
    begin = int(sum(counts[: store.rank]))
    end = begin + counts[store.rank]

    arr = np.empty((end - begin,) + sample_shape, dtype)
    file_start = 0
    for i, m in enumerate(metas):
        fs, fe = file_start, file_start + m["nrows"]
        file_start = fe
        lo, hi = max(begin, fs), min(end, fe)
        if lo >= hi:
            continue
        src = np.memmap(_stem(directory, name, i) + ".bin", dtype=dtype,
                        mode="r", shape=(m["nrows"],) + sample_shape)
        arr[lo - begin:hi - begin] = src[lo - fs:hi - fs]
        del src
    if mmap:
        # Tiered restore across a world change: the re-split rows must
        # live in ONE backing file per rank; write it next to the saved
        # ones (suffixed by the new world so reruns don't collide) and
        # map that.
        stem = _stem(directory, name, store.rank) + f".w{store.world}"
        arr.tofile(stem + ".bin")
        del arr
        store.add_mmap(name, stem + ".bin", dtype, sample_shape)
    else:
        store.add(name, arr)
