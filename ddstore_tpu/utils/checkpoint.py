"""Checkpoint / resume: train state via orbax, store shards via raw files.

The reference has no checkpointing at all — not for the store (data is
reloaded from source each run, SURVEY §5) and not for its example model.
Here both halves are covered:

* :func:`save_train_state` / :func:`restore_train_state` — any pytree of
  arrays (the models' ``TrainState`` NamedTuples) through orbax's
  StandardCheckpointer (async-safe, multihost-aware).
* :func:`save_shard` / :func:`load_shard` — a store variable's LOCAL
  shard to/from a per-rank binary file plus a JSON sidecar; restore is a
  collective ``add`` (or an mmap-backed ``add_mmap`` to come back in
  tiered mode). This turns ``init``+``update`` incremental population
  (reference ddstore.hpp:110-195) into durable resume.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import numpy as np

__all__ = ["save_train_state", "save_train_state_async",
           "restore_train_state", "save_shard", "load_shard"]


def _ckpt_path(path: str) -> str:
    return os.path.abspath(path)


def save_train_state(path: str, state: Any) -> None:
    """Write a pytree of arrays (blocking)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(_ckpt_path(path), state, force=True)


class AsyncSave:
    """Handle for an in-flight async checkpoint: ``wait()`` blocks until
    the write is durable and releases the checkpointer. The handle keeps
    the checkpointer alive — dropping it without ``wait()`` risks a
    partial checkpoint at process exit."""

    def __init__(self, ckptr):
        self._ckptr = ckptr

    def wait(self) -> None:
        if self._ckptr is not None:
            self._ckptr.wait_until_finished()
            self._ckptr.close()
            self._ckptr = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()


def save_train_state_async(path: str, state: Any) -> AsyncSave:
    """Start writing a pytree checkpoint WITHOUT blocking the train loop:
    device arrays are snapshotted to host, then serialized on background
    threads while training continues (orbax AsyncCheckpointer). Call
    ``.wait()`` (or use as a context manager) before the next save to the
    same path or before process exit."""
    import orbax.checkpoint as ocp

    ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    ckptr.save(_ckpt_path(path), args=ocp.args.StandardSave(state),
               force=True)
    return AsyncSave(ckptr)


def restore_train_state(path: str, like: Any) -> Any:
    """Read a pytree checkpoint; ``like`` supplies structure/shardings
    (pass the freshly-created state — restored arrays adopt its
    shardings, so resume works on any mesh of the same shape)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(_ckpt_path(path), target=like)


def save_shard(store, name: str, directory: str,
               chunk_rows: int = 65536) -> str:
    """Write this rank's shard of ``name`` to ``<dir>/<name>.r<rank>.bin``
    with a JSON sidecar. Local-only IO; call on every rank."""
    m = store._require(name)
    begin, end = store.my_row_range(name)
    os.makedirs(directory, exist_ok=True)
    stem = os.path.join(directory,
                        f"{name.replace('/', '_')}.r{store.rank}")
    with open(stem + ".bin", "wb") as f:
        for s in range(begin, end, chunk_rows):
            store.get(name, s, min(chunk_rows, end - s)).tofile(f)
    with open(stem + ".json", "w") as f:
        json.dump({"dtype": m.dtype.str, "sample_shape": list(m.sample_shape),
                   "nrows": end - begin, "rank": store.rank,
                   "world": store.world}, f)
    return stem + ".bin"


def load_shard(store, name: str, directory: str, *,
               mmap: bool = False, rank: Optional[int] = None) -> None:
    """Collective: re-register ``name`` from files written by
    :func:`save_shard`. ``mmap=True`` restores in tiered (file-backed,
    read-only) mode; otherwise the shard is copied back into RAM.
    ``rank`` overrides which rank's file this process loads (for
    re-sharding onto a differently-ranked relaunch)."""
    r = store.rank if rank is None else rank
    stem = os.path.join(directory, f"{name.replace('/', '_')}.r{r}")
    with open(stem + ".json") as f:
        meta = json.load(f)
    dtype = np.dtype(meta["dtype"])
    sample_shape = tuple(meta["sample_shape"])
    if mmap:
        store.add_mmap(name, stem + ".bin", dtype, sample_shape)
    else:
        nrows = meta["nrows"]
        arr = (np.fromfile(stem + ".bin", dtype=dtype)
               .reshape((nrows,) + sample_shape)) if nrows else \
            np.empty((0,) + sample_shape, dtype)
        store.add(name, arr)
