"""Overload-safe serving gateway — the client side.

The native side (native/gateway.cc) multiplexes ephemeral readers onto
each rank's existing TCP framing: a reader ``attach``es with a tenant
label over the dedicated control connection (kOpAttach), receives a
session token backed by a heartbeat-renewed lease, and its reads share
the rank's striped lane pools under the tenant's QoS budget. An
admission gate in front of Get/GetBatch/ReadRuns consults the live
latency histograms + tenant SLOs: when a protected tenant's predicted
p99 approaches its objective, over-share tenants are deferred in a
bounded queue and then refused with the non-fatal ``ERR_ADMISSION``
carrying a retry-after hint. Lease expiry — a reader SIGKILLed
mid-session, a dropped control connection — atomically releases the
session's snapshot pins, quota reservation and lane-budget share
within O(lease). ``drain()`` stops admitting, lets in-flight reads
finish under a deadline and sheds the rest.

This package is the Python session object over that machinery:
:class:`GatewaySession` attaches, renews the lease from a daemon
thread, retries ``ERR_ADMISSION`` with seeded-jitter backoff honoring
the server's retry-after hint (bounded by ``DDSTORE_GW_RETRY_MAX``),
and releases everything on ``close()``/``__exit__``. Everything is
inert unless ``DDSTORE_GATEWAY=1`` (default off: byte-, error-code-
and seeded-fault-counter-identical to the ungated tree).

Environment: ``DDSTORE_GATEWAY``, ``DDSTORE_GW_LEASE_MS``,
``DDSTORE_GW_DEFER_MS``, ``DDSTORE_GW_QUEUE``,
``DDSTORE_GW_ADMIT_MARGIN``, ``DDSTORE_GW_LANE_SHARE``,
``DDSTORE_GW_RETRY_MAX``, ``DDSTORE_SNAP_PIN_TTL_MS``. See README
"Serving gateway".
"""

from __future__ import annotations

from .session import GatewaySession

__all__ = ["GatewaySession"]
