"""Lease-renewed ephemeral reader sessions (see package docstring).

A :class:`GatewaySession` is the client half of the native gateway's
session machinery: ``attach`` places the lease (and optional snapshot
pin + quota reservation) on the serving rank over the dedicated
control connection, a daemon thread heartbeats it at ~lease/3, reads
go through the tenant-scoped view with ``ERR_ADMISSION`` retried under
seeded-jitter backoff, and ``close()`` detaches. If the process dies
instead — SIGKILL mid-read, dropped control connection — the server
side reaps the lease within O(lease) and releases the same resources,
which is the whole point: no client cleanup path is load-bearing.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Optional

import numpy as np

from ..binding import (ERR_ADMISSION, ERR_NOT_FOUND, ERR_TRANSPORT,
                       DDStoreError)

#: ERR_ADMISSION retry budget per read call (env DDSTORE_GW_RETRY_MAX;
#: the native transient ladder has its own DDSTORE_RETRY_MAX — this one
#: is the CLIENT's patience with flow control, not with failures).
_RETRY_MAX_DEFAULT = 8

#: one backoff sleep is clamped to this many ms no matter what the
#: server hints (a draining rank hints its full drain deadline).
_BACKOFF_CAP_MS = 5000


def _env_int(name: str, dflt: int) -> int:
    try:
        return int(os.environ.get(name, "") or dflt)
    except ValueError:
        return dflt


class GatewaySession:
    """One ephemeral reader's attach-read-detach lifecycle.

    Not constructed directly — use :meth:`DDStore.gateway_session`.
    Usable as a context manager; reads (:meth:`get`,
    :meth:`get_batch`) are tenant-scoped (shared default-namespace
    variables stay readable, like any :class:`TenantHandle`) and
    transparently honor the gateway's admission verdicts: a deferral
    that still ends in ``ERR_ADMISSION`` sleeps the server's
    retry-after hint with seeded jitter and retries, up to
    ``max_retries`` (env ``DDSTORE_GW_RETRY_MAX``), then surfaces the
    error with ``.retry_after_ms`` attached.

    ``snapshot=True`` asks the serving rank to hold a snapshot pin for
    the session's lifetime: the owner's copy-on-publish keeps the
    attach-time shard versions alive while this reader streams, and —
    unlike a client-held pin — the lease releases it even if the
    reader is SIGKILLed. ``quota_bytes`` reserves that much of the
    tenant's byte budget for the same lifetime."""

    def __init__(self, store, tenant: str = "", snapshot: bool = False,
                 quota_bytes: int = 0, target: int = -1,
                 max_retries: Optional[int] = None,
                 seed: Optional[int] = None,
                 lease_ms: Optional[int] = None):
        self._store = store
        self._native = store._native
        self.tenant = tenant
        self.target = int(target)
        self.max_retries = (_env_int("DDSTORE_GW_RETRY_MAX",
                                     _RETRY_MAX_DEFAULT)
                            if max_retries is None else int(max_retries))
        if lease_ms is None:
            lease_ms = _env_int("DDSTORE_GW_LEASE_MS", 5000)
        self._lease_s = max(int(lease_ms), 1) / 1000.0
        if seed is None:
            seed = _env_int("DDSTORE_FAULT_SEED", 0)
        self._rng = random.Random(int(seed))
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self.expired = False
        self.renewals_ok = 0
        self.renew_errors = 0
        self.admission_retries = 0
        self.admission_giveups = 0
        self.backoff_s = 0.0
        # Reads go through a tenant-scoped view (namespace + QoS
        # accounting); the session's snapshot pin, if any, lives
        # server-side under the lease, so the view itself is plain.
        self._view = store.attach(tenant) if tenant else store
        self.token = self._native.gateway_attach(
            target=self.target, tenant=tenant, with_snapshot=snapshot,
            quota_bytes=int(quota_bytes))
        self._renewer = threading.Thread(
            target=self._renew_loop, daemon=True,
            name=f"dds-gw-renew-{self.token:#x}")
        self._renewer.start()

    # -- lease -------------------------------------------------------------

    def _renew_loop(self) -> None:
        # Heartbeat at lease/3: the lease survives two consecutive
        # missed/failed beats, so one control-connection drop (the
        # ctrl-conndrop chaos arm) costs a retry, not the session.
        period = self._lease_s / 3.0
        while not self._stop.wait(period):
            try:
                self._native.gateway_renew(self.token, self.target)
                with self._mu:
                    self.renewals_ok += 1
            except DDStoreError as e:
                if e.code == ERR_NOT_FOUND:
                    # The server already reaped us (expiry or drain):
                    # renewing harder cannot help. Reads now race the
                    # released pins — surface via .expired/.alive.
                    with self._mu:
                        self.expired = True
                    return
                with self._mu:
                    self.renew_errors += 1
                # Transient (ERR_TRANSPORT under chaos): next beat
                # retries; the 3x margin absorbs it.
            except Exception:  # noqa: BLE001 — interpreter teardown
                return

    def renew(self) -> None:
        """One synchronous heartbeat (the deterministic test hook)."""
        self._native.gateway_renew(self.token, self.target)
        with self._mu:
            self.renewals_ok += 1

    def alive(self) -> bool:
        """False once the server reaped the lease (the daemon renewer
        learned of it) or :meth:`close` ran."""
        with self._mu:
            return not self.expired and not self._stop.is_set()

    # -- reads -------------------------------------------------------------

    def _admission_retry(self, what: str, fn):
        attempt = 0
        while True:
            try:
                return fn()
            except DDStoreError as e:
                if e.code != ERR_ADMISSION:
                    raise
                if attempt >= self.max_retries or self._stop.is_set():
                    with self._mu:
                        self.admission_giveups += 1
                    raise
                attempt += 1
                hint_ms = int(getattr(e, "retry_after_ms", 0) or 0)
                base = min(max(hint_ms, 1), _BACKOFF_CAP_MS) / 1000.0
                with self._mu:
                    self.admission_retries += 1
                    delay = base * (0.5 + self._rng.random())
                    self.backoff_s += delay
                if self._stop.wait(delay):
                    raise  # closed mid-backoff: surface the deferral

    def get(self, name: str, start: int, count: int = 1,
            out: Optional[np.ndarray] = None) -> np.ndarray:
        """Single-peer row-range read under admission control."""
        return self._admission_retry(
            f"get({name})",
            lambda: self._view.get(name, start, count, out=out))

    def get_batch(self, name: str, indices,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
        """Coalesced multi-peer batch read under admission control."""
        return self._admission_retry(
            f"get_batch({name})",
            lambda: self._view.get_batch(name, indices, out=out))

    # -- lifecycle ---------------------------------------------------------

    def stats(self) -> dict:
        """Client-side session ledger (the server-side counters live
        in ``DDStore.gateway_stats``)."""
        with self._mu:
            return {
                "token": self.token,
                "tenant": self.tenant,
                "target": self.target,
                "expired": self.expired,
                "renewals_ok": self.renewals_ok,
                "renew_errors": self.renew_errors,
                "admission_retries": self.admission_retries,
                "admission_giveups": self.admission_giveups,
                "backoff_s": self.backoff_s,
            }

    def close(self) -> None:
        """Stop the renewer and detach (idempotent). A session the
        server already reaped detaches as a no-op; an unreachable
        server (chaos) is also fine — the lease will do the cleanup."""
        if self._stop.is_set():
            return
        self._stop.set()
        if self._renewer.is_alive():
            self._renewer.join(timeout=self._lease_s)
        try:
            self._native.gateway_detach(self.token, self.target)
        except DDStoreError as e:
            if e.code not in (ERR_NOT_FOUND, ERR_TRANSPORT):
                raise
            # Already reaped (expiry beat us to it) or unreachable
            # (the reaper is the backstop) — both are clean exits.

    def __enter__(self) -> "GatewaySession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover - best-effort lease goodbye
        try:
            self.close()
        except Exception:
            pass
