"""On-demand native build for the ddstore_tpu C++ core.

Compiles ddstore_tpu/native/*.cc into a shared library with g++ the first
time the binding is imported (or whenever a source file is newer than the
cached .so). This replaces the reference's `CC=mpicc CXX=mpicxx pip install .`
requirement (/root/reference/README.md:20-32) — no MPI toolchain exists on
TPU-VM hosts, and the library must be usable from a plain checkout.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
import threading

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_LIB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_lib")
_LIB_PATH = os.path.join(_LIB_DIR, "libddstore_tpu.so")
_SOURCES = ["store.cc", "local_transport.cc", "tcp_transport.cc", "capi.cc"]
_HEADERS = ["store.h", "local_transport.h", "tcp_transport.h"]
_lock = threading.Lock()


def _stale() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for f in _SOURCES + _HEADERS:
        if os.path.getmtime(os.path.join(_NATIVE_DIR, f)) > lib_mtime:
            return True
    return False


def build(force: bool = False) -> str:
    """Returns the path to the built shared library, compiling if needed."""
    with _lock:
        if not force and not _stale():
            return _LIB_PATH
        # Installed wheels bundle the library (setup.py build_native); the
        # site-packages tree may be read-only, so fall back to the bundled
        # lib rather than insisting on a rebuild.
        if os.path.exists(_LIB_PATH) and not os.access(_LIB_DIR, os.W_OK):
            return _LIB_PATH
        os.makedirs(_LIB_DIR, exist_ok=True)
        cxx = os.environ.get("DDSTORE_CXX", "g++")
        cmd = [
            cxx, "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
            "-Wall",
        ]
        cmd += [os.path.join(_NATIVE_DIR, s) for s in _SOURCES]
        # Build to a temp path then rename: concurrent test processes may
        # race on the build, and dlopen of a half-written .so is fatal.
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_LIB_DIR)
        os.close(fd)
        try:
            subprocess.run(cmd + ["-o", tmp], check=True, capture_output=True,
                           text=True)
            os.replace(tmp, _LIB_PATH)
        except subprocess.CalledProcessError as e:  # pragma: no cover
            raise RuntimeError(
                f"native build failed:\n{e.stderr}") from e
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return _LIB_PATH
