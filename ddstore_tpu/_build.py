"""On-demand native build for the ddstore_tpu C++ core.

Compiles ddstore_tpu/native/*.cc into a shared library with g++ the first
time the binding is imported (or whenever a source file is newer than the
cached .so). This replaces the reference's `CC=mpicc CXX=mpicxx pip install .`
requirement (/root/reference/README.md:20-32) — no MPI toolchain exists on
TPU-VM hosts, and the library must be usable from a plain checkout.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
import threading

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_LIB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_lib")
_SOURCES = ["store.cc", "local_transport.cc", "tcp_transport.cc",
            "uring_transport.cc", "worker_pool.cc", "cma.cc", "fault.cc",
            "gateway.cc", "health.cc", "integrity.cc", "metrics_hist.cc",
            "tier.cc", "trace.cc", "capi.cc"]
_HEADERS = ["store.h", "local_transport.h", "tcp_transport.h",
            "uring_transport.h", "wire.h", "worker_pool.h", "cma.h",
            "fault.h", "gateway.h", "health.h", "integrity.h",
            "measure.h", "metrics_hist.h", "tier.h", "trace.h",
            "thread_annotations.h"]
_lock = threading.Lock()

# Sanitizer builds (SURVEY §5: the reference has no TSan/ASan anywhere; the
# shared_mutex-heavy core + serving threads are exactly the code that needs
# them). DDSTORE_SANITIZE=thread|address|undefined selects a
# separately-cached .so so plain and sanitized builds don't evict each
# other. `undefined` (UBSan, ISSUE 8 satellite) catches the shift/
# overflow/alignment class the wire-framing and offset arithmetic are
# full of — and unlike TSan it does not hang under this gVisor kernel.
_SANITIZERS = {"thread": "-fsanitize=thread",
               "address": "-fsanitize=address",
               "undefined": "-fsanitize=undefined"}


def _sanitize_mode() -> str:
    mode = os.environ.get("DDSTORE_SANITIZE", "").strip().lower()
    if mode and mode not in _SANITIZERS:
        raise ValueError(
            f"DDSTORE_SANITIZE={mode!r}: expected one of {set(_SANITIZERS)}")
    return mode


def _lib_path(mode: str) -> str:
    suffix = f"_{mode}" if mode else ""
    return os.path.join(_LIB_DIR, f"libddstore_tpu{suffix}.so")


def _stale(lib_path: str) -> bool:
    if not os.path.exists(lib_path):
        return True
    lib_mtime = os.path.getmtime(lib_path)
    for f in _SOURCES + _HEADERS:
        if os.path.getmtime(os.path.join(_NATIVE_DIR, f)) > lib_mtime:
            return True
    return False


def _sweep_strays(max_age_s: float = 600.0) -> None:
    """Remove leaked build-staging files (``_lib/tmp*.so``). A build
    killed between mkstemp and its cleanup leaks the staging file; a
    LIVE concurrent build's temp is at most seconds old, so anything
    older than ``max_age_s`` is provably dead. Runs at EVERY build()
    entry — including the fresh-cache early return, which is where the
    old sweep never fired and four strays accumulated (ISSUE 3)."""
    import glob
    import time as _time
    for stray in glob.glob(os.path.join(_LIB_DIR, "tmp*.so")):
        try:
            if _time.time() - os.path.getmtime(stray) > max_age_s:
                os.unlink(stray)
        except OSError:
            pass


def build(force: bool = False) -> str:
    """Returns the path to the built shared library, compiling if needed."""
    mode = _sanitize_mode()
    lib_path = _lib_path(mode)
    with _lock:
        if os.path.isdir(_LIB_DIR) and os.access(_LIB_DIR, os.W_OK):
            _sweep_strays()
        if not force and not _stale(lib_path):
            return lib_path
        # Installed wheels bundle the library (setup.py build_native); the
        # site-packages tree may be read-only, so fall back to the bundled
        # lib rather than insisting on a rebuild.
        if os.path.exists(lib_path) and not os.access(_LIB_DIR, os.W_OK):
            return lib_path
        os.makedirs(_LIB_DIR, exist_ok=True)
        cxx = os.environ.get("DDSTORE_CXX", "g++")
        cmd = [
            cxx, "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
            "-Wall",
        ]
        if mode:
            # -O1 + frame pointers give usable sanitizer reports.
            cmd += [_SANITIZERS[mode], "-O1", "-fno-omit-frame-pointer", "-g"]
        cmd += [os.path.join(_NATIVE_DIR, s) for s in _SOURCES]
        # Build to a temp path then rename: concurrent test processes may
        # race on the build, and dlopen of a half-written .so is fatal.
        # The finally below cleans the staging file on every non-killed
        # exit; _sweep_strays above catches the SIGKILL leaks.
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_LIB_DIR)
        os.close(fd)
        try:
            subprocess.run(cmd + ["-o", tmp], check=True, capture_output=True,
                           text=True)
            os.replace(tmp, lib_path)
        except subprocess.CalledProcessError as e:  # pragma: no cover
            raise RuntimeError(
                f"native build failed:\n{e.stderr}") from e
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return lib_path


def main(argv=None) -> None:
    """``python -m ddstore_tpu._build`` (or ``make native``): the
    reproducible rebuild entry — compiles iff a native source is newer
    than the cached library and prints the library path either way."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m ddstore_tpu._build",
        description="Build the native ddstore_tpu core (stale-aware).")
    ap.add_argument("--force", action="store_true",
                    help="rebuild even when the cached .so is fresh")
    args = ap.parse_args(argv)
    print(build(force=args.force))


if __name__ == "__main__":
    main()
