"""Tenant-scoped (and optionally snapshot-pinned) views over one
:class:`~ddstore_tpu.store.DDStore`.

A handle shares the parent's native store, group and rank — attaching
is a local operation (plus, for snapshots, one control round trip per
peer to place the version pins). Isolation is by construction: every
registration the handle makes is scoped to its namespace at the NATIVE
layer, and the Python boundary rejects names that could alias another
namespace (control characters), so two tenants cannot see, read,
update, or free each other's variables no matter what strings they
pass. The default namespace — variables registered through the root
``DDStore`` — stays readable from every handle: that is how an eval or
inference job attaches to the resident training shards.
"""

from __future__ import annotations

from typing import Optional

from ..binding import DDStoreError, ERR_PEER_LOST
from ..store import DDStore

#: native namespace separators. Scoped names are built HERE and only
#: parsed natively (keep in sync with native/store.cc TenantOfVarName
#: / Store::SnapVarName).
TENANT_SEP = "\x02"
SNAP_PREFIX = "\x03s\x03"

#: characters a tenant label may not contain: the native scope
#: separator plus the env-spec delimiters (DDSTORE_TENANT_QUOTAS /
#: _SHARES entries are "t=v" joined by "," with ":" inside values).
_TENANT_BADCHARS = set("=,:")


def scoped_name(tenant: str, name: str) -> str:
    """Native registry name of ``name`` in ``tenant``'s namespace
    (the default tenant ``""`` is the bare name)."""
    if not tenant:
        return name
    return f"{TENANT_SEP}{tenant}{TENANT_SEP}{name}"


def snapshot_name(snap_id: int, native_name: str) -> str:
    """Snapshot-scoped view of a native registry name: the owner
    resolves it to the primary (version unchanged) or the kept copy
    under one registry-lock acquisition."""
    return f"{SNAP_PREFIX}{snap_id}\x03{native_name}"


def _check_tenant_label(tenant: str) -> None:
    if any(ord(c) < 0x20 for c in tenant):
        raise ValueError(f"tenant label {tenant!r} contains control "
                         f"characters")
    bad = _TENANT_BADCHARS.intersection(tenant)
    if bad:
        raise ValueError(f"tenant label {tenant!r} contains reserved "
                         f"spec characters {sorted(bad)}")


class TenantHandle(DDStore):
    """A tenant's view of a shared store (see module docstring).

    Not constructed directly — use :meth:`DDStore.attach`. The handle
    inherits the full read/registration API; writes are scoped to the
    tenant's namespace, reads fall back to the shared default
    namespace, and ``snapshot=True`` makes the handle read-only with
    every read pinned to the acquire-time shard versions.

    Epoch fences are LOCAL NO-OPS on a handle: the store-global fence
    belongs to the owner job (a snapshot reader's epochs must never
    block the writer — that is the point of the snapshot)."""

    def __init__(self, parent: DDStore, tenant: str = "",
                 snapshot: bool = False):
        # Deliberately no super().__init__: the handle BORROWS the
        # parent's native store and group instead of creating its own.
        _check_tenant_label(tenant)
        self._parent = parent
        self.tenant = tenant
        self.is_snapshot = bool(snapshot)
        self.world_group = parent.world_group
        self.group = parent.group
        self.replica_id = parent.replica_id
        self.num_replicas = parent.num_replicas
        self.backend = parent.backend
        self.copy = parent.copy
        self._native = parent._native
        self._advertised = parent._advertised
        self._endpoints = parent._endpoints
        self._generation = 0
        self._peer_listeners = []
        self._known_suspects = frozenset()
        self._gid = getattr(parent, "_gid", None)
        # The default tenant's namespace IS the root registry: share the
        # parent's metadata so both views stay coherent. A named
        # tenant's namespace belongs to the TENANT, not to one handle
        # object — every handle of the tenant (snapshot readers
        # included) shares the one registry the root store keeps.
        self._meta = (parent._meta if tenant == ""
                      else parent._tenant_meta.setdefault(tenant, {}))
        self._snap_id: Optional[int] = None
        if snapshot:
            try:
                self._snap_id = self._native.snapshot_acquire(tenant)
            except DDStoreError as e:
                if e.code == ERR_PEER_LOST:
                    # Rank-by-rank pin placement met a dead peer: the
                    # native acquire UNWOUND the pins it had placed
                    # (all-or-nothing, with one retry pass per live
                    # peer) — best-effort under control-plane chaos: a
                    # pin on a live peer whose unpin failed every
                    # attempt is released when that peer's store
                    # closes. Re-attach after recovery.
                    raise DDStoreError(
                        e.code,
                        f"attach(tenant={tenant!r}, snapshot=True): a "
                        f"peer died during rank-by-rank snapshot-pin "
                        f"placement; the partially placed pins were "
                        f"unwound (best-effort on unreachable live "
                        f"peers). Recover the dead rank "
                        f"(elastic.recover), then re-attach") from None
                raise

    # -- name scoping ------------------------------------------------------

    def _wname(self, name: str) -> str:
        return scoped_name(self.tenant, name)

    def _read_tenant(self) -> str:
        # Async reads are admitted and ledgered under the READING
        # tenant, not the data's owner: an eval tenant streaming the
        # shared default-namespace dataset must burn its own QoS share,
        # not the default tenant's.
        return self.tenant

    def _rname(self, name: str) -> str:
        if name in self._meta:
            n = scoped_name(self.tenant, name)
        elif name in self._parent._meta:
            n = name  # shared default-namespace dataset (read-only view)
        else:
            raise KeyError(
                f"unknown variable {name!r} in tenant "
                f"{self.tenant!r} (cross-tenant reads are refused); "
                f"own: {sorted(self._meta)}, shared: "
                f"{sorted(self._parent._meta)}")
        if self._snap_id is not None:
            n = snapshot_name(self._snap_id, n)
        return n

    def _require(self, name: str):
        if name in self._meta:
            return self._meta[name]
        if name in self._parent._meta:
            return self._parent._meta[name]
        raise KeyError(
            f"unknown variable {name!r} in tenant {self.tenant!r} "
            f"(cross-tenant access is refused); own: "
            f"{sorted(self._meta)}, shared: "
            f"{sorted(self._parent._meta)}")

    # -- write guards ------------------------------------------------------

    def _require_writable(self, what: str) -> None:
        if self._snap_id is not None:
            raise DDStoreError(
                -1, f"{what}: snapshot handle is read-only (detach and "
                    f"re-attach without snapshot=True to write)")

    def add(self, name, arr, copy=None, readonly=False):
        self._require_writable(f"add({name})")
        super().add(name, arr, copy=copy, readonly=readonly)

    def init(self, name, nrows, sample_shape, dtype):
        self._require_writable(f"init({name})")
        super().init(name, nrows, sample_shape, dtype)

    def add_ragged(self, name, samples):
        self._require_writable(f"add_ragged({name})")
        super().add_ragged(name, samples)

    def add_mmap(self, name, path, dtype, sample_shape, mode="r"):
        self._require_writable(f"add_mmap({name})")
        super().add_mmap(name, path, dtype, sample_shape, mode=mode)

    def update(self, name, arr, row_offset=0):
        self._require_writable(f"update({name})")
        if name not in self._meta:
            raise DDStoreError(
                -1, f"update({name}): cross-tenant update refused — "
                    f"the variable is not registered in tenant "
                    f"{self.tenant!r} (shared default-namespace "
                    f"variables are writable only through their owner "
                    f"handle)")
        super().update(name, arr, row_offset)

    def spill_to_disk(self, name, directory, chunk_rows=65536):
        self._require_writable(f"spill_to_disk({name})")
        if name not in self._meta:
            raise DDStoreError(
                -1, f"spill_to_disk({name}): not a tenant "
                    f"{self.tenant!r} variable")
        return super().spill_to_disk(name, directory,
                                     chunk_rows=chunk_rows)

    def free(self, name=None):
        self._require_writable(f"free({name})")
        if name is not None and name not in self._meta:
            raise DDStoreError(
                -1, f"free({name}): cross-tenant free refused — not a "
                    f"tenant {self.tenant!r} variable")
        super().free(name)

    # -- lifecycle / sync --------------------------------------------------

    def attach(self, tenant: str = "", snapshot: bool = False):
        """Handles attach from the ROOT store (one registry of handles
        per job, not a tree)."""
        return self._parent.attach(tenant, snapshot=snapshot)

    def barrier(self) -> None:
        # One collective-tag counter per store: the parent's.
        self._parent.barrier()

    def epoch_begin(self) -> None:
        """Local no-op: the store-global epoch fence is the OWNER
        job's; an attached reader's epochs must not block the writer
        (nor trip the fence state machine)."""

    def epoch_end(self) -> None:
        """Local no-op (see epoch_begin)."""

    def detach(self) -> None:
        """Release the snapshot pins (if any) everywhere. The last
        handle pinning a kept shard version reclaims it. Idempotent;
        the handle's reads serve CURRENT bytes afterwards."""
        if self._snap_id is not None:
            sid, self._snap_id = self._snap_id, None
            self._native.snapshot_release(sid)

    def close(self) -> None:
        """Detach only — the native store belongs to the parent."""
        self.detach()

    def __exit__(self, *exc):
        self.detach()

    def __del__(self):  # pragma: no cover - best-effort pin cleanup
        try:
            self.detach()
        except Exception:
            pass
