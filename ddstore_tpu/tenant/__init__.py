"""Multi-tenant store service layer.

The store becomes a long-lived shared service: a trainer, an eval job,
and an inference reader ``attach()`` to the SAME resident shards
concurrently, each through a :class:`TenantHandle` that composes the
primitives the engine already has —

* **namespaces** over the one native variable registry (scoped
  ``"\\x02<tenant>\\x02<name>"`` names; the default tenant ``""`` is the
  bare name, keeping the whole pre-tenancy tree byte- and
  error-code-identical),
* **quotas + admission control** (a native byte/var budget checked
  atomically at registration — ``ERR_QUOTA``, a distinct non-fatal
  class — plus weighted async-admission shares on the PR 6 gate),
* **QoS lane budgets** (share-weighted caps on the striped-lane width a
  tenant's reads engage, planned by the cost-model scheduler as
  additional cells rather than a new tuner), and
* **read-only snapshot epochs** (``attach(snapshot=True)`` pins every
  shard's current content version; the owner's ``update`` + epoch fence
  publishes new versions while snapshot readers keep serving the pinned
  ones — copy-on-publish kept versions for updated shards only,
  reclaimed at last detach). This is what makes the paper's ``update``
  path a safe ONLINE write API.

Environment: ``DDSTORE_TENANT_QUOTAS="t=bytes[:vars],..."``,
``DDSTORE_TENANT_SHARES="t=weight,..."`` (runtime setters exist too).
See README "Multi-tenant service".
"""

from __future__ import annotations

from typing import Dict, Tuple

from .handle import (SNAP_PREFIX, TENANT_SEP, TenantHandle, scoped_name,
                     snapshot_name)

__all__ = ["TenantHandle", "TENANT_SEP", "SNAP_PREFIX", "scoped_name",
           "snapshot_name", "parse_quota_spec", "parse_share_spec",
           "share_split"]


def parse_quota_spec(spec: str) -> Dict[str, Tuple[int, int]]:
    """``DDSTORE_TENANT_QUOTAS`` parser (mirrors the native one):
    ``"t=bytes[:vars],..."`` -> ``{tenant: (max_bytes, max_vars)}``
    with -1 = unlimited. Malformed entries are skipped, like the
    native side — config parsing never fails construction."""
    out: Dict[str, Tuple[int, int]] = {}
    for entry in (spec or "").split(","):
        if "=" not in entry:
            continue
        tenant, _, val = entry.partition("=")
        if not tenant or any(ord(c) < 0x20 for c in tenant):
            continue  # control chars collide with the native formats
        nbytes, _, nvars = val.partition(":")
        try:
            out[tenant] = (int(nbytes), int(nvars) if nvars else -1)
        except ValueError:
            continue
    return out


def parse_share_spec(spec: str) -> Dict[str, int]:
    """``DDSTORE_TENANT_SHARES`` parser: ``"t=weight,..."`` ->
    ``{tenant: weight}`` (weights >= 1; malformed entries skipped)."""
    out: Dict[str, int] = {}
    for entry in (spec or "").split(","):
        if "=" not in entry:
            continue
        tenant, _, val = entry.partition("=")
        if not tenant or any(ord(c) < 0x20 for c in tenant):
            continue  # control chars collide with the native formats
        try:
            w = int(val)
        except ValueError:
            continue
        if w >= 1:
            out[tenant] = w
    return out


def share_split(total: int, shares: Dict[str, int]) -> Dict[str, int]:
    """Weighted split of an integer resource (async width, lane count)
    across tenants: ``max(1, total * share / sum)`` each — every tenant
    always makes progress, exactly the native admission gate's rule, so
    the planner's exported budgets and the gate's enforcement agree."""
    if not shares:
        return {}
    s = sum(shares.values()) or 1
    return {t: max(1, min(int(total), (int(total) * w) // s))
            for t, w in shares.items()}
