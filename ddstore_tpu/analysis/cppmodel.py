"""Lightweight C++ source model for the repo-native analyzer.

No libclang, no compiler: a comment/string-aware lexer plus a
brace-tracking scope walker, tuned to this codebase's idioms (Google
style, no templates-of-templates at definition sites, annotations from
``native/thread_annotations.h``). The headers' ``DDS_*`` annotations are
the ground truth the lock checker consumes; this module extracts them
together with class structure (mutex members, guarded fields, member
types, declaration order) and every function body as a token stream.

Deliberately approximate where approximation is safe: unresolvable
member accesses (iterator ``it->second`` chains, ``auto`` vars) are
skipped rather than guessed, so imprecision costs coverage, never false
positives.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

DDS_MACROS = ("DDS_GUARDED_BY", "DDS_REQUIRES", "DDS_EXCLUDES",
              "DDS_ACQUIRED_BEFORE", "DDS_NO_BLOCKING",
              "DDS_DESTROYED_BEFORE")

_TOKEN_RE = re.compile(
    r"[A-Za-z_][A-Za-z0-9_]*"          # identifier
    r"|\d[\dxXa-fA-F'.uUlLfe+-]*"      # number (loose)
    r"|::|->|<<=|>>=|<=|>=|==|!=|&&|\|\||\+\+|--|\+=|-=|\*=|/=|\|=|&="
    r"|[{}()\[\];,<>=&|!~^*/%+.?:-]"   # single-char punct
)


@dataclass
class Token:
    text: str
    line: int


def _scan_source(text: str) -> Tuple[str, List[Tuple[int, str]]]:
    """ONE comment/preprocessor/string state machine for both views of
    a C++ source: returns (stripped text, [(line, string literal)]).
    In the stripped text, comments, preprocessor lines, and string/char
    literal CONTENTS are blanked with spaces (quotes kept) — byte
    offsets and line numbers are preserved exactly. Literal values are
    captured before blanking, so the knob scanner and the lock checker
    always share one view of what is code."""
    out = list(text)
    # Blank preprocessor lines first (whole line; handles continuation).
    for m in re.finditer(r"^[ \t]*#[^\n]*(\\\n[^\n]*)*", text, re.M):
        for j in range(m.start(), m.end()):
            if out[j] != "\n":
                out[j] = " "
    text = "".join(out)
    n = len(text)
    i = 0
    line = 1
    state = "code"
    lits: List[Tuple[int, str]] = []
    cur: List[str] = []
    cur_line = 0
    while i < n:
        c = text[i]
        nxt = text[i + 1] if i + 1 < n else ""
        if c == "\n":
            line += 1
        if state == "code":
            if c == "/" and nxt == "/":
                state = "line"
                out[i] = out[i + 1] = " "
                i += 2
                continue
            if c == "/" and nxt == "*":
                state = "block"
                out[i] = out[i + 1] = " "
                i += 2
                continue
            if c == '"':
                state = "str"
                cur = []
                cur_line = line
                i += 1
                continue
            if c == "'":
                state = "chr"
                i += 1
                continue
            i += 1
            continue
        if state == "line":
            if c == "\n":
                state = "code"
            else:
                out[i] = " "
            i += 1
            continue
        if state == "block":
            if c == "*" and nxt == "/":
                out[i] = out[i + 1] = " "
                state = "code"
                i += 2
                continue
            if c != "\n":
                out[i] = " "
            i += 1
            continue
        if state in ("str", "chr"):
            quote = '"' if state == "str" else "'"
            if c == "\\":
                if state == "str":
                    cur.append(c)
                    if i + 1 < n:
                        cur.append(text[i + 1])
                out[i] = " "
                if i + 1 < n and text[i + 1] != "\n":
                    out[i + 1] = " "
                i += 2
                continue
            if c == quote:
                if state == "str":
                    lits.append((cur_line, "".join(cur)))
                state = "code"
            elif c != "\n":
                if state == "str":
                    cur.append(c)
                out[i] = " "
            i += 1
            continue
    return "".join(out), lits


def strip_comments(text: str) -> str:
    """Stripped-code view (see _scan_source)."""
    return _scan_source(text)[0]


def string_literals(text: str) -> List[Tuple[int, str]]:
    """(line, value) for every string literal in code (comments and
    preprocessor lines excluded); same state machine as
    strip_comments."""
    return _scan_source(text)[1]


def tokenize(stripped: str) -> List[Token]:
    toks = []
    line = 1
    pos = 0
    for m in _TOKEN_RE.finditer(stripped):
        line += stripped.count("\n", pos, m.start())
        pos = m.start()
        toks.append(Token(m.group(0), line))
    return toks


@dataclass
class ClassInfo:
    name: str                 # short name, e.g. "Conn"
    qual: str                 # scope path, e.g. "TcpTransport::Conn"
    file: str
    mutexes: List[str] = field(default_factory=list)
    #: field -> guard expression text (as written in the annotation)
    guarded: Dict[str, str] = field(default_factory=dict)
    no_blocking: List[str] = field(default_factory=list)
    #: mutex field -> [target exprs]
    acquired_before: Dict[str, List[str]] = field(default_factory=dict)
    #: member -> member it must be destroyed before (declared after)
    destroyed_before: Dict[str, str] = field(default_factory=dict)
    #: method -> [mutex exprs]
    requires: Dict[str, List[str]] = field(default_factory=dict)
    excludes: Dict[str, List[str]] = field(default_factory=dict)
    #: members of type std::thread / std::vector<std::thread>
    thread_members: List[str] = field(default_factory=list)
    #: member name -> declaration text (for member type resolution)
    member_types: Dict[str, str] = field(default_factory=dict)
    #: member declaration order (fields only, best effort)
    decl_order: List[str] = field(default_factory=list)


@dataclass
class FunctionInfo:
    name: str                 # unqualified
    qual: str                 # e.g. "TcpTransport::ReadVOn"
    cls: Optional[str]        # short class name context, if any
    file: str
    line: int
    body: List[Token]
    params: List[Token]
    is_ctor_dtor: bool = False


class Model:
    """Everything the detectors need, across all parsed files."""

    def __init__(self) -> None:
        self.classes: Dict[str, ClassInfo] = {}   # qual -> info
        self.functions: List[FunctionInfo] = []
        self.files: Dict[str, str] = {}           # path -> stripped text
        self.strings: Dict[str, List[Tuple[int, str]]] = {}

    # -- class lookup helpers ------------------------------------------------

    def class_by_short(self, short: str) -> Optional[ClassInfo]:
        hits = [c for c in self.classes.values() if c.name == short]
        return hits[0] if len(hits) == 1 else None

    def resolve_mutex(self, expr: str,
                      ctx: Optional[str]) -> Optional[str]:
        """Canonical mutex id ("Qual::field") for an annotation/lock
        expression, resolved against context class short name `ctx`
        first, then globally by unique match."""
        expr = expr.strip()
        if "::" in expr:
            cls_name, fld = expr.rsplit("::", 1)
            cls_name = cls_name.split("::")[-1]
            for c in self.classes.values():
                if c.name == cls_name and fld in c.mutexes:
                    return f"{c.qual}::{fld}"
            return None
        # bare name: context class chain first
        if ctx:
            chain = self._context_chain(ctx)
            for c in chain:
                if expr in c.mutexes:
                    return f"{c.qual}::{expr}"
        hits = [c for c in self.classes.values() if expr in c.mutexes]
        if len(hits) == 1:
            return f"{hits[0].qual}::{expr}"
        return None

    def _context_chain(self, short: str) -> List[ClassInfo]:
        """The class with this short name plus its enclosing classes
        (innermost first)."""
        out = []
        for c in self.classes.values():
            if c.name == short:
                out.append(c)
                parts = c.qual.split("::")[:-1]
                while parts:
                    q = "::".join(parts)
                    if q in self.classes:
                        out.append(self.classes[q])
                    parts.pop()
                break
        return out

    def mutex_no_blocking(self, mutex_id: str) -> bool:
        qual, fld = mutex_id.rsplit("::", 1)
        c = self.classes.get(qual)
        return bool(c) and fld in c.no_blocking


_CLASS_HEAD = ("class", "struct")
_SKIP_HEAD = ("enum", "union")


def parse_file(model: Model, path: str, display: str) -> None:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        raw = f.read()
    stripped, lits = _scan_source(raw)
    model.files[display] = stripped
    model.strings[display] = lits
    toks = tokenize(stripped)
    _walk(model, toks, display)


def _walk(model: Model, toks: List[Token], display: str) -> None:
    """One pass over the token stream: maintain a scope stack of
    ("ns"|"class"|"fn"|"expr", name) entries; collect class decls and
    function bodies."""
    i = 0
    n = len(toks)
    scopes: List[Tuple[str, str]] = []   # (kind, name)
    stmt: List[Token] = []               # tokens since last boundary

    def class_path() -> List[str]:
        return [name for kind, name in scopes if kind == "class"]

    while i < n:
        t = toks[i]
        if t.text == "{":
            kind, name = _classify_brace(stmt)
            if kind == "class":
                qual = "::".join(class_path() + [name])
                if qual not in model.classes:
                    model.classes[qual] = ClassInfo(name, qual, display)
                scopes.append(("class", name))
                stmt = []
                i += 1
                continue
            if kind == "fn":
                # find matching close brace, record body
                depth = 1
                j = i + 1
                while j < n and depth:
                    if toks[j].text == "{":
                        depth += 1
                    elif toks[j].text == "}":
                        depth -= 1
                    j += 1
                body = toks[i + 1:j - 1]
                fname, fcls, params = _fn_identity(stmt, class_path())
                if fname:
                    cshort = fcls
                    is_cd = bool(cshort) and (fname == cshort or
                                              fname == "~" + cshort)
                    model.functions.append(FunctionInfo(
                        fname,
                        "::".join(([] if not cshort else [cshort]) +
                                  [fname]),
                        cshort, display, t.line, body, params, is_cd))
                    # method-level annotations in the definition head
                    _fn_annotations(model, stmt, cshort, fname)
                stmt = []
                i = j
                continue
            # namespace / extern "C" / skip-scope / expr brace
            scopes.append((kind, name))
            stmt = [] if kind != "expr" else stmt
            i += 1
            continue
        if t.text == "}":
            if scopes and scopes[-1][0] == "expr":
                # initializer brace (`RouteClass r_ DDS_...(m){...}`):
                # the declaration continues to the `;` — keep the
                # statement head for _class_member.
                scopes.pop()
                i += 1
                continue
            if scopes:
                scopes.pop()
            stmt = []
            i += 1
            # swallow optional trailing `;`
            if i < n and toks[i].text == ";":
                i += 1
            continue
        if t.text == ";":
            if scopes and scopes[-1][0] == "class":
                _class_member(model, stmt,
                              "::".join(class_path()))
            stmt = []
            i += 1
            continue
        if t.text == ":" and stmt and stmt[-1].text in (
                "public", "private", "protected"):
            stmt.pop()  # access specifier, not part of a declaration
            i += 1
            continue
        stmt.append(t)
        i += 1


def _classify_brace(stmt: List[Token]) -> Tuple[str, str]:
    """What does this `{` open, judging by the statement tokens before
    it?"""
    texts = [t.text for t in stmt]
    if not texts:
        return ("expr", "")
    if "namespace" in texts or texts[0] == "extern":
        name = texts[-1] if texts[-1] != "namespace" else ""
        return ("ns", name)
    for kw in _SKIP_HEAD:
        if kw in texts:
            return ("expr", "")
    for kw in _CLASS_HEAD:
        if kw in texts:
            # `class X { ...` / `struct X : public Y {` — but NOT a
            # variable of struct type (`struct stat st;` never reaches
            # a brace). Name = identifier right after the keyword.
            k = texts.index(kw)
            if k + 1 < len(texts) and re.match(r"[A-Za-z_]\w*$",
                                               texts[k + 1]):
                return ("class", texts[k + 1])
            return ("expr", "")
    # function definition: a top-level (...) group whose opening paren
    # is preceded by a non-macro identifier, and the statement does not
    # look like an initializer (`= {`).
    if "=" in texts and texts.index("=") > 0 and "(" not in texts:
        return ("expr", "")
    name, _cls, _params = _fn_identity(stmt, [])
    if name:
        return ("fn", name)
    return ("expr", "")


def _fn_identity(stmt: List[Token], class_path: List[str]):
    """(name, class_short, params) if the statement head is a function
    definition, else (None, None, [])."""
    texts = [t.text for t in stmt]
    # locate the parameter list: the FIRST top-level paren group whose
    # preceding identifier is not an annotation macro and not a known
    # keyword; skip over trailing const/override/noexcept, annotation
    # macros, and ctor initializer lists. Parens inside template angle
    # brackets (`std::function<bool(int)>`) are NOT parameter lists —
    # track an angle depth (a `<` following an identifier opens one).
    depth = 0
    adepth = 0
    open_idx = -1
    for k, x in enumerate(texts):
        if x == "<" and k and (re.match(r"[A-Za-z_]\w*$", texts[k - 1])
                               or texts[k - 1] == ">"):
            adepth += 1
            continue
        if x == ">" and adepth > 0:
            adepth -= 1
            continue
        if adepth > 0:
            continue
        if x == "(":
            if depth == 0:
                prev = texts[k - 1] if k else ""
                if (re.match(r"[A-Za-z_]\w*$", prev)
                        and prev not in DDS_MACROS
                        and prev not in ("if", "for", "while", "switch",
                                         "return", "sizeof", "catch")):
                    open_idx = k
                    break
            depth += 1
        elif x == ")":
            depth -= 1
    if open_idx < 0:
        return (None, None, [])
    name = texts[open_idx - 1]
    # destructor?
    if open_idx >= 2 and texts[open_idx - 2] == "~":
        name = "~" + name
    cls = None
    k = open_idx - 2 - (1 if name.startswith("~") else 0)
    if k >= 1 and texts[k] == "::" and re.match(r"[A-Za-z_]\w*$",
                                                texts[k - 1]):
        cls = texts[k - 1]
    elif class_path:
        cls = class_path[-1]
    if name.startswith("~") and cls is None:
        cls = name[1:]
    # reject obvious non-definitions: control keywords as names
    if name in ("if", "for", "while", "switch", "catch"):
        return (None, None, [])
    # params: tokens inside the balanced group
    depth = 0
    params = []
    for t in stmt[open_idx:]:
        if t.text == "(":
            depth += 1
            if depth == 1:
                continue
        elif t.text == ")":
            depth -= 1
            if depth == 0:
                break
        params.append(t)
    return (name, cls, params)


def _macro_args(texts: List[str], k: int) -> List[str]:
    """Comma-split args of the macro call starting at texts[k] (the
    macro name)."""
    if k + 1 >= len(texts) or texts[k + 1] != "(":
        return []
    depth = 0
    args: List[str] = []
    cur: List[str] = []
    for x in texts[k + 1:]:
        if x == "(":
            depth += 1
            if depth == 1:
                continue
        elif x == ")":
            depth -= 1
            if depth == 0:
                break
        if x == "," and depth == 1:
            args.append("".join(cur))
            cur = []
        else:
            cur.append(x)
    if cur:
        args.append("".join(cur))
    return [a for a in (a.strip() for a in args) if a]


def _fn_annotations(model: Model, stmt: List[Token],
                    cls: Optional[str], fname: str) -> None:
    texts = [t.text for t in stmt]
    for k, x in enumerate(texts):
        if x in ("DDS_REQUIRES", "DDS_EXCLUDES") and cls:
            ci = model.class_by_short(cls)
            if ci is None:
                continue
            args = _macro_args(texts, k)
            if x == "DDS_REQUIRES":
                ci.requires.setdefault(fname, []).extend(args)
            else:
                ci.excludes.setdefault(fname, []).extend(args)


_MUTEX_TYPES = ("mutex", "shared_mutex", "recursive_mutex",
                "timed_mutex")


def _class_member(model: Model, stmt: List[Token], qual: str) -> None:
    """Process one `;`-terminated statement at class scope."""
    if not stmt or qual not in model.classes:
        return
    ci = model.classes[qual]
    texts = [t.text for t in stmt]
    # annotations present?
    macro_idx = [k for k, x in enumerate(texts) if x in DDS_MACROS]

    # Is it a method declaration? (a top-level paren group preceded by a
    # plain identifier that is not a macro) — methods carry
    # REQUIRES/EXCLUDES; fields carry the rest.
    name_m, _cls, _p = _fn_identity(stmt, [qual.split("::")[-1]])
    is_method = name_m is not None
    if is_method:
        for k in macro_idx:
            x = texts[k]
            args = _macro_args(texts, k)
            if x == "DDS_REQUIRES":
                ci.requires.setdefault(name_m, []).extend(args)
            elif x == "DDS_EXCLUDES":
                ci.excludes.setdefault(name_m, []).extend(args)
        return

    # field: name = last identifier before the first macro / `=` / end.
    stop = len(texts)
    for k in macro_idx:
        stop = min(stop, k)
    if "=" in texts:
        stop = min(stop, texts.index("="))
    fname = None
    for x in reversed(texts[:stop]):
        if re.match(r"[A-Za-z_]\w*$", x) and x not in (
                "const", "mutable", "static", "constexpr", "struct",
                "class", "volatile"):
            fname = x
            break
    if not fname:
        return
    decl_text = " ".join(texts[:stop])
    ci.member_types[fname] = decl_text
    ci.decl_order.append(fname)
    is_mutex = any(re.search(rf"(^|::|\s){mt}\s*$",
                             decl_text.rsplit(fname, 1)[0].strip())
                   for mt in _MUTEX_TYPES)
    if is_mutex:
        ci.mutexes.append(fname)
    if re.search(r"(^|\W)std\s*::\s*thread(\W|$)",
                 decl_text) or re.search(
                     r"vector\s*<\s*std\s*::\s*thread\s*>", decl_text):
        ci.thread_members.append(fname)
    for k in macro_idx:
        x = texts[k]
        args = _macro_args(texts, k)
        if x == "DDS_GUARDED_BY" and args:
            ci.guarded[fname] = args[0]
        elif x == "DDS_NO_BLOCKING":
            if fname in ci.mutexes:
                ci.no_blocking.append(fname)
        elif x == "DDS_ACQUIRED_BEFORE":
            ci.acquired_before.setdefault(fname, []).extend(args)
        elif x == "DDS_DESTROYED_BEFORE" and args:
            ci.destroyed_before[fname] = args[0]
