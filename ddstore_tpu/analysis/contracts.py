"""Python/contract lints: parallel declarations that must not drift.

The repo's most repeatable bug shape (setup.py sources in PR 4, again
mechanized in PR 5) is two lists that describe the same thing and
cannot import each other. Three instances are checked here:

``capi-binding``
    every ``dds_*`` symbol defined in ``native/capi.cc`` must be
    declared/used in ``binding.py`` and vice versa — a C export nobody
    binds is dead weight; a binding decl with no export segfaults at
    ``dlsym`` time.
``knob-registry``
    every ``DDSTORE_*`` env var read anywhere (C++ ``getenv``-family /
    pin-env string literals in ``native/``; ``os.environ`` reads in the
    Python tree) AND every one documented in README/MIGRATION must be
    a ``sched/knobs.py`` REGISTRY entry. The analyzer checks its own
    knobs by the same rule (it scans its own package too).
``tier1-skip``
    a test file marked ``tier1_required`` must contain no
    ``pytest.skip`` / ``skipif`` / ``importorskip`` path (the marker's
    whole point: a wedged accelerator can never skip these).
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Set

from .cppmodel import string_literals
from .findings import Finding

_DDS_EXPORT_RE = re.compile(r"^(?!\s)[A-Za-z_][\w\s\*]*?[\s\*]"
                            r"(dds_[a-z0-9_]+)\s*\(", re.M)
_DDS_NAME_RE = re.compile(r"\bdds_[a-z0-9_]+\b")
_KNOB_RE = re.compile(r"^DDSTORE_[A-Z0-9_]+$")


def capi_exports(capi_path: str) -> Set[str]:
    with open(capi_path) as f:
        text = f.read()
    # strip comments crudely by line (capi.cc uses // comments)
    text = re.sub(r"//[^\n]*", "", text)
    return set(_DDS_EXPORT_RE.findall(text))


def binding_decls(binding_path: str) -> Set[str]:
    """dds_* symbols binding.py actually declares or calls: attribute
    names (`lib.dds_x`) and string literals (the getattr loop's
    `"dds_epoch_begin"` style). COMMENTS are excluded — a comment
    naming a symbol must neither satisfy the parity check for a
    deleted signature nor fire a drift finding for deleted prose."""
    import io
    import tokenize as _tok
    out: Set[str] = set()
    with open(binding_path, "rb") as f:
        src = f.read()
    for tok in _tok.tokenize(io.BytesIO(src).readline):
        if tok.type == _tok.COMMENT:
            continue
        if tok.type in (_tok.NAME, _tok.STRING):
            out |= set(_DDS_NAME_RE.findall(tok.string))
    return out


def check_capi_binding(repo: str) -> List[Finding]:
    capi = os.path.join(repo, "ddstore_tpu", "native", "capi.cc")
    binding = os.path.join(repo, "ddstore_tpu", "binding.py")
    exports = capi_exports(capi)
    decls = binding_decls(binding)
    out: List[Finding] = []
    for sym in sorted(exports - decls):
        out.append(Finding(
            "capi-binding", "ddstore_tpu/native/capi.cc",
            _line_of(capi, sym), sym,
            f"capi.cc exports `{sym}` but binding.py never declares or "
            f"calls it (dead export, or a missing ctypes signature)"))
    for sym in sorted(decls - exports):
        out.append(Finding(
            "capi-binding", "ddstore_tpu/binding.py",
            _line_of(binding, sym), sym,
            f"binding.py references `{sym}` but capi.cc does not "
            f"export it (dlsym would fail at load time)"))
    return out


def _line_of(path: str, needle: str) -> int:
    """First line where `needle` appears as a whole word — substring
    matching would anchor `dds_get` at a `dds_get_batch` line."""
    pat = re.compile(rf"\b{re.escape(needle)}\b")
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if pat.search(line):
                return i
    return 0


# -- knob registry ------------------------------------------------------------

def _python_env_reads(path: str) -> List[tuple]:
    """(line, name) for every DDSTORE_* env READ in a Python file:
    os.environ[...]/.get(...), os.getenv(...), and dict-style reads of
    an env mapping. Writes (env["X"] = ...) and kwargs are excluded."""
    with open(path) as f:
        try:
            tree = ast.parse(f.read(), path)
        except SyntaxError:
            return []
    reads = []

    def knob_const(node) -> str:
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and _KNOB_RE.match(node.value):
            return node.value
        return ""

    for node in ast.walk(tree):
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load):
            name = knob_const(node.slice)
            if name:
                reads.append((node.lineno, name))
        elif isinstance(node, ast.Call):
            fname = ""
            if isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            elif isinstance(node.func, ast.Name):
                fname = node.func.id
            if fname in ("get", "getenv", "setdefault", "pop"):
                if node.args:
                    name = knob_const(node.args[0])
                    if name:
                        reads.append((node.lineno, name))
    return reads


def _cpp_knob_refs(path: str) -> List[tuple]:
    """(line, name) for every DDSTORE_* string literal in a C++ source
    — they are all env-var references in this tree (getenv/EnvLong
    arguments and RouteClass pin_env fields)."""
    with open(path) as f:
        raw = f.read()
    out = []
    for line, value in string_literals(raw):
        for m in re.finditer(r"DDSTORE_[A-Z0-9_]+", value):
            out.append((line, m.group(0)))
    return out


def _registry_for(repo: str):
    """The knob REGISTRY of the tree being analyzed. When the target
    repo carries its own ``sched/knobs.py`` (it always does for this
    repo), load THAT file — ``--repo /other/worktree`` must judge the
    other tree's getenv sites against the other tree's registry, not
    the running package's. Fallback: the installed module."""
    import sys

    from ddstore_tpu.sched import knobs as _own_knobs
    path = os.path.join(repo, "ddstore_tpu", "sched", "knobs.py")
    if not os.path.exists(path) or os.path.realpath(path) == \
            os.path.realpath(_own_knobs.__file__):
        return _own_knobs.REGISTRY
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_ddlint_target_knobs", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclass processing resolves cls.__module__ via sys.modules:
    # the module must be registered while it executes.
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop(spec.name, None)
    return mod.REGISTRY


def check_knob_registry(repo: str) -> List[Finding]:
    REGISTRY = _registry_for(repo)
    out: List[Finding] = []
    native = os.path.join(repo, "ddstore_tpu", "native")
    for fname in sorted(os.listdir(native)):
        if not (fname.endswith(".cc") or fname.endswith(".h")):
            continue
        if fname == "demo.cc":
            continue  # standalone demo binary, not linked
        rel = f"ddstore_tpu/native/{fname}"
        for line, name in _cpp_knob_refs(os.path.join(native, fname)):
            if name not in REGISTRY:
                out.append(Finding(
                    "knob-registry", rel, line, f"{name}@{fname}",
                    f"{name} referenced in native code but not in "
                    f"sched.knobs.REGISTRY — classify it as a pin of "
                    f"a planned knob or as config"))
    py_roots = ["ddstore_tpu", "bench.py", "setup.py"]
    for root in py_roots:
        path = os.path.join(repo, root)
        files = []
        if os.path.isdir(path):
            for dirpath, _dirs, names in os.walk(path):
                if "__pycache__" in dirpath or "_lib" in dirpath:
                    continue
                files += [os.path.join(dirpath, n) for n in names
                          if n.endswith(".py")]
        elif path.endswith(".py") and os.path.exists(path):
            files = [path]
        for f in sorted(files):
            rel = os.path.relpath(f, repo)
            for line, name in _python_env_reads(f):
                if name not in REGISTRY:
                    out.append(Finding(
                        "knob-registry", rel, line,
                        f"{name}@{os.path.basename(f)}",
                        f"{name} read from the environment but not in "
                        f"sched.knobs.REGISTRY"))
    # documented knobs must be registered too (moved here from
    # tests/test_sched.py so there is ONE source of truth; the test now
    # delegates to this check)
    for doc in ("README.md", "MIGRATION.md"):
        p = os.path.join(repo, doc)
        if not os.path.exists(p):
            continue
        with open(p) as f:
            for i, line in enumerate(f, 1):
                for m in re.finditer(r"DDSTORE_[A-Z0-9_]+", line):
                    if m.group(0) not in REGISTRY:
                        out.append(Finding(
                            "knob-registry", doc, i,
                            f"{m.group(0)}@{doc}",
                            f"{m.group(0)} documented in {doc} but not "
                            f"in sched.knobs.REGISTRY"))
    # dedupe per (name, file): one finding per drift site class
    seen = set()
    uniq = []
    for f in out:
        if f.key() in seen:
            continue
        seen.add(f.key())
        uniq.append(f)
    return uniq


# -- tier1_required skip paths ------------------------------------------------

def check_tier1_skips(repo: str) -> List[Finding]:
    out: List[Finding] = []
    tests = os.path.join(repo, "tests")
    if not os.path.isdir(tests):
        return out
    for fname in sorted(os.listdir(tests)):
        if not fname.startswith("test_") or not fname.endswith(".py"):
            continue
        path = os.path.join(tests, fname)
        with open(path) as f:
            try:
                tree = ast.parse(f.read(), path)
            except SyntaxError:
                continue
        if not _is_tier1_marked(tree):
            continue
        for node in ast.walk(tree):
            bad = None
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute) and fn.attr in (
                        "skip", "importorskip", "skipif"):
                    # pytest.skip(...) / pytest.importorskip(...) /
                    # pytest.mark.skipif(...)
                    bad = fn.attr
            elif isinstance(node, ast.Attribute) and node.attr in (
                    "skipif", "skip") and isinstance(
                        node.value, ast.Attribute) and \
                    node.value.attr == "mark":
                bad = node.attr
            if bad:
                out.append(Finding(
                    "tier1-skip", f"tests/{fname}", node.lineno,
                    f"{fname}@{bad}@L{node.lineno}",
                    f"{fname} is tier1_required but contains a "
                    f"`{bad}` path — tier-1 tests must always run "
                    f"(see the marker's description)"))
    return out


def _is_tier1_marked(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        # module-level `pytestmark = pytest.mark.tier1_required` (or a
        # list containing it), and per-test decorators
        if isinstance(node, ast.Attribute) and \
                node.attr == "tier1_required":
            return True
    return False
