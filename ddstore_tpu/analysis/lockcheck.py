"""Per-function lock-state tracking + the concurrency detectors.

Consumes the ``DDS_*`` annotations extracted by :mod:`cppmodel` as
ground truth and walks every function body with a scoped lock-state
machine (``lock_guard``/``unique_lock``/``shared_lock``/``scoped_lock``
RAII scopes, manual ``.lock()``/``.unlock()``, vectors of
``unique_lock``). Detector classes:

``guard``
    an annotated field touched without its guard held (and without a
    ``DDS_REQUIRES`` covering it); constructors/destructors exempt.
``blocking-under-lock``
    a blocking call (connect/poll/recv/sleep_for/Wait/getenv/...) while
    a ``DDS_NO_BLOCKING`` mutex is held.
``excludes``
    a ``DDS_EXCLUDES`` function acquiring one of its excluded mutexes
    ("never hold a data-lane mutex during Ping", mechanized).
``requires``
    a call to a ``DDS_REQUIRES`` method without the required mutex held.
``lock-order``
    a cycle in the global acquisition-order graph (edges = observed
    lexical nesting + declared ``DDS_ACQUIRED_BEFORE``).
``dtor-order``
    a ``DDS_DESTROYED_BEFORE`` member declared on the wrong side of its
    target (destruction runs in reverse declaration order), or a
    ``std::thread``(-vector) member that no function of its class ever
    joins.

Lambda semantics: a lambda body is analyzed as part of its enclosing
function but with an EMPTY lock state (it usually runs later, on
another thread), except lambdas passed directly to a condition
variable's ``wait``/``wait_for``/``wait_until``, which run under the
caller's lock and inherit it. Scope-bound helper lambdas that only run
under the enclosing lock (the transport's ``fail()`` closures) show up
as findings and are pinned in ``baseline.json`` with that reason.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .cppmodel import DDS_MACROS, FunctionInfo, Model
from .findings import Finding

#: Calls that may block (or are this repo's known blocking wrappers).
#: getenv is here deliberately: "no getenv under async_mu_ on the hot
#: path" is a pinned invariant (PR 6).
BLOCKING_CALLS = {
    # syscalls / libc
    "connect", "poll", "accept", "select", "recv", "recvmsg",
    "recvfrom", "send", "sendmsg", "sendto", "readv", "writev",
    "read", "write", "getaddrinfo", "getenv", "usleep", "nanosleep",
    "sleep", "process_vm_readv", "posix_fallocate",
    # io_uring: enter blocks when wait_nr > 0 (and may block on a full
    # SQ even without); wait_cqe is liburing vocabulary — unused here
    # (raw syscalls) but policed so a future wrapper can't slip in.
    "io_uring_enter", "io_uring_wait_cqe",
    # std::this_thread
    "sleep_for", "sleep_until",
    # repo-known blocking wrappers
    "FullSend", "FullRecv", "SendIov", "SendVec", "RecvScatter",
    "EnsureConnected", "DialWithTimeout", "ControlRoundTrip",
    "FaultSleepMs", "EnvLong", "EnvInt", "Wait", "join", "Barrier",
    "Ping", "ReadVOn", "ReadVOnRetry", "TryReadV",
    # io_uring-era blocking wrappers (uring_transport.cc)
    "SubmitAndWait", "UringReadVLocked", "ReadBatch", "EnvLongU",
}

#: condition_variable methods: the lock is (atomically) released while
#: waiting, so they are neither "blocking under the lock" nor a release
#: for guard purposes (the predicate runs with the lock re-held).
_CV_WAITS = ("wait", "wait_for", "wait_until")

_LOCK_DECLS = ("lock_guard", "scoped_lock", "unique_lock", "shared_lock")

_IDENT = re.compile(r"[A-Za-z_]\w*$")


@dataclass
class _Acq:
    mutex: str          # canonical id
    scope_depth: int
    var: Optional[str]  # unique/shared_lock variable name, if any
    released: bool = False


class _Frame:
    """Lock state for one function (or lambda) frame."""

    def __init__(self, held: Optional[List[_Acq]] = None) -> None:
        self.acqs: List[_Acq] = list(held or [])
        self.depth = 0


def _known_class_in(model: Model, type_text: str,
                    ctx: Optional[str]) -> Optional[str]:
    """Short name of a known class mentioned in a declaration's type
    text (context-local nested classes win)."""
    words = re.findall(r"[A-Za-z_]\w*", type_text)
    shorts = {c.name for c in model.classes.values()}
    if ctx:
        chain = model._context_chain(ctx)
        nested = set()
        for c in chain:
            for q, ci in model.classes.items():
                if q.startswith(c.qual + "::"):
                    nested.add(ci.name)
        for w in words:
            if w in nested:
                return w
    for w in words:
        if w in shorts and w not in ("std",):
            return w
    return None


def _var_types(model: Model, fn: FunctionInfo) -> Dict[str, str]:
    """Best-effort map of variable name -> known class short name, from
    the parameter list, local declarations, and the context class's
    member types."""
    out: Dict[str, str] = {}
    # members of the context class (and its enclosures)
    if fn.cls:
        for c in model._context_chain(fn.cls):
            for mname, decl in c.member_types.items():
                k = _known_class_in(model, decl, fn.cls)
                if k and mname not in out:
                    out[mname] = k
    # parameters + locals: scan token pairs `Type [&*] name`
    toks = fn.params + fn.body
    texts = [t.text for t in toks]
    for i, x in enumerate(texts):
        if not _IDENT.match(x):
            continue
        k = None
        # `Conn& c` / `Peer* p` / `PingConn pc` / `const Conn& c`
        if i + 2 < len(texts) and texts[i + 1] in ("&", "*") and \
                _IDENT.match(texts[i + 2]):
            k = (x, texts[i + 2])
        elif i + 1 < len(texts) and _IDENT.match(texts[i + 1]) and \
                texts[i + 1] not in ("const", "override"):
            k = (x, texts[i + 1])
        if k:
            cls = _known_class_in(model, k[0], fn.cls) \
                if k[0] not in ("return", "new", "delete") else None
            if cls and k[0] == cls and k[1] not in out:
                out[k[1]] = cls
        # `make_shared<AsyncState>(...)` assigned: `auto st = ...`
    joined = " ".join(texts)
    for m in re.finditer(
            r"(?:auto|std\s*::\s*shared_ptr\s*<[^>]*>)\s*&?\s*"
            r"([A-Za-z_]\w*)\s*=\s*std\s*::\s*make_shared\s*<\s*"
            r"([A-Za-z_]\w*)\s*>", joined):
        cls = _known_class_in(model, m.group(2), fn.cls)
        if cls:
            out[m.group(1)] = cls
    # `std::shared_ptr<AsyncState> st;` declarations
    for m in re.finditer(
            r"std\s*::\s*shared_ptr\s*<\s*([A-Za-z_]\w*)\s*>\s*&?\s*"
            r"([A-Za-z_]\w*)", joined):
        cls = _known_class_in(model, m.group(1), fn.cls)
        if cls and m.group(2) not in out:
            out[m.group(2)] = cls
    # range-for over a typed container: `for (auto& c : p.conns)`
    # resolves c via the element type of the container's declaration
    # (two passes so a base typed in pass one types its elements here).
    for _ in range(2):
        for m in re.finditer(
                r"for\s*\(\s*(?:const\s+)?auto\s*&\s*([A-Za-z_]\w*)\s*"
                r":\s*([A-Za-z_]\w*)(?:\s*(?:\.|->)\s*([A-Za-z_]\w*))?"
                r"\s*\)", joined):
            var, base, member = m.group(1), m.group(2), m.group(3)
            if var in out:
                continue
            decl = None
            if member:
                base_cls = out.get(base)
                if base_cls:
                    ci = model.class_by_short(base_cls)
                    if ci:
                        decl = ci.member_types.get(member)
            else:
                if fn.cls:
                    for c in model._context_chain(fn.cls):
                        if base in c.member_types:
                            decl = c.member_types[base]
                            break
            if decl:
                cls = _known_class_in(model, decl, fn.cls)
                if cls:
                    out[var] = cls
    return out


def _lock_target(model: Model, arg_texts: List[str], fn: FunctionInfo,
                 var_types: Dict[str, str]) -> Optional[str]:
    """Canonical mutex id of a lock-construction argument expression
    (``mu_``, ``st->mu``, ``p.cma_mu``, ``*x`` ...)."""
    # strip leading `*` / `&`
    a = [x for x in arg_texts if x not in ("*", "&")]
    if not a:
        return None
    if len(a) == 1:
        return model.resolve_mutex(a[0], fn.cls)
    # base . / -> field chains: resolve base var, take LAST field
    if a[-2] in (".", "->") and _IDENT.match(a[-1]):
        fld = a[-1]
        base = None
        for x in a[:-2]:
            if _IDENT.match(x):
                base = x  # last identifier in the base expression
        if base and base in var_types:
            cls = model.class_by_short(var_types[base])
            if cls and fld in cls.mutexes:
                return f"{cls.qual}::{fld}"
        # fall back to unique field-name match
        hits = [c for c in model.classes.values() if fld in c.mutexes]
        if len(hits) == 1:
            return f"{hits[0].qual}::{fld}"
    return None


def check_functions(model: Model) -> Tuple[List[Finding],
                                           List[Tuple[str, str, str]]]:
    """Run the per-function detectors. Returns (findings,
    observed_edges) where an edge is (held_mutex, acquired_mutex,
    site).

    Besides the purely lexical edges (locks nested inside one function
    body), a ONE-LEVEL call-graph propagation pass runs afterwards: a
    helper that takes a lock propagates that acquisition edge to its
    direct callers — ``g() { lock(A); Helper(); }`` with
    ``Helper() { lock(B); }`` records the A→B edge at g's call site,
    which purely lexical analysis misses entirely. Acquisitions a
    helper makes under its ``DDS_REQUIRES`` context are covered the
    same way (the required mutexes are modeled as held inside the
    helper, so its base-frame edges exist; the propagation adds the
    CALLER-held edges on top). Resolution is deliberately
    conservative — a typed receiver or a same-class bare call only, no
    virtual dispatch guessing — so a propagated edge is as trustworthy
    as a lexical one. One level, not transitive closure: summaries
    hold each function's OWN acquisitions only."""
    findings: List[Finding] = []
    edges: List[Tuple[str, str, str]] = []
    seen: Set[str] = set()

    def emit(cat: str, file: str, line: int, symbol: str,
             message: str) -> None:
        f = Finding(cat, file, line, symbol, message)
        if f.key() not in seen:
            seen.add(f.key())
            findings.append(f)

    # (cls-or-None, name) -> union of mutexes the function(s) acquire
    # in their own (non-lambda) frames; overloads merge conservatively.
    summaries: Dict[Tuple[Optional[str], str], Set[str]] = {}
    # call sites with locks held: (caller, callee_cls, callee, held, line)
    calls: List[Tuple[FunctionInfo, Optional[str], str,
                      "frozenset[str]", int]] = []
    for fn in model.functions:
        acquired = _check_one(model, fn, emit, edges, calls)
        summaries.setdefault((fn.cls, fn.name), set()).update(acquired)
    for caller, cls, callee, held, line in calls:
        acq = summaries.get((cls, callee))
        if not acq:
            continue
        for a in held:
            for b in acq:
                edges.append((a, b,
                              f"{caller.file}:{line} ({caller.qual} -> "
                              f"{callee}, one-level propagation)"))
    return findings, edges


def _requires_of(model: Model, fn: FunctionInfo) -> List[str]:
    if not fn.cls:
        return []
    out = []
    for c in model._context_chain(fn.cls):
        for expr in c.requires.get(fn.name, []):
            mid = model.resolve_mutex(expr, fn.cls)
            if mid:
                out.append(mid)
    return out


def _excludes_of(model: Model, fn: FunctionInfo) -> List[str]:
    if not fn.cls:
        return []
    out = []
    for c in model._context_chain(fn.cls):
        for expr in c.excludes.get(fn.name, []):
            mid = model.resolve_mutex(expr, fn.cls)
            if mid:
                out.append(mid)
    return out


def _guard_of(model: Model, cls_short: str, field: str,
              ctx: Optional[str]) -> Optional[str]:
    ci = model.class_by_short(cls_short)
    if not ci or field not in ci.guarded:
        return None
    return model.resolve_mutex(ci.guarded[field], ctx or cls_short)


def _check_one(model: Model, fn: FunctionInfo, emit, edges,
               calls=None) -> Set[str]:
    var_types = _var_types(model, fn)
    required = _requires_of(model, fn)
    excluded = set(_excludes_of(model, fn))
    base = [_Acq(m, 0, None) for m in required]
    frames: List[_Frame] = [_Frame(base)]
    # Mutexes this function acquires in its OWN (non-lambda) frames —
    # the one-level call-graph summary check_functions propagates to
    # call sites. DDS_REQUIRES mutexes are excluded: the caller holds
    # those already, they are not acquisitions of this function.
    acquired_summary: Set[str] = set()
    toks = fn.body
    texts = [t.text for t in toks]
    n = len(toks)
    # vectors of unique_lock (UpdatePeer's all-lane swap)
    lockvec_vars: Set[str] = set()
    call_stack: List[Optional[str]] = []
    lambda_stack: List[Tuple[int, int]] = []  # (frame_idx, depth_at_entry)

    def held() -> List[_Acq]:
        return [a for a in frames[-1].acqs if not a.released]

    def held_ids() -> Set[str]:
        return {a.mutex for a in held()}

    def acquire(mid: str, var: Optional[str], line: int) -> None:
        fr = frames[-1]
        for a in held():
            # a.mutex == mid records a self-edge: re-acquiring a held
            # (non-recursive) mutex is a self-deadlock, surfaced by the
            # order graph's self-loop check.
            edges.append((a.mutex, mid,
                          f"{fn.file}:{line} ({fn.qual})"))
        if mid in excluded:
            emit("excludes", fn.file, line,
                 f"{fn.qual}@{mid}",
                 f"{fn.qual} is DDS_EXCLUDES({_short(mid)}) but "
                 f"acquires it")
        if len(frames) == 1:  # not inside a deferred-execution lambda
            acquired_summary.add(mid)
        fr.acqs.append(_Acq(mid, fr.depth, var))

    i = 0
    while i < n:
        t = toks[i]
        x = t.text
        fr = frames[-1]

        # ---- scope tracking -------------------------------------------------
        if x == "{":
            fr.depth += 1
            i += 1
            continue
        if x == "}":
            fr.depth -= 1
            fr.acqs = [a for a in fr.acqs if a.scope_depth <= fr.depth]
            if lambda_stack and fr.depth < lambda_stack[-1][1]:
                lambda_stack.pop()
                frames.pop()
            i += 1
            continue

        # ---- lambda entry ---------------------------------------------------
        if x == "[" and _is_lambda_start(texts, i):
            j = _match(texts, i, "[", "]")
            # optional params
            k = j + 1
            if k < n and texts[k] == "(":
                k = _match(texts, k, "(", ")") + 1
            # skip specifiers (mutable, ->, type tokens) up to `{`
            while k < n and texts[k] != "{":
                # `;`/`)`/`,` before `{` -> not a lambda body after all
                if texts[k] in (";", ")", ","):
                    break
                k += 1
            if k < n and texts[k] == "{":
                inherits = bool(call_stack) and call_stack[-1] in _CV_WAITS
                nf = _Frame(held() if inherits else [])
                nf.depth = 0
                frames.append(nf)
                lambda_stack.append((len(frames) - 1, 1))
                nf.depth = 0
                # consume up to and including the `{`
                frames[-1].depth = 1
                i = k + 1
                continue
            i = j + 1
            continue

        # ---- call-context tracking ------------------------------------------
        if x == "(":
            prev = texts[i - 1] if i else ""
            call_stack.append(prev if _IDENT.match(prev) else None)
            i += 1
            continue
        if x == ")":
            if call_stack:
                call_stack.pop()
            i += 1
            continue

        # ---- lock declarations ----------------------------------------------
        if x in _LOCK_DECLS:
            decl = _parse_lock_decl(texts, i)
            if decl:
                var, args, end = decl
                if args is None:
                    # deferred-construction vector etc.: nothing held yet
                    i = end
                    continue
                mid = _lock_target(model, args, fn, var_types)
                if mid:
                    acquire(mid, var, toks[min(end, n - 1)].line)
                i = end
                continue
            # `std::vector<std::unique_lock<...>> locks;`
            vec = _parse_lockvec_decl(texts, i)
            if vec:
                lockvec_vars.add(vec)
            i += 1
            continue

        # ---- emplace_back on a lock vector ----------------------------------
        if x == "emplace_back" and i >= 2 and texts[i - 1] == "." and \
                texts[i - 2] in lockvec_vars:
            args, end = _call_args(texts, i + 1)
            mid = _lock_target(model, args, fn, var_types)
            if mid:
                acquire(mid, None, t.line)
            i = end
            continue

        # ---- manual lock()/unlock() on tracked vars or mutexes --------------
        if x in ("lock", "unlock") and i >= 2 and \
                texts[i - 1] in (".", "->") and \
                i + 1 < n and texts[i + 1] == "(":
            basev = texts[i - 2]
            handled = False
            for a in frames[-1].acqs:
                if a.var == basev:
                    a.released = x == "unlock"
                    handled = True
            if not handled and x == "lock":
                mid = _lock_target(model, [basev], fn, var_types)
                if mid:
                    acquire(mid, basev, t.line)
            i += 2
            continue

        # ---- calls: blocking / requires checks ------------------------------
        if _IDENT.match(x) and i + 1 < n and texts[i + 1] == "(":
            is_member_call = i >= 1 and texts[i - 1] in (".", "->")
            if is_member_call and x in _CV_WAITS:
                i += 1
                continue
            if x in BLOCKING_CALLS and x not in _LOCK_DECLS:
                for a in held():
                    if model.mutex_no_blocking(a.mutex):
                        emit("blocking-under-lock", fn.file, t.line,
                             f"{fn.qual}@{_short(a.mutex)}@{x}",
                             f"{fn.qual} calls blocking `{x}` while "
                             f"holding {_short(a.mutex)} "
                             f"(DDS_NO_BLOCKING)")
            # requires-check: method with DDS_REQUIRES called bare or
            # via a typed receiver
            req_cls = None
            if is_member_call:
                basev = _base_var(texts, i - 2)
                if basev in var_types:
                    req_cls = var_types[basev]
            else:
                req_cls = fn.cls
            # One-level call-graph propagation: record the call site
            # with the locks held RIGHT NOW; check_functions joins it
            # against the callee's acquisition summary afterwards.
            # Same conservative resolution as the requires check (a
            # typed receiver or a same-class bare call).
            if calls is not None and req_cls:
                hid = held_ids()
                if hid:
                    calls.append((fn, req_cls, x, frozenset(hid),
                                  t.line))
            if req_cls:
                for c in model._context_chain(req_cls):
                    for expr in c.requires.get(x, []):
                        mid = model.resolve_mutex(expr, req_cls)
                        if mid and mid not in held_ids():
                            emit("requires", fn.file, t.line,
                                 f"{fn.qual}@{x}@{_short(mid)}",
                                 f"{fn.qual} calls {c.name}::{x} "
                                 f"(DDS_REQUIRES({_short(mid)})) "
                                 f"without holding it")
                    if x in c.requires:
                        break

        # ---- guarded field access -------------------------------------------
        if _IDENT.match(x) and not fn.is_ctor_dtor:
            nxt = texts[i + 1] if i + 1 < n else ""
            prev = texts[i - 1] if i else ""
            if nxt not in ("::",) and prev != "::":
                owner: Optional[str] = None
                if prev in (".", "->"):
                    basev = _base_var(texts, i - 2)
                    if basev == "this":
                        owner = fn.cls
                    elif basev in var_types:
                        owner = var_types[basev]
                elif fn.cls and nxt != "(":
                    owner = fn.cls
                if owner:
                    gid = None
                    ocls = None
                    for c in (model._context_chain(owner)
                              if owner == fn.cls and prev not in
                              (".", "->") else
                              [model.class_by_short(owner)] if
                              model.class_by_short(owner) else []):
                        if x in c.guarded:
                            gid = model.resolve_mutex(c.guarded[x],
                                                      fn.cls or c.name)
                            ocls = c
                            break
                    if gid and ocls and gid not in held_ids():
                        emit("guard", fn.file, t.line,
                             f"{fn.qual}@{ocls.name}::{x}",
                             f"{fn.qual} touches {ocls.name}::{x} "
                             f"(DDS_GUARDED_BY({_short(gid)})) without "
                             f"holding it")
        i += 1
    return acquired_summary


def _short(mutex_id: str) -> str:
    parts = mutex_id.split("::")
    return "::".join(parts[-2:])


def _base_var(texts: List[str], k: int) -> str:
    """Identifier of the object expression ending at texts[k]
    (walking back over one `[...]` subscript or `(...)` group, so
    `peers_[i]->hosts` resolves to `peers_`)."""
    if k < 0:
        return ""
    if texts[k] in ("]", ")"):
        op, cl = ("[", "]") if texts[k] == "]" else ("(", ")")
        depth = 0
        while k >= 0:
            if texts[k] == cl:
                depth += 1
            elif texts[k] == op:
                depth -= 1
                if depth == 0:
                    k -= 1
                    break
            k -= 1
    return texts[k] if k >= 0 and _IDENT.match(texts[k] or "") else ""


def _is_lambda_start(texts: List[str], i: int) -> bool:
    prev = texts[i - 1] if i else ""
    if _IDENT.match(prev) or prev in (")", "]"):
        return False  # subscript
    return True


def _match(texts: List[str], i: int, op: str, cl: str) -> int:
    depth = 0
    for k in range(i, len(texts)):
        if texts[k] == op:
            depth += 1
        elif texts[k] == cl:
            depth -= 1
            if depth == 0:
                return k
    return len(texts) - 1


def _call_args(texts: List[str], open_idx: int):
    """Args tokens of the call whose `(` is at open_idx; returns
    (arg_texts, index_after_close)."""
    if open_idx >= len(texts) or texts[open_idx] != "(":
        return [], open_idx + 1
    close = _match(texts, open_idx, "(", ")")
    return texts[open_idx + 1:close], close + 1


def _parse_lock_decl(texts: List[str], i: int):
    """At texts[i] == lock_guard/unique_lock/...: parse
    `lock_guard<...> NAME(ARGS);` -> (name, args, idx_after). Returns
    (name, None, idx) for declarations without a mutex argument."""
    k = i + 1
    if k < len(texts) and texts[k] == "<":
        k = _match(texts, k, "<", ">") + 1
    if k < len(texts) and _IDENT.match(texts[k]):
        name = texts[k]
        if k + 1 < len(texts) and texts[k + 1] == "(":
            args, end = _call_args(texts, k + 1)
            # `std::adopt_lock` etc. ride along; drop trailing tag args
            args = [a for a in args
                    if a not in ("std", "adopt_lock", "defer_lock",
                                 "try_to_lock")]
            while args and args[-1] == ",":
                args.pop()
            # split on top-level comma: first arg is the mutex
            first: List[str] = []
            depth = 0
            for a in args:
                if a in ("(", "<", "["):
                    depth += 1
                elif a in (")", ">", "]"):
                    depth -= 1
                if a == "," and depth == 0:
                    break
                first.append(a)
            return (name, first, end)
        return (name, None, k + 1)
    return None


def _parse_lockvec_decl(texts: List[str], i: int) -> Optional[str]:
    """Detect `vector<std::unique_lock<...>> NAME` idiom; texts[i] is
    the unique_lock token. Walk back over the `std ::` qualifier for
    `vector <` and forward for the name."""
    j = i - 1
    while j >= 0 and texts[j] in ("::", "std"):
        j -= 1
    if j >= 1 and texts[j] == "<" and texts[j - 1] == "vector":
        k = _match(texts, j, "<", ">") + 1
        if k < len(texts) and _IDENT.match(texts[k]):
            return texts[k]
    return None


# -- lock-order graph ---------------------------------------------------------

def check_lock_order(model: Model,
                     edges: List[Tuple[str, str, str]]) -> List[Finding]:
    """Cycle detection over declared + observed acquisition-order
    edges."""
    graph: Dict[str, Dict[str, str]] = {}

    def add(a: str, b: str, site: str) -> None:
        if a == b:
            graph.setdefault(a, {}).setdefault(b, site)
            return
        graph.setdefault(a, {}).setdefault(b, site)
        graph.setdefault(b, {})

    for c in model.classes.values():
        for m, targets in c.acquired_before.items():
            src = model.resolve_mutex(m, c.name)
            for t in targets:
                dst = model.resolve_mutex(t, c.name)
                if src and dst:
                    add(src, dst, f"{c.file} (DDS_ACQUIRED_BEFORE on "
                                  f"{c.name}::{m})")
    for a, b, site in edges:
        add(a, b, site)

    findings: List[Finding] = []
    # self-loops (recursive acquisition) are cycles too
    for a, nbrs in graph.items():
        if a in nbrs:
            findings.append(Finding(
                "lock-order", _file_of(model, a), 0,
                f"cycle:{_short(a)}",
                f"{_short(a)} acquired while already held "
                f"(self-deadlock for a non-recursive mutex) at "
                f"{nbrs[a]}"))
    # Tarjan SCC
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Dict[str, bool] = {}
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(v: str) -> None:
        work = [(v, iter(graph.get(v, {})))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack[v] = True
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack[w] = True
                    work.append((w, iter(graph.get(w, {}))))
                    advanced = True
                    break
                elif onstack.get(w):
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    onstack[w] = False
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in list(graph):
        if v not in index:
            strongconnect(v)
    for scc in sccs:
        if len(scc) < 2:
            continue
        cyc = sorted(_short(m) for m in scc)
        sites = []
        sset = set(scc)
        for a in scc:
            for b, site in graph.get(a, {}).items():
                if b in sset:
                    sites.append(f"{_short(a)}->{_short(b)} at {site}")
        findings.append(Finding(
            "lock-order", _file_of(model, scc[0]), 0,
            "cycle:" + "->".join(cyc),
            "lock-acquisition-order cycle: " + "; ".join(sites)))
    return findings


def _joins_member(texts: List[str], tm: str) -> bool:
    """Does this function body join thread member `tm` — directly, via
    a std::move'd local, or via a range-for loop variable?"""
    joined = " ".join(texts)
    # locals that alias tm: `x = std::move(tm)` / `x(std::move(tm))`
    # and range-for loop vars `for (auto& x : tm)`
    aliases = {tm}
    for m in re.finditer(
            r"([A-Za-z_]\w*)\s*(?:=|\()\s*std\s*::\s*move\s*\(\s*" +
            re.escape(tm) + r"\s*\)", joined):
        aliases.add(m.group(1))
    for m in re.finditer(
            r"for\s*\(\s*(?:const\s+)?auto\s*&\s*([A-Za-z_]\w*)\s*:\s*" +
            re.escape(tm) + r"\s*\)", joined):
        aliases.add(m.group(1))
    for i, x in enumerate(texts):
        if x == "join" and i >= 2 and texts[i - 1] in (".", "->"):
            if _base_var(texts, i - 2) in aliases:
                return True
    return False


def _file_of(model: Model, mutex_id: str) -> str:
    qual = mutex_id.rsplit("::", 1)[0]
    c = model.classes.get(qual)
    return c.file if c else "<unknown>"


# -- destructor / teardown-order checks ---------------------------------------

def check_dtor_order(model: Model) -> List[Finding]:
    findings: List[Finding] = []
    for c in model.classes.values():
        for member, target in c.destroyed_before.items():
            if member not in c.decl_order or target not in c.decl_order:
                findings.append(Finding(
                    "dtor-order", c.file, 0,
                    f"{c.qual}@{member}",
                    f"DDS_DESTROYED_BEFORE({target}) on "
                    f"{c.qual}::{member}: member or target not found "
                    f"in declaration order"))
                continue
            if c.decl_order.index(member) < c.decl_order.index(target):
                findings.append(Finding(
                    "dtor-order", c.file, 0,
                    f"{c.qual}@{member}",
                    f"{c.qual}::{member} is DDS_DESTROYED_BEFORE("
                    f"{target}) but is declared BEFORE it — members "
                    f"are destroyed in reverse declaration order, so "
                    f"it must be declared after {target}"))
        # every std::thread member must be joined by some function of
        # the class — directly (`tm.join()`), after a move into a local
        # (`t = std::move(tm); ... t.join()`, HealthMonitor-style), or
        # via a range-for over a thread vector (`for (auto& t : tm)
        # t.join()`). Merely MENTIONING the member in a function that
        # joins a DIFFERENT thread does not count (a deleted join loop
        # must not stay green because the dtor still clear()s the
        # vector).
        for tm in c.thread_members:
            joined = False
            for fn in model.functions:
                if fn.cls != c.name or joined:
                    continue
                texts = [t.text for t in fn.body]
                joined = _joins_member(texts, tm)
            if not joined:
                findings.append(Finding(
                    "dtor-order", c.file, 0,
                    f"{c.qual}@{tm}",
                    f"thread member {c.qual}::{tm} is never joined by "
                    f"any function of {c.name} (destructor would "
                    f"terminate)"))
    return findings
