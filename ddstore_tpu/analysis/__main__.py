"""CLI: ``python -m ddstore_tpu.analysis`` (or ``make lint``).

Exit 0 when every finding is pinned in ``analysis/baseline.json``;
exit 1 on any NEW finding (printed with file:line anchors). This is
the same pass ``tests/test_static_analysis.py`` runs in tier-1, so a
tier-1 lint failure reproduces locally with one command.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from . import (baseline_entry, baseline_path, load_baseline, repo_root,
               run_against_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ddstore_tpu.analysis",
        description="ddlint: repo-native concurrency & contract "
                    "analyzer (lock discipline, capi/binding drift, "
                    "knob registry, tier1 skip paths)")
    ap.add_argument("--repo", default="", help="checkout root "
                    "(default: auto-detected from the package path)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="pin every CURRENT finding into "
                    "baseline.json with reason=TODO (then edit the "
                    "reasons; new findings fail until pinned)")
    ap.add_argument("--verbose", action="store_true",
                    help="also list baselined findings and stale "
                    "baseline entries")
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    new, stale, all_findings = run_against_baseline(args.repo)
    dt = time.monotonic() - t0
    bpath = baseline_path(args.repo)

    if args.write_baseline:
        baseline = load_baseline(bpath)
        entries = []
        for f in all_findings:
            prev = baseline.get(f.key())
            reason = prev["reason"] if prev and "reason" in prev \
                else "TODO: justify or fix"
            entries.append(baseline_entry(f, reason))
        with open(bpath, "w") as fh:
            json.dump({"findings": entries}, fh, indent=2,
                      sort_keys=True)
            fh.write("\n")
        print(f"{bpath}: pinned {len(entries)} finding(s)")
        return 0

    repo = args.repo or repo_root()
    print(f"ddlint: {len(all_findings)} finding(s) in {repo} "
          f"({dt:.2f}s); {len(all_findings) - len(new)} baselined, "
          f"{len(new)} new, {len(stale)} stale baseline entr"
          f"{'y' if len(stale) == 1 else 'ies'}")
    if args.verbose:
        baseline = load_baseline(bpath)
        for f in all_findings:
            if f.key() in baseline:
                print(f"  (baselined: {baseline[f.key()].get('reason')})")
                print("  " + f.render().replace("\n", "\n  "))
    for e in stale:
        print(f"  stale baseline entry (no longer fires — remove it): "
              f"{e['category']}:{e['file']}:{e['symbol']}")
    if new:
        print(f"\n{len(new)} NEW finding(s):")
        for f in new:
            print(f.render())
        print("\nFix the finding, or pin it in "
              "ddstore_tpu/analysis/baseline.json with a reason "
              "(see README \"Static analysis\").")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
