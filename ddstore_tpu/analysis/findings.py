"""Finding record + baseline handling for the static analyzer.

A finding's identity for baseline matching is ``category:file:symbol``
— deliberately NOT the line number, so pre-existing pinned findings
survive unrelated edits that shift lines. The line is carried for
humans (and asserted exact in the fixture tests, where the input is
synthetic and stable).
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass
from typing import Dict, List, Tuple


@dataclass
class Finding:
    category: str   # guard | lock-order | blocking-under-lock |
    #                 excludes | requires | dtor-order | capi-binding |
    #                 knob-registry | tier1-skip
    file: str       # repo-relative path
    line: int       # 1-based; 0 when the finding is not line-anchored
    symbol: str     # stable anchor, e.g. "TcpTransport::ReadVOn@Conn::fd"
    message: str

    def key(self) -> str:
        return f"{self.category}:{self.file}:{self.symbol}"

    def render(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        return f"[{self.category}] {loc} {self.symbol}\n    {self.message}"


def load_baseline(path: str) -> Dict[str, dict]:
    """baseline.json -> {finding key: entry}. Every entry must carry a
    `reason` — a baseline without one is itself a lint error upstream."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    out = {}
    for e in data.get("findings", []):
        key = f"{e['category']}:{e['file']}:{e['symbol']}"
        out[key] = e
    return out


def diff_baseline(findings: List[Finding], baseline: Dict[str, dict]
                  ) -> Tuple[List[Finding], List[dict]]:
    """(new findings not pinned in the baseline, stale baseline entries
    that no longer fire)."""
    keys = {f.key() for f in findings}
    new = [f for f in findings if f.key() not in baseline]
    stale = [e for k, e in baseline.items() if k not in keys]
    return new, stale


def baseline_entry(f: Finding, reason: str) -> dict:
    d = asdict(f)
    d["reason"] = reason
    return d
