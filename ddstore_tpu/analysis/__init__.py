"""ddlint — the repo-native concurrency & contract analyzer.

A deterministic, dependency-free static pass over the native layer and
the Python contract surfaces, run as a tier-1 test
(``tests/test_static_analysis.py``) and as ``make lint`` /
``python -m ddstore_tpu.analysis``. Why static: TSan hangs under this
container's gVisor kernel (pinned since PR 3) and ASan only sees
interleavings that actually ran — while the invariants this tree's
safety rests on ("never hold a data-lane mutex during Ping", "no
getenv under async_mu_", "health thread declared last = joined first",
capi exports == binding decls, every DDSTORE_* knob in REGISTRY) are
all checkable from the source alone, on every run, in seconds.

Ground truth is the ``DDS_*`` annotations in
``native/thread_annotations.h``; findings diff against the checked-in
``analysis/baseline.json`` (pre-existing violations pinned with a
reason) and anything NEW fails the pass. See README "Static analysis"
for how to read and extend the baseline.
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

from . import contracts, lockcheck
from .cppmodel import Model, parse_file
from .findings import Finding, baseline_entry, diff_baseline, load_baseline

__all__ = ["Finding", "run_all", "run_lockcheck", "run_contracts",
           "analyze_cpp", "load_baseline", "diff_baseline",
           "baseline_entry", "repo_root", "baseline_path",
           "NATIVE_SOURCES"]

#: Native translation units/headers the lock checker scans (demo.cc is
#: a standalone binary, not linked into the library).
NATIVE_SOURCES = [
    "thread_annotations.h", "measure.h", "fault.h", "health.h",
    "worker_pool.h", "store.h", "cma.h", "local_transport.h",
    "tcp_transport.h", "fault.cc", "health.cc", "worker_pool.cc",
    "store.cc", "cma.cc", "local_transport.cc", "tcp_transport.cc",
    "capi.cc",
]


def repo_root() -> str:
    """The checkout root (two levels up from this package)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def baseline_path(repo: str = "") -> str:
    """The baseline belonging to the tree being analyzed: a target
    repo's own ``ddstore_tpu/analysis/baseline.json`` when ``--repo``
    points elsewhere (findings must diff — and --write-baseline must
    write — against THAT tree's pins), else this package's."""
    if repo:
        target = os.path.join(repo, "ddstore_tpu", "analysis",
                              "baseline.json")
        if os.path.isdir(os.path.dirname(target)) and \
                os.path.abspath(os.path.dirname(target)) != \
                os.path.dirname(os.path.abspath(__file__)):
            return target
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def build_model(repo: str) -> Model:
    """Parse the native sources into one cross-file model (headers
    first so annotations exist before bodies are checked)."""
    model = Model()
    native = os.path.join(repo, "ddstore_tpu", "native")
    for fname in NATIVE_SOURCES:
        path = os.path.join(native, fname)
        if os.path.exists(path):
            parse_file(model, path, f"ddstore_tpu/native/{fname}")
    return model


def analyze_cpp(repo: str) -> List[Finding]:
    """Part A+B: annotation-checked lock discipline over the native
    layer."""
    model = build_model(repo)
    findings, edges = lockcheck.check_functions(model)
    findings += lockcheck.check_lock_order(model, edges)
    findings += lockcheck.check_dtor_order(model)
    return findings


def run_contracts(repo: str) -> List[Finding]:
    """Part C: capi<->binding parity, knob-registry drift, tier-1 skip
    paths."""
    out = contracts.check_capi_binding(repo)
    out += contracts.check_knob_registry(repo)
    out += contracts.check_tier1_skips(repo)
    return out


def run_lockcheck(repo: str) -> List[Finding]:
    return analyze_cpp(repo)


def run_all(repo: str = "") -> List[Finding]:
    repo = repo or repo_root()
    return analyze_cpp(repo) + run_contracts(repo)


def run_against_baseline(repo: str = "") -> Tuple[List[Finding],
                                                  List[dict],
                                                  List[Finding]]:
    """(new findings, stale baseline entries, all findings)."""
    repo = repo or repo_root()
    findings = run_all(repo)
    baseline = load_baseline(baseline_path(repo))
    new, stale = diff_baseline(findings, baseline)
    return new, stale, findings
