"""ctypes binding over the native store core.

The native↔Python boundary (role of the reference's Cython binding,
/root/reference/src/pyddstore.pyx:33-131): numpy buffers cross as raw
pointers with zero copies on the Python side. Unlike the reference, the
native core is dtype-agnostic (rows are byte spans), so there is no
template dispatch — dtype bookkeeping lives in the high-level
:mod:`ddstore_tpu.store` layer.

ctypes releases the GIL for the duration of every foreign call, so remote
reads, batched fetches, and barriers never block Python threads (the
serving thread is pure C++ and never touches the GIL at all — one of the
design requirements the reference sidesteps by using MPI progress).
"""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence

import numpy as np

from ._build import build

_lib: Optional[ctypes.CDLL] = None

_i64 = ctypes.c_int64
_i64p = ctypes.POINTER(ctypes.c_int64)


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(build())
    lib.dds_create_local.restype = ctypes.c_void_p
    lib.dds_create_local.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.dds_create_tcp.restype = ctypes.c_void_p
    lib.dds_create_tcp.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.dds_server_port.restype = ctypes.c_int
    lib.dds_server_port.argtypes = [ctypes.c_void_p]
    lib.dds_set_peers.restype = ctypes.c_int
    lib.dds_set_peers.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_char_p),
                                  ctypes.POINTER(ctypes.c_int), ctypes.c_int]
    lib.dds_update_peer.restype = ctypes.c_int
    lib.dds_update_peer.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.c_char_p, ctypes.c_int]
    lib.dds_barrier_seq.restype = _i64
    lib.dds_barrier_seq.argtypes = [ctypes.c_void_p]
    lib.dds_routing_state.restype = ctypes.c_int
    lib.dds_routing_state.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double), _i64p, _i64p,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    lib.dds_set_barrier_seq.restype = ctypes.c_int
    lib.dds_set_barrier_seq.argtypes = [ctypes.c_void_p, _i64]
    lib.dds_add.restype = ctypes.c_int
    lib.dds_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                            _i64, _i64, _i64, _i64p, ctypes.c_int]
    lib.dds_init.restype = ctypes.c_int
    lib.dds_init.argtypes = [ctypes.c_void_p, ctypes.c_char_p, _i64, _i64,
                             _i64, _i64p]
    lib.dds_update.restype = ctypes.c_int
    lib.dds_update.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_void_p, _i64, _i64]
    lib.dds_get.restype = ctypes.c_int
    lib.dds_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                            _i64, _i64, ctypes.c_char_p]
    lib.dds_get_batch.restype = ctypes.c_int
    lib.dds_get_batch.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_void_p, _i64p, _i64,
                                  ctypes.c_char_p]
    lib.dds_get_batch_async.restype = _i64
    lib.dds_get_batch_async.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_void_p, _i64p, _i64,
                                        ctypes.c_char_p]
    lib.dds_read_runs_async.restype = _i64
    lib.dds_read_runs_async.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_void_p, _i64p, _i64p,
                                        _i64p, _i64p, _i64,
                                        ctypes.c_char_p]
    lib.dds_async_wait.restype = ctypes.c_int
    lib.dds_async_wait.argtypes = [ctypes.c_void_p, _i64, _i64,
                                   ctypes.POINTER(ctypes.c_double)]
    lib.dds_async_release.restype = ctypes.c_int
    lib.dds_async_release.argtypes = [ctypes.c_void_p, _i64]
    lib.dds_async_pending.restype = _i64
    lib.dds_async_pending.argtypes = [ctypes.c_void_p]
    lib.dds_query.restype = ctypes.c_int
    lib.dds_query.argtypes = [ctypes.c_void_p, ctypes.c_char_p, _i64p, _i64p,
                              _i64p, _i64p]
    for fn in ("dds_epoch_begin", "dds_epoch_end", "dds_fence_reset"):
        getattr(lib, fn).restype = ctypes.c_int
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.dds_set_epoch_collective.restype = ctypes.c_int
    lib.dds_set_epoch_collective.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dds_set_ifaces.restype = ctypes.c_int
    lib.dds_set_ifaces.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dds_rebind.restype = ctypes.c_int
    lib.dds_rebind.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_void_p]
    lib.dds_free_var.restype = ctypes.c_int
    lib.dds_free_var.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dds_barrier.restype = ctypes.c_int
    lib.dds_barrier.argtypes = [ctypes.c_void_p, _i64]
    lib.dds_cma_ops.restype = _i64
    lib.dds_cma_ops.argtypes = [ctypes.c_void_p]
    lib.dds_uds_conns.restype = _i64
    lib.dds_uds_conns.argtypes = [ctypes.c_void_p]
    lib.dds_plan_stats.restype = ctypes.c_int
    lib.dds_plan_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_lane_state.restype = ctypes.c_int
    lib.dds_lane_state.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_lane_bytes.restype = ctypes.c_int
    lib.dds_lane_bytes.argtypes = [ctypes.c_void_p, ctypes.c_int, _i64p,
                                   ctypes.c_int]
    lib.dds_set_retry_deadline.restype = ctypes.c_int
    lib.dds_set_retry_deadline.argtypes = [ctypes.c_void_p,
                                           ctypes.c_double]
    lib.dds_sched_cells.restype = ctypes.c_int
    lib.dds_sched_cells.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_double),
                                    ctypes.c_int]
    lib.dds_sched_pin_route.restype = ctypes.c_int
    lib.dds_sched_pin_route.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.c_int]
    lib.dds_sched_pin_lanes.restype = ctypes.c_int
    lib.dds_sched_pin_lanes.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.c_int]
    lib.dds_set_async_width.restype = ctypes.c_int
    lib.dds_set_async_width.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dds_async_width.restype = ctypes.c_int
    lib.dds_async_width.argtypes = [ctypes.c_void_p]
    lib.dds_tenant_set_quota.restype = ctypes.c_int
    lib.dds_tenant_set_quota.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         _i64, _i64]
    lib.dds_tenant_set_share.restype = ctypes.c_int
    lib.dds_tenant_set_share.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_int]
    lib.dds_tenant_set_lane_budget.restype = ctypes.c_int
    lib.dds_tenant_set_lane_budget.argtypes = [ctypes.c_void_p,
                                               ctypes.c_char_p,
                                               ctypes.c_int]
    lib.dds_tenant_names.restype = ctypes.c_int
    lib.dds_tenant_names.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int]
    lib.dds_tenant_stats.restype = ctypes.c_int
    lib.dds_tenant_stats.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     _i64p]
    lib.dds_snapshot_acquire.restype = _i64
    lib.dds_snapshot_acquire.argtypes = [ctypes.c_void_p,
                                         ctypes.c_char_p]
    lib.dds_snapshot_release.restype = ctypes.c_int
    lib.dds_snapshot_release.argtypes = [ctypes.c_void_p, _i64]
    lib.dds_snapshot_stats.restype = ctypes.c_int
    lib.dds_snapshot_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_replication.restype = ctypes.c_int
    lib.dds_replication.argtypes = [ctypes.c_void_p]
    lib.dds_replicate.restype = ctypes.c_int
    lib.dds_replicate.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dds_refresh_mirrors.restype = ctypes.c_int
    lib.dds_refresh_mirrors.argtypes = [ctypes.c_void_p]
    lib.dds_replica_set.restype = ctypes.c_int
    lib.dds_replica_set.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.POINTER(ctypes.c_int),
                                    ctypes.c_int]
    lib.dds_health_state.restype = ctypes.c_int
    lib.dds_health_state.argtypes = [ctypes.c_void_p, _i64p, ctypes.c_int]
    lib.dds_heartbeat_configure.restype = ctypes.c_int
    lib.dds_heartbeat_configure.argtypes = [ctypes.c_void_p,
                                            ctypes.c_long, ctypes.c_int]
    lib.dds_mark_suspect.restype = ctypes.c_int
    lib.dds_mark_suspect.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_int]
    lib.dds_failover_stats.restype = ctypes.c_int
    lib.dds_failover_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_fault_configure.restype = ctypes.c_int
    lib.dds_fault_configure.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                        ctypes.c_char_p]
    lib.dds_fault_stats.restype = ctypes.c_int
    lib.dds_fault_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_integrity_configure.restype = ctypes.c_int
    lib.dds_integrity_configure.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                            ctypes.c_long]
    lib.dds_integrity_stats.restype = ctypes.c_int
    lib.dds_integrity_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_integrity_sums.restype = ctypes.c_int
    lib.dds_integrity_sums.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       _i64, _i64,
                                       ctypes.POINTER(ctypes.c_uint64),
                                       _i64p]
    lib.dds_integrity_scrub.restype = ctypes.c_int
    lib.dds_integrity_scrub.argtypes = [ctypes.c_void_p]
    lib.dds_tier_configure.restype = ctypes.c_int
    lib.dds_tier_configure.argtypes = [ctypes.c_void_p, _i64]
    lib.dds_set_var_tier.restype = ctypes.c_int
    lib.dds_set_var_tier.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int]
    lib.dds_var_tier.restype = ctypes.c_int
    lib.dds_var_tier.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dds_set_tier_placement.restype = ctypes.c_int
    lib.dds_set_tier_placement.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p, ctypes.c_int]
    lib.dds_cache_prefetch.restype = _i64
    lib.dds_cache_prefetch.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       _i64p, _i64, _i64, ctypes.c_char_p]
    lib.dds_cache_evict.restype = ctypes.c_int
    lib.dds_cache_evict.argtypes = [ctypes.c_void_p, _i64]
    lib.dds_tiering_stats.restype = ctypes.c_int
    lib.dds_tiering_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_create_uring.restype = ctypes.c_void_p
    lib.dds_create_uring.argtypes = [ctypes.c_int, ctypes.c_int,
                                     ctypes.c_int]
    lib.dds_uring_probe.restype = ctypes.c_int
    lib.dds_uring_probe.argtypes = [_i64p]
    lib.dds_uring_probe_reason.restype = ctypes.c_int
    lib.dds_uring_probe_reason.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.dds_uring_state.restype = ctypes.c_int
    lib.dds_uring_state.argtypes = [ctypes.c_void_p]
    lib.dds_uring_reason.restype = ctypes.c_int
    lib.dds_uring_reason.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int]
    lib.dds_uring_stats.restype = ctypes.c_int
    lib.dds_uring_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_cold_direct_stats.restype = ctypes.c_int
    lib.dds_cold_direct_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_set_var_file.restype = ctypes.c_int
    lib.dds_set_var_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_char_p]
    lib.dds_req_send_stats.restype = ctypes.c_int
    lib.dds_req_send_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_metrics_configure.restype = ctypes.c_int
    lib.dds_metrics_configure.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dds_metrics_enabled.restype = ctypes.c_int
    lib.dds_metrics_enabled.argtypes = [ctypes.c_void_p]
    lib.dds_metrics_reset.restype = ctypes.c_int
    lib.dds_metrics_reset.argtypes = [ctypes.c_void_p]
    lib.dds_metrics_snapshot.restype = _i64
    lib.dds_metrics_snapshot.argtypes = [ctypes.c_void_p,
                                         ctypes.c_void_p, _i64]
    lib.dds_metrics_pull.restype = _i64
    lib.dds_metrics_pull.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_void_p, _i64]
    lib.dds_metrics_stats.restype = ctypes.c_int
    lib.dds_metrics_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_metrics_tenants.restype = ctypes.c_int
    lib.dds_metrics_tenants.argtypes = [ctypes.c_void_p,
                                        ctypes.c_char_p, ctypes.c_int]
    lib.dds_metrics_record.restype = ctypes.c_int
    lib.dds_metrics_record.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int, ctypes.c_int,
                                       ctypes.c_char_p, _i64, _i64]
    lib.dds_slo_configure.restype = ctypes.c_int
    lib.dds_slo_configure.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dds_slo_evaluate.restype = _i64
    lib.dds_slo_evaluate.argtypes = [ctypes.c_void_p, _i64p,
                                     ctypes.c_int]
    lib.dds_slo_stats.restype = ctypes.c_int
    lib.dds_slo_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_gateway_configure.restype = ctypes.c_int
    lib.dds_gateway_configure.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.c_long, ctypes.c_long,
                                          ctypes.c_int, ctypes.c_int,
                                          ctypes.c_int, ctypes.c_long]
    lib.dds_gateway_attach.restype = _i64
    lib.dds_gateway_attach.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_char_p, ctypes.c_int,
                                       _i64]
    lib.dds_gateway_renew.restype = ctypes.c_int
    lib.dds_gateway_renew.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                      _i64]
    lib.dds_gateway_detach.restype = ctypes.c_int
    lib.dds_gateway_detach.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       _i64]
    lib.dds_gateway_drain.restype = ctypes.c_int
    lib.dds_gateway_drain.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.dds_gateway_reap.restype = ctypes.c_int
    lib.dds_gateway_reap.argtypes = [ctypes.c_void_p]
    lib.dds_gateway_stats.restype = ctypes.c_int
    lib.dds_gateway_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.dds_trace_configure.restype = ctypes.c_int
    lib.dds_trace_configure.argtypes = [ctypes.c_int, ctypes.c_long]
    lib.dds_trace_enabled.restype = ctypes.c_int
    lib.dds_trace_enabled.argtypes = []
    lib.dds_trace_reset.restype = ctypes.c_int
    lib.dds_trace_reset.argtypes = []
    lib.dds_trace_emit.restype = ctypes.c_int
    lib.dds_trace_emit.argtypes = [ctypes.c_uint32, ctypes.c_uint64,
                                   ctypes.c_int, _i64, _i64, _i64]
    lib.dds_trace_new_span.restype = ctypes.c_uint64
    lib.dds_trace_new_span.argtypes = [ctypes.c_int]
    lib.dds_trace_flight.restype = ctypes.c_int
    lib.dds_trace_flight.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.dds_trace_dump.restype = _i64
    lib.dds_trace_dump.argtypes = [ctypes.c_void_p, _i64]
    lib.dds_trace_flight_dump.restype = _i64
    lib.dds_trace_flight_dump.argtypes = [ctypes.c_void_p, _i64]
    lib.dds_trace_stats.restype = ctypes.c_int
    lib.dds_trace_stats.argtypes = [_i64p]
    lib.dds_rank.restype = ctypes.c_int
    lib.dds_rank.argtypes = [ctypes.c_void_p]
    lib.dds_world.restype = ctypes.c_int
    lib.dds_world.argtypes = [ctypes.c_void_p]
    lib.dds_destroy.restype = None
    lib.dds_destroy.argtypes = [ctypes.c_void_p]
    lib.dds_release_local_group.restype = None
    lib.dds_release_local_group.argtypes = [ctypes.c_char_p]
    lib.dds_error_string.restype = ctypes.c_char_p
    lib.dds_error_string.argtypes = [ctypes.c_int]
    lib.dds_owner_of.restype = ctypes.c_int
    lib.dds_owner_of.argtypes = [_i64p, ctypes.c_int, _i64]
    _lib = lib
    return lib


# Error codes tested by the Python-side classification (mirrors
# dds::ErrorCode; see native/store.h).
ERR_INVALID_ARG = -1  # bad name / shape / range / tier
ERR_NOT_FOUND = -2   # unknown variable / expired gateway lease token
ERR_TRANSPORT = -6   # transient-class transport failure
ERR_PEER_LOST = -10  # transient-retry budget exhausted: owner presumed
#                      dead — fatal, invoke elastic.recover
ERR_QUOTA = -11      # tenant byte/var budget exhausted at registration:
#                      admission refused — nothing died, free variables
#                      or raise the quota (distinct from ERR_PEER_LOST)
ERR_CORRUPT = -12    # data integrity failure (DDSTORE_VERIFY=1): the
#                      delivered bytes disagree with the owner's
#                      published checksums at a stable content version
#                      on every readable holder — non-fatal like
#                      ERR_QUOTA (nothing died; the store's bytes may
#                      be fine and only one holder rotten — inspect
#                      integrity_stats()["last_corrupt_peer"])
ERR_ADMISSION = -13  # serving-gateway admission refusal: over-share
#                      tenant deferred past its window (or the rank is
#                      draining) — non-fatal, defer-not-peer-lost; the
#                      gateway's last_retry_after_ms stat carries the
#                      back-off hint (seeded-jitter retry, then give up)


class DDStoreError(RuntimeError):
    """Raised when the native core reports an error (maps the C error codes
    the way the reference surfaces C++ throws through Cython ``except +``,
    pyddstore.pyx:44-50)."""

    def __init__(self, code: int, context: str = ""):
        self.code = code
        msg = _load().dds_error_string(code).decode()
        super().__init__(f"{context}: {msg}" if context else msg)


def _check(code: int, context: str = "") -> None:
    if code != 0:
        raise DDStoreError(code, context)


def owner_of(cum: Sequence[int], row: int) -> int:
    """Owner rank of global row `row` given cumulative row counts."""
    arr = np.ascontiguousarray(cum, dtype=np.int64)
    return _load().dds_owner_of(arr.ctypes.data_as(_i64p), len(arr), row)


def fault_configure(spec: str, seed: int = 0,
                    ranks: Optional[Sequence[int]] = None) -> None:
    """(Re)configure the process-global deterministic fault injector —
    the runtime equivalent of ``DDSTORE_FAULT_SPEC``/``_SEED``/``_RANKS``.

    ``spec`` is ``kind:probability[:param_ms]`` entries joined by commas
    (data kinds: ``reset``, ``trunc``, ``delay``, ``stall``,
    ``corrupt``; control-plane kinds: ``ctrl-reset``, ``ctrl-delay``,
    ``ctrl-stall`` — these target the request/response control ops and
    draw from their OWN seeded counter domain, so data-plane schedules
    are bit-identical with the ctrl arm present or absent); an empty
    spec disables injection. ``ranks`` restricts injection to ops
    SERVED by those ranks (per-peer fault schedules in shared-process
    tests). Resets every injector counter including both draw
    counters, so the same ``(spec, seed)`` replays the same fault
    schedule."""
    ranks_csv = ",".join(str(int(r)) for r in ranks) if ranks else ""
    _check(_load().dds_fault_configure(spec.encode(), int(seed),
                                       ranks_csv.encode()),
           f"fault_configure({spec!r})")


#: Default transient-retry deadline seconds when DDSTORE_OP_DEADLINE_S
#: is unset — keep in sync with the native RetryPolicy default in
#: fault.cc (the readahead degraded path derives its shared-budget math
#: from this; drift would silently hand refetches the wrong base).
DEFAULT_OP_DEADLINE_S = 300.0


# -- ddtrace: event-ring tracing + flight recorder ---------------------------
#
# Process-global like the fault injector (rings belong to THREADS, and a
# ThreadGroup test's in-process "ranks" share one trace — every event
# carries its emitting rank). All decode tables here mirror native
# enums/layouts in native/trace.h; drift breaks the dump format.

#: numpy layout of one dumped trace event (keep in sync with
#: trace.h `Event` — 48 packed bytes).
TRACE_EVENT_DTYPE = np.dtype([
    ("t_ns", "<u8"), ("span", "<u8"), ("type", "<u2"), ("tid", "<u2"),
    ("rank", "<i4"), ("a", "<i8"), ("b", "<i8"), ("c", "<i8")])

#: event-type decode table (trace.h EventType).
TRACE_TYPES = {
    1: "op_begin", 2: "op_end", 3: "retry", 4: "backoff",
    5: "lane_dial", 6: "lane_close", 7: "serve_begin", 8: "serve_end",
    9: "cma_read", 10: "window_issue", 11: "window_ready",
    12: "window_stall", 13: "plan_replan", 14: "plan_applied",
    15: "suspect", 16: "suspect_clear", 17: "quota_reject",
    18: "lane_budget_rotate", 19: "flight", 20: "failover",
    21: "verify_fail", 22: "scrub", 23: "barrier", 24: "barrier_done",
    25: "barrier_abort", 26: "cache_fill", 27: "cache_hit",
    28: "cache_evict", 29: "slo_breach", 30: "gw_session",
    31: "gw_shed",
}
#: name -> code view of :data:`TRACE_TYPES` (Python-side emitters).
TRACE_TYPE_CODES = {v: k for k, v in TRACE_TYPES.items()}

#: op classes carried in op_begin/op_end `a` (trace.h OpClass).
TRACE_OP_CLASSES = {0: "get", 1: "get_batch", 2: "read_runs",
                    3: "async_batch"}

#: flight-recorder trigger codes (trace.h FlightReason).
TRACE_FLIGHT_REASONS = {1: "peer_lost", 2: "quota", 3: "window_giveup",
                        4: "suspect", 5: "manual", 6: "corrupt",
                        7: "barrier_abort", 8: "slo_breach",
                        9: "shed_storm"}

#: dict keys of :func:`trace_stats`, in native layout order (keep in
#: sync with capi dds_trace_stats / trace::Stats).
#: ``captured``/``dropped``/``flight_dumps``/``spans`` are monotone
#: since process start; the rest are gauges.
TRACE_STAT_KEYS = ("enabled", "ring_events", "threads", "capacity",
                   "live", "captured", "dropped", "flight_events",
                   "flight_dumps", "spans")


# -- ddmetrics: always-on latency/bytes histograms + SLO monitor --------------
#
# Per-STORE (unlike the process-global trace rings): a ThreadGroup's
# in-process ranks keep separate latency surfaces, and the cross-rank
# pull (kOpMetrics) merges them into one cluster view. All layouts
# mirror native/metrics_hist.h; drift breaks the snapshot format.

#: log2 bucket count of each histogram (metrics_hist.h kBuckets).
METRICS_BUCKETS = 44

#: numpy layout of one snapshot cell (keep in sync with
#: metrics_hist.h `CellRecord` — packed little-endian).
METRICS_CELL_DTYPE = np.dtype([
    ("cls", "<i4"), ("route", "<i4"), ("peer", "<i4"),
    ("reserved", "<i4"), ("tenant", "S48"),
    ("count", "<u8"), ("lat_sum_ns", "<u8"),
    ("lat", "<u8", (METRICS_BUCKETS,)),
    ("bytes_sum", "<u8"),
    ("bytes", "<u8", (METRICS_BUCKETS,))])

#: route decode table (metrics_hist.h Route — ordered by the
#: span_latency attribution precedence: uring > cma > tcp > local).
METRICS_ROUTES = {0: "local", 1: "tcp", 2: "cma", 3: "uring"}
#: name -> code view (Python-side recorders / tests).
METRICS_ROUTE_CODES = {v: k for k, v in METRICS_ROUTES.items()}

#: dict keys of ``NativeStore.metrics_stats`` in native layout order
#: (keep in sync with capi dds_metrics_stats).
METRICS_STAT_KEYS = ("enabled", "cells", "cells_cap", "dropped_cells",
                     "tenants", "tenant_overflow", "ops_recorded")

#: dict keys of ``NativeStore.slo_stats`` in native layout order (keep
#: in sync with capi dds_slo_stats). ``evaluations``/``breaches`` are
#: monotone; the rest are gauges.
SLO_STAT_KEYS = ("rules", "evaluations", "breaches", "window_ms",
                 "last_breach_tenant_slot")
#: the gauge subset of :data:`SLO_STAT_KEYS` (never delta'd).
SLO_GAUGE_KEYS = ("rules", "window_ms", "last_breach_tenant_slot")

#: dict keys of ``NativeStore.gateway_stats`` in native layout order
#: (keep in sync with capi dds_gateway_stats / gw::Gateway::Stats).
#: attaches..rejected and drain_sheds are monotone; the rest gauges.
GATEWAY_STAT_KEYS = ("enabled", "sessions", "attaches", "detaches",
                     "expired", "renewals", "admitted", "deferred",
                     "rejected", "drain_sheds", "draining", "inflight",
                     "deferred_now", "last_retry_after_ms")
#: the gauge subset of :data:`GATEWAY_STAT_KEYS` (never delta'd).
GATEWAY_GAUGE_KEYS = ("enabled", "sessions", "draining", "inflight",
                      "deferred_now", "last_retry_after_ms")


def trace_configure(enabled: int, ring_events: int = -1) -> None:
    """Flip tracing on/off at runtime (``enabled`` 0/1; -1 keeps) and
    optionally set the per-thread ring capacity for rings allocated
    from now on (existing threads keep their rings). The load-time
    equivalents are ``DDSTORE_TRACE`` / ``DDSTORE_TRACE_RING``."""
    _check(_load().dds_trace_configure(int(enabled), int(ring_events)),
           "trace_configure")


def trace_enabled() -> bool:
    """One native relaxed load: is tracing recording right now?"""
    return bool(_load().dds_trace_enabled())


def trace_reset() -> None:
    """Drop every recorded event (rings trimmed, flight buffer
    cleared); the monotone totals in :func:`trace_stats` keep
    counting. Test/bench isolation hook."""
    _check(_load().dds_trace_reset(), "trace_reset")


def trace_emit(type_, span: int = 0, rank: int = -1, a: int = 0,
               b: int = 0, c: int = 0) -> None:
    """Append one event to THIS thread's ring (no-op while tracing is
    off). ``type_`` is a :data:`TRACE_TYPES` code or name — the hook
    Python-side emitters (readahead windows, scheduler replans) use."""
    code = TRACE_TYPE_CODES.get(type_, type_) \
        if isinstance(type_, str) else int(type_)
    _load().dds_trace_emit(int(code), int(span), int(rank), int(a),
                           int(b), int(c))


def trace_new_span(rank: int = -1) -> int:
    """Mint a fresh span id for a Python-side logical op."""
    return int(_load().dds_trace_new_span(int(rank)))


def trace_flight(reason, rank: int = -1) -> None:
    """Trigger the flight recorder manually (``reason`` a
    :data:`TRACE_FLIGHT_REASONS` code or name) — the readahead window
    give-up path calls this."""
    codes = {v: k for k, v in TRACE_FLIGHT_REASONS.items()}
    code = codes.get(reason, reason) if isinstance(reason, str) \
        else int(reason)
    _check(_load().dds_trace_flight(int(code), int(rank)),
           "trace_flight")


def trace_stats() -> dict:
    """Trace counters (:data:`TRACE_STAT_KEYS`): rings/threads/live
    occupancy gauges plus the monotone captured/dropped/flight/span
    totals."""
    arr = (ctypes.c_int64 * 12)()
    _check(_load().dds_trace_stats(arr), "trace_stats")
    return dict(zip(TRACE_STAT_KEYS, list(arr)[:len(TRACE_STAT_KEYS)]))


def _trace_dump_call(fn) -> np.ndarray:
    need = int(fn(None, 0))
    if need <= 0:
        return np.empty(0, dtype=TRACE_EVENT_DTYPE)
    buf = ctypes.create_string_buffer(need)
    n = int(fn(buf, need))
    events = np.frombuffer(buf.raw[:n], dtype=TRACE_EVENT_DTYPE).copy()
    # Chronological merge across the per-thread rings.
    return events[np.argsort(events["t_ns"], kind="stable")]


def trace_dump() -> np.ndarray:
    """Every live ring event of this process as a structured array
    (:data:`TRACE_EVENT_DTYPE`), time-sorted across threads. Bounded by
    the rings' capacity; empty when tracing never ran."""
    return _trace_dump_call(_load().dds_trace_dump)


def trace_flight_dump() -> np.ndarray:
    """The LAST flight-recorder snapshot (same format as
    :func:`trace_dump`, ending in its ``flight`` marker event)."""
    return _trace_dump_call(_load().dds_trace_flight_dump)


#: dict keys of :meth:`NativeStore.lane_state`, in native layout order.
#: ``active_lanes``/``parked``/``best_bw_bytes_per_s`` describe the
#: bulk-stripe tuner (the headline); the scatter class (many-small-op
#: dealing) has its own tuner with its own park.
LANE_STATE_KEYS = ("max_lanes", "active_lanes", "parked", "autotune",
                   "samples", "best_bw_bytes_per_s",
                   "scatter_active_lanes", "scatter_parked")


#: column names of one :meth:`NativeStore.sched_cells` row, in native
#: layout order (keep in sync with TcpTransport::SchedCells). ``source``
#: 0 = CMA/TCP router cell, 1 = lane-tuner level cell; ``cls`` 0 = bulk,
#: 1 = scatter; ``knob`` is the route (0 = cma, 1 = tcp) or the lane
#: count the cell measures.
SCHED_CELL_COLS = ("source", "cls", "knob", "ewma_bps", "n")


#: dict keys of :meth:`NativeStore.failover_stats`, in native layout
#: order (keep in sync with capi dds_failover_stats /
#: Store::FailoverCounters). ``replication``, ``hb_active`` and
#: ``suspected_now`` are GAUGES; everything else is monotone since
#: store creation (PipelineMetrics diffs those per epoch).
FAILOVER_STAT_KEYS = (
    "replication", "failover_reads", "failover_runs", "failover_bytes",
    "suspect_skips", "replica_giveups", "mirror_fills",
    "mirror_refresh_skipped", "mirror_bytes", "hb_pings", "hb_failures",
    "hb_suspects_raised", "hb_active", "suspected_now",
)

#: the gauge subset of :data:`FAILOVER_STAT_KEYS` (never delta'd).
FAILOVER_GAUGE_KEYS = ("replication", "hb_active", "suspected_now")


#: dict keys of :meth:`NativeStore.tenant_stats`, in native layout
#: order (keep in sync with capi dds_tenant_stats /
#: Store::TenantCounters). ``quota_bytes``/``quota_vars``/``bytes``/
#: ``vars``/``snapshot_pins``/``share`` are GAUGES; the rest is
#: monotone since store creation (PipelineMetrics diffs those per
#: epoch into ``summary()["tenants"]``).
TENANT_STAT_KEYS = (
    "quota_bytes", "quota_vars", "bytes", "vars", "quota_rejections",
    "read_bytes", "reads", "served_bytes", "served_reads",
    "async_admitted", "async_deferred", "snapshot_pins", "share",
)

#: the gauge subset of :data:`TENANT_STAT_KEYS` (never delta'd).
TENANT_GAUGE_KEYS = ("quota_bytes", "quota_vars", "bytes", "vars",
                     "snapshot_pins", "share")


#: dict keys of :meth:`NativeStore.fault_stats`, in native layout order.
FAULT_STAT_KEYS = (
    "fault_checks", "injected_reset", "injected_trunc", "injected_delay",
    "injected_stall", "injected_delay_ms",
    "retry_transient", "retry_attempts", "retry_reconnects",
    "retry_backoff_ms", "retry_giveups", "retry_fatal", "last_error_peer",
    "injected_corrupt", "ctrl_checks", "ctrl_injected",
)


#: dict keys of :meth:`NativeStore.integrity_stats`, in native layout
#: order (keep in sync with capi dds_integrity_stats /
#: Store::IntegrityStats). ``verify_mode``/``sums_tables``/
#: ``last_corrupt_peer`` are GAUGES; everything else is monotone since
#: store creation (PipelineMetrics diffs those per epoch into
#: ``summary()["integrity"]``).
INTEGRITY_STAT_KEYS = (
    "verify_mode", "sums_tables", "sums_computed", "sums_rows",
    "sums_served", "verified_reads", "verified_bytes",
    "verify_mismatches", "verify_seq_retries", "verify_primary_retries",
    "verify_failovers", "corrupt_errors", "scrub_rows",
    "scrub_divergent", "scrub_repaired", "last_corrupt_peer",
)

#: the gauge subset of :data:`INTEGRITY_STAT_KEYS` (never delta'd).
INTEGRITY_GAUGE_KEYS = ("verify_mode", "sums_tables", "last_corrupt_peer")


#: dict keys of :meth:`NativeStore.tiering_stats`, in native layout
#: order (keep in sync with capi dds_tiering_stats /
#: Store::TieringStats). The first five are GAUGES (cache budget and
#: occupancy, cold-tier registrations); everything else is monotone
#: since store creation (PipelineMetrics diffs those per epoch into
#: ``summary()["tiering"]``).
TIERING_STAT_KEYS = (
    "cache_max_bytes", "cache_bytes", "cache_entries", "cold_vars",
    "cold_bytes", "cache_hits", "cache_hit_bytes", "cache_misses",
    "cache_miss_bytes", "cache_fills", "cache_fill_bytes",
    "cache_fill_failures", "cache_evictions", "cache_evicted_bytes",
    "cache_over_budget", "cache_prefetches",
)

#: the gauge subset of :data:`TIERING_STAT_KEYS` (never delta'd).
TIERING_GAUGE_KEYS = ("cache_max_bytes", "cache_bytes", "cache_entries",
                      "cold_vars", "cold_bytes")


#: dict keys of :func:`uring_probe` in native layout order (keep in
#: sync with capi dds_uring_probe). ``features`` is the raw
#: IORING_FEAT_* bitmask from io_uring_setup; the op_* flags come from
#: IORING_REGISTER_PROBE.
URING_PROBE_KEYS = ("supported", "features", "op_send", "op_recv",
                    "op_sendmsg", "op_recvmsg", "op_read",
                    "op_read_fixed", "ext_arg", "reserved")

#: dict keys of :meth:`NativeStore.uring_stats` in native layout order
#: (keep in sync with capi dds_uring_stats /
#: UringTransport::UringCounters). ``engaged`` is a gauge; the rest are
#: monotone. A healthy engaged run shows ``enters`` far below
#: ``frames`` — that ratio IS the syscall batching win.
URING_STAT_KEYS = ("engaged", "bursts", "enters", "sqes", "frames",
                   "fallbacks", "ring_errors")

#: the gauge subset of :data:`URING_STAT_KEYS` (never delta'd).
URING_GAUGE_KEYS = ("engaged",)

#: dict keys of :meth:`NativeStore.cold_direct_stats` in native layout
#: order (keep in sync with capi dds_cold_direct_stats /
#: ColdDirectReader::Stats). ``files``/``regbuf``/``ring_ok`` are
#: gauges; the rest monotone.
COLD_DIRECT_STAT_KEYS = ("files", "reads", "bytes", "fallbacks",
                         "regbuf", "ring_ok")

#: the gauge subset of :data:`COLD_DIRECT_STAT_KEYS` (never delta'd).
COLD_DIRECT_GAUGE_KEYS = ("files", "regbuf", "ring_ok")


def uring_probe() -> dict:
    """Process-wide io_uring capability verdict, independent of any
    store (:data:`URING_PROBE_KEYS` plus a human ``reason`` string —
    "ok", or why the kernel refused). Cached after the first call; the
    diag module and the bench record it so a TCP-fallback run is
    diagnosable from its artifacts alone."""
    lib = _load()
    arr = (ctypes.c_int64 * 10)()
    _check(lib.dds_uring_probe(arr), "uring_probe")
    out = dict(zip(URING_PROBE_KEYS, list(arr)))
    del out["reserved"]
    buf = ctypes.create_string_buffer(256)
    lib.dds_uring_probe_reason(buf, 256)
    out["reason"] = buf.value.decode(errors="replace")
    return out


def _as_i64p(arr: np.ndarray):
    return arr.ctypes.data_as(_i64p)


class NativeStore:
    """Thin, byte-oriented wrapper over one native store instance."""

    def __init__(self, handle: int, local_gid: Optional[str] = None):
        if not handle:
            raise RuntimeError("native store creation failed")
        self._h = handle
        self._local_gid = local_gid
        self._lib = _load()

    # -- constructors ------------------------------------------------------

    @classmethod
    def create_local(cls, group_id: str, rank: int, world: int) -> "NativeStore":
        lib = _load()
        h = lib.dds_create_local(group_id.encode(), rank, world)
        return cls(h, local_gid=group_id)

    @classmethod
    def create_tcp(cls, rank: int, world: int, port: int = 0) -> "NativeStore":
        lib = _load()
        h = lib.dds_create_tcp(rank, world, port)
        return cls(h)

    @classmethod
    def create_uring(cls, rank: int, world: int,
                     port: int = 0) -> "NativeStore":
        """io_uring wire backend (``DDSTORE_TRANSPORT=uring``). A
        drop-in TcpTransport subclass: peers, lanes, faults, failover
        and the gateway all behave identically; only the per-lane wire
        loop batches a whole frame burst into one ``io_uring_enter``.
        Construction NEVER fails on an io_uring-less kernel — the
        handle serves through the inherited TCP path and
        :meth:`uring_state`/:meth:`uring_reason` export the verdict."""
        lib = _load()
        h = lib.dds_create_uring(rank, world, port)
        return cls(h)

    # -- transport wiring --------------------------------------------------

    @property
    def server_port(self) -> int:
        return self._lib.dds_server_port(self._h)

    def set_peers(self, hosts: Sequence[str], ports: Sequence[int]) -> None:
        """Each host entry may be a comma-separated per-NIC address list;
        the peer's connection pool spreads round-robin across them."""
        n = len(hosts)
        harr = (ctypes.c_char_p * n)(*[h.encode() for h in hosts])
        parr = (ctypes.c_int * n)(*ports)
        _check(self._lib.dds_set_peers(self._h, harr, parr, n), "set_peers")

    def set_ifaces(self, addrs: Sequence[str]) -> None:
        """Local per-NIC source addresses; outgoing pool connections bind
        to them round-robin (multi-NIC striping, DDSTORE_IFACES)."""
        _check(self._lib.dds_set_ifaces(
            self._h, ",".join(addrs).encode()), "set_ifaces")

    def update_peer(self, target: int, host: str, port: int) -> None:
        """Elastic recovery: re-point one peer at a relaunched
        replacement's endpoint (stale connections closed, CMA re-probed
        against the new pid)."""
        _check(self._lib.dds_update_peer(
            self._h, target, host.encode(), port), f"update_peer({target})")

    def routing_state(self) -> dict:
        """Adaptive routing snapshot for both traffic classes (bulk =
        single >=8 MiB reads; scatter = many-small-op batches): per-path
        EWMA bandwidths, decision/probe counts, crossovers, current
        preference — exported into bench extras so routing regressions
        are diagnosable from the BENCH json alone."""
        out = {}
        for cls, label in ((0, "bulk"), (1, "scatter")):
            cma = ctypes.c_double()
            tcp = ctypes.c_double()
            dec = ctypes.c_int64()
            cro = ctypes.c_int64()
            via = ctypes.c_int()
            cal = ctypes.c_int()
            _check(self._lib.dds_routing_state(
                self._h, cls, ctypes.byref(cma), ctypes.byref(tcp),
                ctypes.byref(dec), ctypes.byref(cro), ctypes.byref(via),
                ctypes.byref(cal)),
                "routing_state")
            out.update({f"cma_{label}_gbps": cma.value / 1e9,
                        f"tcp_{label}_gbps": tcp.value / 1e9,
                        f"{label}_decisions": dec.value,
                        f"{label}_crossovers": cro.value,
                        f"{label}_via_tcp": bool(via.value),
                        f"{label}_calibrated": bool(cal.value)})
        # Same-host Unix-lane dials: whether loopback peers actually took
        # the UDS fast lane or silently fell back to loopback TCP.
        out["uds_conns"] = self._lib.dds_uds_conns(self._h)
        return out

    def set_retry_deadline(self, seconds: float) -> None:
        """Override THIS store's transient-retry deadline
        (``DDSTORE_OP_DEADLINE_S``); ``<= 0`` restores the env/default.
        The degraded readahead path uses it to share ONE deadline
        budget across a window give-up and its per-batch refetch, so a
        permanently dead owner surfaces ``kErrPeerLost`` within ~1x the
        deadline instead of ~2x. Per-store: other stores in the process
        keep their full budgets; still advisory within this store —
        concurrent reads on it see the reduced budget while set, so
        callers must clear it in a ``finally``."""
        _check(self._lib.dds_set_retry_deadline(self._h, float(seconds)),
               "set_retry_deadline")

    def lane_state(self) -> dict:
        """Striped-lane autotuner snapshot (:data:`LANE_STATE_KEYS`):
        the configured pool size (``DDSTORE_TCP_LANES``), the lane count
        striped reads currently engage, whether the tuner has parked
        (per-lane throughput stopped scaling), and the best measured
        stripe bandwidth. ``{}`` for non-TCP backends."""
        arr = (ctypes.c_int64 * 8)()
        if self._lib.dds_lane_state(self._h, arr) != 0:
            return {}
        out = dict(zip(LANE_STATE_KEYS, list(arr)[:len(LANE_STATE_KEYS)]))
        for k in ("parked", "autotune", "scatter_parked"):
            out[k] = bool(out[k])
        return out

    def lane_bytes(self, target: int = -1) -> list:
        """Per-lane response bytes carried over TCP/UDS since store
        creation (``target >= 0``: that peer's lanes; ``-1``: summed
        across peers, lane-index-aligned). ``[]`` for non-TCP backends.
        Monotone; diff snapshots for per-epoch lane utilization — that
        is what ``PipelineMetrics`` does with its lane source."""
        cap = 64
        arr = (ctypes.c_int64 * cap)()
        n = self._lib.dds_lane_bytes(self._h, int(target), arr, cap)
        if n < 0:
            return []
        return list(arr)[:n]

    def sched_cells(self) -> list:
        """Warm-window substrate snapshot for the cost-model scheduler:
        a list of dicts keyed by :data:`SCHED_CELL_COLS` — every
        router/lane-tuner measurement cell's EWMA bytes/s and clean
        sample count. ``[]`` for non-TCP backends (nothing to plan
        against; the planner then leaves the transport knobs alone)."""
        cap = 64
        arr = (ctypes.c_double * (cap * 5))()
        n = self._lib.dds_sched_cells(self._h, arr, cap)
        if n < 0:
            return []
        return [dict(zip(SCHED_CELL_COLS, arr[i * 5:(i + 1) * 5]))
                for i in range(n)]

    def sched_pin_route(self, cls: int, mode: int) -> None:
        """Planner route pin for traffic class ``cls`` (0 = bulk, 1 =
        scatter): ``mode`` 0 = CMA, 1 = TCP, -1 = release to the
        adaptive router. Ranks below the user env pins
        (``DDSTORE_CMA_BULK``/``SCATTER``); released by a peer update."""
        _check(self._lib.dds_sched_pin_route(self._h, int(cls), int(mode)),
               f"sched_pin_route({cls}, {mode})")

    def sched_pin_lanes(self, cls: int, lanes: int) -> None:
        """Planner lane-width pin for traffic class ``cls``: ``lanes``
        >= 1 pins the stripe width (clamped to the pool size), -1
        releases to the lane autotuner."""
        _check(self._lib.dds_sched_pin_lanes(self._h, int(cls),
                                             int(lanes)),
               f"sched_pin_lanes({cls}, {lanes})")

    def set_async_width(self, n: int) -> None:
        """Async admission width (concurrently RUNNING async batched
        reads): ``n`` >= 1 overrides, <= 0 restores the
        ``DDSTORE_ASYNC_THREADS`` / core-ladder default. Excess issues
        queue and start as running reads complete — the ticket contract
        is unchanged."""
        _check(self._lib.dds_set_async_width(self._h, int(n)),
               f"set_async_width({n})")

    @property
    def async_width(self) -> int:
        """The admission width currently in force (override, env, or
        the 4/2/1 core-ladder default)."""
        return int(self._lib.dds_async_width(self._h))

    # -- tenant namespaces / quotas / snapshot epochs ----------------------

    def tenant_set_quota(self, tenant: str, max_bytes: int,
                         max_vars: int = -1) -> None:
        """Byte/var budget for ``tenant`` (< 0 = unlimited). Checked
        atomically at add/init registration; over-budget registrations
        raise :data:`ERR_QUOTA` — a distinct, non-fatal class."""
        _check(self._lib.dds_tenant_set_quota(
            self._h, tenant.encode(), int(max_bytes), int(max_vars)),
            f"tenant_set_quota({tenant})")

    def tenant_set_share(self, tenant: str, share: int) -> None:
        """Async-admission weight (>= 1): with any share configured,
        ``tenant`` runs at most ``max(1, width * share / total)``
        concurrent async batched reads; excess defers and admits as
        slots free (ticket contract unchanged)."""
        _check(self._lib.dds_tenant_set_share(
            self._h, tenant.encode(), int(share)),
            f"tenant_set_share({tenant})")

    def tenant_set_lane_budget(self, tenant: str, lanes: int) -> None:
        """QoS lane budget: striped reads of ``tenant``'s variables
        engage at most ``lanes`` transport lanes (<= 0 clears). No-op
        on non-TCP backends."""
        _check(self._lib.dds_tenant_set_lane_budget(
            self._h, tenant.encode(), int(lanes)),
            f"tenant_set_lane_budget({tenant})")

    def tenant_names(self) -> list:
        """Every tenant the store has seen (config or traffic). A
        leading separator marks the DEFAULT tenant "" — a CSV of plain
        labels cannot otherwise carry it."""
        cap = 1 << 16
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.dds_tenant_names(self._h, buf, cap)
        if n <= 0:
            return []
        raw = buf.value.decode()
        names = [""] if raw.startswith(",") else []
        return names + [t for t in raw.split(",") if t]

    def tenant_stats(self, tenant: str) -> dict:
        """Ledger snapshot for one tenant (:data:`TENANT_STAT_KEYS`)."""
        arr = (ctypes.c_int64 * 16)()
        _check(self._lib.dds_tenant_stats(self._h, tenant.encode(), arr),
               f"tenant_stats({tenant})")
        return dict(zip(TENANT_STAT_KEYS,
                        list(arr)[:len(TENANT_STAT_KEYS)]))

    def snapshot_acquire(self, tenant: str = "") -> int:
        """Pin the store-wide current shard versions; returns the
        snapshot id the reader's scoped names carry. All-or-nothing: a
        peer that cannot be pinned fails the acquire (pins already
        placed are rolled back)."""
        sid = self._lib.dds_snapshot_acquire(self._h, tenant.encode())
        if sid <= 0:
            raise DDStoreError(int(sid), "snapshot_acquire")
        return int(sid)

    def snapshot_release(self, snap_id: int) -> None:
        """Release a snapshot everywhere; kept versions whose last pin
        this was are reclaimed. Idempotent."""
        _check(self._lib.dds_snapshot_release(self._h, int(snap_id)),
               f"snapshot_release({snap_id})")

    def snapshot_stats(self) -> dict:
        """This rank's snapshot gauges: active pins, kept shard
        versions and their RAM cost, plus the monotone count of pins
        reclaimed by the stale-pin reaper (TTL / dead owner)."""
        arr = (ctypes.c_int64 * 4)()
        _check(self._lib.dds_snapshot_stats(self._h, arr),
               "snapshot_stats")
        return {"active_snapshots": int(arr[0]),
                "kept_versions": int(arr[1]),
                "kept_bytes": int(arr[2]),
                "reclaimed_pins": int(arr[3])}

    # -- serving gateway ---------------------------------------------------

    def gateway_configure(self, enabled: int = -1, lease_ms: int = -1,
                          defer_ms: int = -1, queue_cap: int = -1,
                          admit_margin_pct: int = -1,
                          lane_share: int = -1,
                          pin_ttl_ms: int = -1) -> None:
        """Runtime gateway (re)configuration; -1 keeps each field.
        ``enabled=1`` clears a previous drain and (re)arms the lease
        reaper; ``pin_ttl_ms`` arms stranded-pin reclaim even with the
        gateway off. Load-time knobs: ``DDSTORE_GATEWAY`` /
        ``DDSTORE_GW_*`` / ``DDSTORE_SNAP_PIN_TTL_MS``."""
        _check(self._lib.dds_gateway_configure(
            self._h, int(enabled), int(lease_ms), int(defer_ms),
            int(queue_cap), int(admit_margin_pct), int(lane_share),
            int(pin_ttl_ms)), "gateway_configure")

    def gateway_attach(self, target: int = -1, tenant: str = "",
                       with_snapshot: bool = False,
                       quota_bytes: int = 0) -> int:
        """Attach an ephemeral reader session on ``target``'s gateway
        (< 0 = this rank) and return the session token. The lease
        must be renewed at ~lease/3 or its pins/quota/lane share are
        reaped."""
        token = int(self._lib.dds_gateway_attach(
            self._h, int(target), tenant.encode(),
            1 if with_snapshot else 0, int(quota_bytes)))
        if token < 0:
            raise DDStoreError(token, f"gateway_attach({tenant!r})")
        return token

    def gateway_renew(self, token: int, target: int = -1) -> None:
        """Lease heartbeat; raises ``ERR_NOT_FOUND`` after expiry."""
        _check(self._lib.dds_gateway_renew(self._h, int(target),
                                           int(token)),
               f"gateway_renew({token})")

    def gateway_detach(self, token: int, target: int = -1) -> None:
        """Graceful goodbye: releases the lease's snapshot pins, quota
        reservation and (last-of-tenant) lane share."""
        _check(self._lib.dds_gateway_detach(self._h, int(target),
                                            int(token)),
               f"gateway_detach({token})")

    def gateway_drain(self, deadline_ms: int = 1000) -> bool:
        """Stop admitting, wait up to ``deadline_ms`` for in-flight
        reads, shed the rest with ``ERR_ADMISSION``. True when the
        gateway went quiet inside the deadline."""
        rc = int(self._lib.dds_gateway_drain(self._h, int(deadline_ms)))
        if rc == 0:
            return True
        if rc == ERR_TRANSPORT:
            return False
        raise DDStoreError(rc, "gateway_drain")

    def gateway_reap(self) -> int:
        """One synchronous lease/pin reap pass (the deterministic test
        hook for the background reaper). Returns reclaimed pin count."""
        rc = int(self._lib.dds_gateway_reap(self._h))
        if rc < 0:
            raise DDStoreError(rc, "gateway_reap")
        return rc

    def gateway_stats(self) -> dict:
        """Gateway counters (:data:`GATEWAY_STAT_KEYS`)."""
        arr = (ctypes.c_int64 * 16)()
        _check(self._lib.dds_gateway_stats(self._h, arr),
               "gateway_stats")
        return dict(zip(GATEWAY_STAT_KEYS,
                        list(arr)[:len(GATEWAY_STAT_KEYS)]))

    # -- ddmetrics: live latency histograms + SLO monitor -----------------

    def metrics_configure(self, enabled: int) -> None:
        """Flip THIS store's histograms at runtime (0/1; -1 keeps).
        Load-time knob: ``DDSTORE_METRICS`` (default on)."""
        _check(self._lib.dds_metrics_configure(self._h, int(enabled)),
               "metrics_configure")

    def metrics_enabled(self) -> bool:
        return bool(self._lib.dds_metrics_enabled(self._h))

    def metrics_reset(self) -> None:
        """Zero every cell's counters (claimed keys stay interned)."""
        _check(self._lib.dds_metrics_reset(self._h), "metrics_reset")

    def _metrics_decode(self, fn, *args) -> np.ndarray:
        need = int(self._lib.dds_metrics_snapshot(self._h, None, 0))
        if need <= 0:
            return np.empty(0, dtype=METRICS_CELL_DTYPE)
        buf = ctypes.create_string_buffer(need)
        n = int(fn(*args, buf, need))
        if n < 0:
            raise DDStoreError(n, "metrics snapshot/pull")
        return np.frombuffer(buf.raw[:n],
                             dtype=METRICS_CELL_DTYPE).copy()

    def metrics_snapshot(self) -> np.ndarray:
        """This store's live histogram cells as a structured array
        (:data:`METRICS_CELL_DTYPE`): one row per (class, route, peer,
        reading-tenant) with log2 latency/bytes buckets."""
        return self._metrics_decode(self._lib.dds_metrics_snapshot,
                                    self._h)

    def metrics_pull(self, target: int) -> np.ndarray:
        """Pull ``target``'s cells over the control plane (kOpMetrics
        on the dedicated heartbeat connection; never a data lane).
        Raises ``DDStoreError(ERR_PEER_LOST)`` for a detector-
        suspected/dead peer — zero control budget burned, no giveup."""
        return self._metrics_decode(self._lib.dds_metrics_pull,
                                    self._h, int(target))

    def metrics_stats(self) -> dict:
        """Histogram registry counters (:data:`METRICS_STAT_KEYS`)."""
        arr = (ctypes.c_int64 * 8)()
        _check(self._lib.dds_metrics_stats(self._h, arr),
               "metrics_stats")
        return dict(zip(METRICS_STAT_KEYS,
                        list(arr)[:len(METRICS_STAT_KEYS)]))

    def metrics_tenants(self) -> list:
        """Interned reading-tenant labels in slot order (slot 0 is the
        default tenant ``""``)."""
        buf = ctypes.create_string_buffer(4096)
        n = self._lib.dds_metrics_tenants(self._h, buf, 4096)
        if n < 0:
            raise DDStoreError(n, "metrics_tenants")
        return buf.value.decode().split(",")

    def metrics_record(self, cls: int, route: int, peer: int,
                       tenant: str, lat_ns: int, nbytes: int) -> None:
        """Fold one synthetic op sample into the histograms (test /
        Python-side-op hook)."""
        _check(self._lib.dds_metrics_record(
            self._h, int(cls), int(route), int(peer), tenant.encode(),
            int(lat_ns), int(nbytes)), "metrics_record")

    def slo_configure(self, spec: str) -> None:
        """Replace the tenant latency objectives
        (``"t=p99:5ms,t2=p50:200us"``; a bare ``"p99:5ms"`` names the
        default tenant; empty clears). Baselines reset to the current
        histograms. Load-time knob: ``DDSTORE_TENANT_SLOS``."""
        _check(self._lib.dds_slo_configure(self._h, spec.encode()),
               f"slo_configure({spec!r})")

    def slo_evaluate(self) -> list:
        """Evaluate every objective over the histogram delta since the
        last evaluation (rate-limited by ``DDSTORE_SLO_WINDOW_MS``).
        Returns breach rows ``[tenant_slot, pct, threshold_ns,
        measured_low_ns, window_count]`` — a breach means the
        p-quantile's whole log2 bucket lies above the objective."""
        cap = 64
        arr = (ctypes.c_int64 * (cap * 6))()
        n = int(self._lib.dds_slo_evaluate(self._h, arr, cap))
        if n < 0:
            raise DDStoreError(n, "slo_evaluate")
        return [list(arr[i * 6:i * 6 + 5]) for i in range(n)]

    def slo_stats(self) -> dict:
        """SLO monitor counters (:data:`SLO_STAT_KEYS`)."""
        arr = (ctypes.c_int64 * 8)()
        _check(self._lib.dds_slo_stats(self._h, arr), "slo_stats")
        return dict(zip(SLO_STAT_KEYS, list(arr)[:len(SLO_STAT_KEYS)]))

    # -- replication / failover / heartbeat -------------------------------

    @property
    def replication(self) -> int:
        """Replication factor in force (``DDSTORE_REPLICATION`` clamped
        to ``[1, world]``; 1 = off, exactly the pre-replication tree)."""
        return int(self._lib.dds_replication(self._h))

    def replicate(self, name: str) -> None:
        """Pull/refresh this rank's mirrors of ``name`` (the shards of
        the next R-1 ranks). Call AFTER the registration barrier."""
        _check(self._lib.dds_replicate(self._h, name.encode()),
               f"replicate({name})")

    def refresh_mirrors(self) -> None:
        """Re-pull every mirror this rank hosts, creating missing ones
        (the elastic-recovery rebuild). Suspected/unreachable owners
        are skipped, never fatal."""
        _check(self._lib.dds_refresh_mirrors(self._h), "refresh_mirrors")

    def replica_set(self, owner: int) -> list:
        """Replica chain of ``owner``'s shard, primary first."""
        cap = 64
        arr = (ctypes.c_int * cap)()
        n = self._lib.dds_replica_set(self._h, int(owner), arr, cap)
        if n < 0:
            raise DDStoreError(n, f"replica_set({owner})")
        return list(arr)[:n]

    def health_state(self) -> list:
        """Per-peer suspicion flags (union of heartbeat verdicts and
        data-path ladder give-ups), one bool per rank."""
        cap = 1024
        arr = (ctypes.c_int64 * cap)()
        n = self._lib.dds_health_state(self._h, arr, cap)
        if n < 0:
            return []
        return [bool(v) for v in list(arr)[:n]]

    def heartbeat_configure(self, interval_ms: int,
                            suspect_n: int = 0) -> None:
        """(Re)start the heartbeat detector at ``interval_ms`` (<= 0
        stops it; ``suspect_n`` <= 0 keeps the env/default threshold)."""
        _check(self._lib.dds_heartbeat_configure(
            self._h, int(interval_ms), int(suspect_n)),
            "heartbeat_configure")

    def mark_suspect(self, target: int, suspected: bool = True) -> None:
        """Force one peer into (or out of) the suspect set — the
        deterministic failover-routing hook tests use."""
        _check(self._lib.dds_mark_suspect(self._h, int(target),
                                          int(bool(suspected))),
               f"mark_suspect({target})")

    def failover_stats(self) -> dict:
        """Replicated-read failover + heartbeat counters
        (:data:`FAILOVER_STAT_KEYS`): reroutes served from replicas,
        detector short-circuits (zero deadline burned), whole-replica-
        set losses, mirror fill/refresh traffic, and the ping ledger.
        Monotone except the :data:`FAILOVER_GAUGE_KEYS` gauges."""
        arr = (ctypes.c_int64 * 16)()
        _check(self._lib.dds_failover_stats(self._h, arr),
               "failover_stats")
        return dict(zip(FAILOVER_STAT_KEYS,
                        list(arr)[:len(FAILOVER_STAT_KEYS)]))

    @property
    def barrier_seq(self) -> int:
        """The transport's collective sequence count (elastic rejoin
        syncs a fresh rank to the group's)."""
        return int(self._lib.dds_barrier_seq(self._h))

    def set_barrier_seq(self, seq: int) -> None:
        _check(self._lib.dds_set_barrier_seq(self._h, seq),
               "set_barrier_seq")

    # -- data plane --------------------------------------------------------

    def add(self, name: str, arr: np.ndarray, all_nrows: Sequence[int],
            copy: bool = True) -> None:
        assert arr.flags["C_CONTIGUOUS"], "shard must be C-contiguous"
        nrows = arr.shape[0] if arr.ndim else 0
        # disp comes from the trailing dims, NOT size//nrows: an empty shard
        # (nrows=0) must still agree with its peers on the row width.
        disp = int(np.prod(arr.shape[1:], dtype=np.int64)) if arr.ndim > 1 else 1
        table = np.ascontiguousarray(all_nrows, dtype=np.int64)
        _check(self._lib.dds_add(
            self._h, name.encode(), arr.ctypes.data, nrows, disp,
            arr.itemsize, _as_i64p(table), int(copy)), f"add({name})")

    def init(self, name: str, nrows: int, disp: int, itemsize: int,
             all_nrows: Sequence[int]) -> None:
        table = np.ascontiguousarray(all_nrows, dtype=np.int64)
        _check(self._lib.dds_init(self._h, name.encode(), nrows, disp,
                                  itemsize, _as_i64p(table)), f"init({name})")

    def update(self, name: str, arr: np.ndarray, row_offset: int) -> None:
        assert arr.flags["C_CONTIGUOUS"]
        nrows = arr.shape[0] if arr.ndim else 0
        _check(self._lib.dds_update(self._h, name.encode(), arr.ctypes.data,
                                    nrows, row_offset), f"update({name})")

    def get(self, name: str, out: np.ndarray, start: int,
            count: int, tenant: str = "") -> None:
        assert out.flags["C_CONTIGUOUS"] and out.flags["WRITEABLE"]
        _check(self._lib.dds_get(self._h, name.encode(), out.ctypes.data,
                                 start, count, tenant.encode()),
               f"get({name}, {start})")

    def get_batch(self, name: str, out: np.ndarray,
                  starts: np.ndarray, tenant: str = "") -> None:
        assert out.flags["C_CONTIGUOUS"] and out.flags["WRITEABLE"]
        starts = np.ascontiguousarray(starts, dtype=np.int64)
        _check(self._lib.dds_get_batch(self._h, name.encode(),
                                       out.ctypes.data, _as_i64p(starts),
                                       len(starts), tenant.encode()),
               f"get_batch({name})")

    # -- async batched reads ----------------------------------------------
    #
    # The epoch-readahead engine's native leg: the read runs on the
    # store's background pool while Python keeps planning/consuming. The
    # caller must keep `out` alive until the ticket completes (the
    # high-level AsyncBatchRead handle holds the reference); `starts` is
    # copied at issue time.

    def get_batch_async(self, name: str, out: np.ndarray,
                        starts: np.ndarray, tenant: str = "") -> int:
        assert out.flags["C_CONTIGUOUS"] and out.flags["WRITEABLE"]
        starts = np.ascontiguousarray(starts, dtype=np.int64)
        ticket = self._lib.dds_get_batch_async(
            self._h, name.encode(), out.ctypes.data, _as_i64p(starts),
            len(starts), tenant.encode())
        if ticket < 0:
            raise DDStoreError(int(ticket), f"get_batch_async({name})")
        return int(ticket)

    def read_runs_async(self, name: str, out: np.ndarray,
                        targets: np.ndarray, src_off: np.ndarray,
                        dst_off: np.ndarray, nbytes: np.ndarray,
                        tenant: str = "") -> int:
        """Async vectored run read: the caller's pre-coalesced per-peer
        runs executed verbatim (O(runs), not O(rows)) — the readahead
        window fast path. Bounds of every dst span are validated here;
        src spans are validated by the local/remote read legs."""
        assert out.flags["C_CONTIGUOUS"] and out.flags["WRITEABLE"]
        arrs = [np.ascontiguousarray(a, dtype=np.int64)
                for a in (targets, src_off, dst_off, nbytes)]
        n = len(arrs[0])
        if not all(len(a) == n for a in arrs):
            raise ValueError("read_runs_async: array length mismatch")
        if n and int((arrs[2] + arrs[3]).max()) > out.nbytes:
            raise ValueError("read_runs_async: dst span exceeds out")
        ticket = self._lib.dds_read_runs_async(
            self._h, name.encode(), out.ctypes.data, _as_i64p(arrs[0]),
            _as_i64p(arrs[1]), _as_i64p(arrs[2]), _as_i64p(arrs[3]), n,
            tenant.encode())
        if ticket < 0:
            raise DDStoreError(int(ticket), f"read_runs_async({name})")
        return int(ticket)

    def async_wait(self, ticket: int, timeout_ms: int = -1):
        """Wait for an async read. Returns ``(status, done_mono_s)``:
        status 1 = done ok, 0 = timeout, <0 = the read's error code.
        ``done_mono_s`` is the completion time on the time.monotonic()
        clock (producer-idle accounting). The status is returned raw —
        the high-level handle must release the ticket even for a failed
        read, so raising here would leak it."""
        ts = ctypes.c_double(0.0)
        rc = self._lib.dds_async_wait(self._h, ticket, timeout_ms,
                                      ctypes.byref(ts))
        return rc, ts.value

    def async_release(self, ticket: int) -> int:
        """Block until the read completes, then free the ticket. Returns
        the read's error code (0 = ok) — never raises: release is the
        teardown barrier and must always free the slot."""
        return int(self._lib.dds_async_release(self._h, ticket))

    @property
    def async_pending(self) -> int:
        """Unreleased async tickets (0 after a clean loader teardown)."""
        return int(self._lib.dds_async_pending(self._h))

    def query(self, name: str):
        total = _i64(0)
        disp = _i64(0)
        itemsize = _i64(0)
        local = _i64(0)
        _check(self._lib.dds_query(self._h, name.encode(),
                                   ctypes.byref(total), ctypes.byref(disp),
                                   ctypes.byref(itemsize), ctypes.byref(local)),
               f"query({name})")
        return {"total_rows": total.value, "disp": disp.value,
                "itemsize": itemsize.value, "local_rows": local.value}

    # -- control plane -----------------------------------------------------

    def epoch_begin(self) -> None:
        _check(self._lib.dds_epoch_begin(self._h), "epoch_begin")

    def epoch_end(self) -> None:
        _check(self._lib.dds_epoch_end(self._h), "epoch_end")

    def set_epoch_collective(self, collective: bool) -> None:
        _check(self._lib.dds_set_epoch_collective(self._h, int(collective)))

    def fence_reset(self) -> None:
        """Force the epoch-fence state machine closed (local,
        idempotent) — the elastic-recovery realignment hook: a fence
        abort need not be unanimous (a victim that partially
        disseminated its barrier notifies can let some survivors
        complete the fence while others roll back), so ``recover()``
        resets every rank to one agreed pre-fence state before the
        group re-enters its first post-recovery epoch."""
        _check(self._lib.dds_fence_reset(self._h), "fence_reset")

    def rebind(self, name: str, arr: np.ndarray) -> None:
        """Atomically swap the local shard's backing memory to ``arr``
        (same length, identical contents — e.g. a fresh mmap of the
        just-spilled shard). The store borrows the buffer; the caller
        keeps it alive. Concurrent readers see old or new bytes, never a
        missing variable."""
        assert arr.flags["C_CONTIGUOUS"]
        _check(self._lib.dds_rebind(self._h, name.encode(),
                                    arr.ctypes.data if arr.size else None),
               f"rebind({name})")

    def free_var(self, name: str) -> None:
        _check(self._lib.dds_free_var(self._h, name.encode()),
               f"free({name})")

    def barrier(self, tag: int) -> None:
        _check(self._lib.dds_barrier(self._h, tag), "barrier")

    @property
    def cma_ops(self) -> int:
        """Reads served via the same-host CMA fast path (shared-memory
        mapped gather, or process_vm_readv for borrowed shards); 0 for
        non-TCP backends or when DDSTORE_CMA=0."""
        return self._lib.dds_cma_ops(self._h)

    def plan_stats(self) -> dict:
        """Cumulative scatter-read planner statistics (``get_batch``):
        batches/rows planned, coalesced runs emitted (local + per-peer),
        remote per-peer run lists issued, duplicate rows served by
        post-fetch replication, and scratch staging volume. Derived:
        ``coalesce_ratio`` = unique rows fetched per transport run (1.0 =
        nothing coalesced; higher = fewer, larger segments on the wire)."""
        arr = (ctypes.c_int64 * 8)()
        _check(self._lib.dds_plan_stats(self._h, arr), "plan_stats")
        (batches, rows, runs, local_runs, peer_lists, dedup_hits,
         scratch_runs, scratch_bytes) = list(arr)
        raw = {
            "plan_batches": batches,
            "plan_rows": rows,
            "plan_runs": runs,
            "plan_local_runs": local_runs,
            "plan_peer_lists": peer_lists,
            "plan_dedup_hits": dedup_hits,
            "plan_scratch_runs": scratch_runs,
            "plan_scratch_bytes": scratch_bytes,
        }
        # Deriving the ratios via a zero-baseline delta keeps their
        # definitions single-sourced in utils.metrics (lazy import:
        # binding must stay importable before the package's siblings).
        from .utils.metrics import plan_stats_delta

        return plan_stats_delta({}, raw)

    # -- end-to-end data integrity -----------------------------------------

    def integrity_configure(self, verify: int = -1,
                            scrub_ms: int = -1) -> None:
        """Runtime integrity toggles (load-time: ``DDSTORE_VERIFY`` /
        ``DDSTORE_SCRUB_MS``): ``verify`` -1 keeps / 0 off / 1 on
        (reader-side checksum verification; also enables sum
        computation); ``scrub_ms`` -1 keeps / 0 stops the background
        scrubber / >0 (re)starts it at that per-mirror tick."""
        _check(self._lib.dds_integrity_configure(
            self._h, int(verify), int(scrub_ms)),
            f"integrity_configure({verify}, {scrub_ms})")

    def integrity_stats(self) -> dict:
        """Integrity counters (:data:`INTEGRITY_STAT_KEYS`): sum-table
        builds/serves, verified reads/bytes, mismatch/retry/failover
        ladder activity, surfaced ``ERR_CORRUPT`` errors and the
        scrubber's checked/divergent/repaired ledger. Monotone except
        the :data:`INTEGRITY_GAUGE_KEYS` gauges."""
        arr = (ctypes.c_int64 * 16)()
        _check(self._lib.dds_integrity_stats(self._h, arr),
               "integrity_stats")
        return dict(zip(INTEGRITY_STAT_KEYS,
                        list(arr)[:len(INTEGRITY_STAT_KEYS)]))

    def integrity_sums(self, name: str, row0: int = 0,
                       count: Optional[int] = None):
        """The LOCAL shard's per-row checksum table slice ``[row0,
        row0+count)`` as ``(sums, seq)`` — ``sums`` a uint64 array,
        ``seq`` the content version it describes. Builds the table
        lazily; raises while integrity is disabled. Test/debug hook."""
        if count is None:
            count = int(self.query(name)["local_rows"]) - row0
        out = np.empty(max(int(count), 0), dtype=np.uint64)
        seq = _i64(-1)
        _check(self._lib.dds_integrity_sums(
            self._h, name.encode(), int(row0), int(count),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            ctypes.byref(seq)), f"integrity_sums({name})")
        return out, int(seq.value)

    def integrity_scrub(self) -> int:
        """One synchronous scrub pass over every resident mirror;
        returns the number of divergent mirrors found (repairs run
        inline, counted in :meth:`integrity_stats`)."""
        n = int(self._lib.dds_integrity_scrub(self._h))
        if n < 0:
            raise DDStoreError(n, "integrity_scrub")
        return n

    # -- tiered storage: hot-row cache + cold placement --------------------

    def tier_configure(self, cache_bytes: int = -1) -> None:
        """Runtime hot-row cache budget (bytes; 0 disables and evicts
        everything, < 0 keeps). Load-time:
        ``DDSTORE_TIER_CACHE_BYTES``."""
        _check(self._lib.dds_tier_configure(self._h, int(cache_bytes)),
               f"tier_configure({cache_bytes})")

    def set_var_tier(self, name: str, tier: int) -> None:
        """Record a registered variable's storage tier (0 = hot
        RAM/shm, 1 = cold file-backed mmap). Drives the
        ``cold_vars``/``cold_bytes`` gauges; serving is tier-agnostic."""
        _check(self._lib.dds_set_var_tier(self._h, name.encode(),
                                          int(tier)),
               f"set_var_tier({name})")

    def var_tier(self, name: str) -> int:
        """The recorded tier of ``name`` (0 hot, 1 cold)."""
        rc = int(self._lib.dds_var_tier(self._h, name.encode()))
        if rc < 0:
            raise DDStoreError(rc, f"var_tier({name})")
        return rc

    def set_tier_placement(self, tenant: str, cold: bool) -> None:
        """Placement policy for ``tenant``'s mirror fills and snapshot
        kept copies: cold lands them file-backed under
        ``DDSTORE_TIER_COLD_DIR`` (load-time:
        ``DDSTORE_TIER_PLACEMENT``)."""
        _check(self._lib.dds_set_tier_placement(
            self._h, tenant.encode(), 1 if cold else 0),
            f"set_tier_placement({tenant})")

    def cache_prefetch(self, name: str, rows, window: int = 0,
                       tenant: str = "") -> None:
        """Warm the hot-row cache with sorted-unique global ``rows`` of
        ``name`` as window ``window`` (the eviction key); the fill runs
        detached on the native async pool, charged against the reading
        ``tenant``'s byte quota until eviction. Advisory: disabled /
        duplicate / over-budget calls are counted no-ops."""
        idx = np.ascontiguousarray(rows, dtype=np.int64).reshape(-1)
        rc = int(self._lib.dds_cache_prefetch(
            self._h, name.encode(), _as_i64p(idx), idx.size,
            int(window), tenant.encode()))
        if rc < 0:
            raise DDStoreError(rc, f"cache_prefetch({name})")

    def cache_evict(self, window: int = -1) -> int:
        """Evict window ``window``'s cache entries (< 0: every entry),
        releasing their quota charges. Returns the count evicted."""
        rc = int(self._lib.dds_cache_evict(self._h, int(window)))
        if rc < 0:
            raise DDStoreError(rc, f"cache_evict({window})")
        return rc

    def tiering_stats(self) -> dict:
        """Tiering counters (:data:`TIERING_STAT_KEYS`): cache budget/
        occupancy gauges, cold-tier registrations, and the monotone
        hit/miss/fill/evict ledger."""
        arr = (ctypes.c_int64 * 16)()
        _check(self._lib.dds_tiering_stats(self._h, arr),
               "tiering_stats")
        return dict(zip(TIERING_STAT_KEYS,
                        list(arr)[:len(TIERING_STAT_KEYS)]))

    # -- io_uring data plane -----------------------------------------------

    def uring_state(self) -> int:
        """1 = uring handle with the ring engaged, 0 = uring handle
        serving through the TCP fallback (kernel refused the probe),
        -1 = not a uring handle."""
        return int(self._lib.dds_uring_state(self._h))

    def uring_reason(self) -> str:
        """This handle's engagement/fallback reason ("ok" when
        engaged; e.g. "io_uring_setup: Operation not permitted" under
        a gVisor-class kernel). Empty string for non-uring handles."""
        buf = ctypes.create_string_buffer(256)
        rc = int(self._lib.dds_uring_reason(self._h, buf, 256))
        if rc < 0:
            return ""
        return buf.value.decode(errors="replace")

    def uring_stats(self) -> dict:
        """Wire-loop counters (:data:`URING_STAT_KEYS`). Raises on
        non-uring handles."""
        arr = (ctypes.c_int64 * 7)()
        _check(self._lib.dds_uring_stats(self._h, arr), "uring_stats")
        return dict(zip(URING_STAT_KEYS, list(arr)))

    def cold_direct_stats(self) -> dict:
        """Cold-tier O_DIRECT reader counters
        (:data:`COLD_DIRECT_STAT_KEYS`); zeros until a var registers
        via :meth:`set_var_file`. Works on every handle kind."""
        arr = (ctypes.c_int64 * 6)()
        _check(self._lib.dds_cold_direct_stats(self._h, arr),
               "cold_direct_stats")
        return dict(zip(COLD_DIRECT_STAT_KEYS, list(arr)))

    def set_var_file(self, name: str, path: str) -> bool:
        """Register a READONLY cold (tier-1) var's backing file so its
        local reads go O_DIRECT through the submission ring instead of
        faulting the mmap. Returns False (never raises) when io_uring
        or O_DIRECT is unavailable — the var stays on the mmap path,
        which serves identical bytes."""
        rc = int(self._lib.dds_set_var_file(self._h, name.encode(),
                                            path.encode()))
        if rc in (ERR_NOT_FOUND, ERR_INVALID_ARG):
            raise DDStoreError(rc, f"set_var_file({name})")
        return rc == 0

    def req_send_stats(self) -> dict:
        """Requester-side TCP pipeline send-gather counters:
        ``req_frames`` / ``req_sends``. Their ratio is the writev
        gather factor of the half-window refill (1.0 = the old
        one-sendmsg-per-frame steady state)."""
        arr = (ctypes.c_int64 * 2)()
        _check(self._lib.dds_req_send_stats(self._h, arr),
               "req_send_stats")
        return {"req_frames": int(arr[0]), "req_sends": int(arr[1])}

    def fault_stats(self) -> dict:
        """Fault-injection + transient-retry counters: the process-global
        injector's draws/injections (``fault_checks``/``injected_*``) plus
        THIS handle's retry layer (``retry_*`` — TCP leaf retries and the
        store-level layer summed, monotone since store creation;
        ``last_error_peer`` names the most recent failed target, -1 =
        none). A seeded schedule reproduces these counters exactly across
        identical runs — the determinism the chaos tests pin."""
        arr = (ctypes.c_int64 * 16)()
        _check(self._lib.dds_fault_stats(self._h, arr), "fault_stats")
        return dict(zip(FAULT_STAT_KEYS, list(arr)[:len(FAULT_STAT_KEYS)]))

    @property
    def rank(self) -> int:
        return self._lib.dds_rank(self._h)

    @property
    def world(self) -> int:
        return self._lib.dds_world(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.dds_destroy(self._h)
            self._h = 0
            if self._local_gid is not None:
                # Drop the process-global LocalGroup registry entry (peers
                # that still exist keep the group alive via shared_ptr).
                self._lib.dds_release_local_group(self._local_gid.encode())
                self._local_gid = None

    def __del__(self):  # best-effort teardown
        try:
            self.close()
        except Exception:
            pass
