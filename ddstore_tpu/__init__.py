"""ddstore_tpu — a TPU-pod-native distributed in-memory sample store.

Built from scratch with the capabilities of ORNL/DDStore (reference at
/root/reference; structural analysis in SURVEY.md): every process (TPU-VM
host) holds one shard of the dataset in host RAM, a global row-index space
spans all shards, and any process reads any sample with a one-sided remote
fetch — no MPI, no GPU in the path.

Layers (bottom-up):

* ``native/`` — C++17 store core + transports (in-process, TCP/DCN
  one-sided read service); the counterpart of the reference's
  ddstore.hpp/common.cxx, redesigned (pluggable transport, 64-bit sizes,
  binary-search owner lookup, pipelined batched reads).
* ``binding.py`` — ctypes boundary, zero-copy numpy buffers.
* ``store.py`` — the ``DDStore`` API (add/get/get_batch/init/update/
  epochs/replica width groups).
* ``data/`` — sample-major dataset adapters, device-feeding loaders.
* ``parallel/`` — JAX mesh/sharding utilities and collectives.
* ``models/`` — flax model families with sharded train steps.
* ``utils/`` — metrics and logging.
"""

from . import _compat  # noqa: F401  — jax API aliases for older runtimes
from .binding import (DDStoreError, NativeStore, fault_configure,
                      owner_of)
from .elastic import recover as elastic_recover
from .elastic import rejoin as elastic_rejoin
from .rendezvous import (FileGroup, JaxGroup, PodConfig, ProcessGroup,
                         SingleGroup, ThreadGroup, auto_group,
                         detect_pod_env, parse_nodelist, pod_bootstrap)
from .store import DDStore

__version__ = "0.1.0"

__all__ = [
    "DDStore",
    "DDStoreError",
    "NativeStore",
    "fault_configure",
    "owner_of",
    "ProcessGroup",
    "SingleGroup",
    "ThreadGroup",
    "FileGroup",
    "JaxGroup",
    "auto_group",
    "PodConfig",
    "detect_pod_env",
    "parse_nodelist",
    "pod_bootstrap",
    "elastic_recover",
    "elastic_rejoin",
    "__version__",
]
