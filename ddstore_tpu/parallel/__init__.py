"""JAX parallelism layer: meshes, sharding helpers, collectives, the
epoch-wise global shuffle, and ring attention for sequence parallelism.

This layer has no counterpart in the reference (its device-side parallelism
is delegated entirely to torch DDP/NCCL, SURVEY §2.2); it is the TPU-native
value-add that connects the host-side store to device meshes.
"""

from .fsdp import fsdp_rules
from .mesh import (batch_sharding, data_parallel_mesh, local_mesh,
                   make_mesh, replicate)
from .pipeline import (interleave_order, interleave_stage_params,
                       pipeline_1f1b, pipeline_apply,
                       pipeline_interleaved, pipeline_interleaved_1f1b,
                       stack_stage_params)
from .ring_attention import ring_attention, ring_self_attention
from .shuffle import (all_to_all_rows, exchange_rows,
                      global_shuffle_epoch, host_global_shuffle,
                      permute_rows, ragged_global_shuffle)
from .tp import expert_rules, megatron_rules, shard_pytree, shardings_of

__all__ = [
    "make_mesh",
    "data_parallel_mesh",
    "local_mesh",
    "batch_sharding",
    "replicate",
    "all_to_all_rows",
    "exchange_rows",
    "permute_rows",
    "global_shuffle_epoch",
    "host_global_shuffle",
    "ragged_global_shuffle",
    "ring_attention",
    "ring_self_attention",
    "fsdp_rules",
    "megatron_rules",
    "expert_rules",
    "shard_pytree",
    "shardings_of",
    "pipeline_apply",
    "pipeline_1f1b",
    "pipeline_interleaved",
    "pipeline_interleaved_1f1b",
    "interleave_stage_params",
    "interleave_order",
    "stack_stage_params",
]
