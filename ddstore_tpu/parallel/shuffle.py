"""Epoch-wise global shuffle.

Two paths, matching the BASELINE.json north star ("the per-epoch global
shuffle lowers to jax.lax.all_to_all over ICI"):

* **Device path** — for device-resident datasets: a fixed-shape, jit-stable
  shuffle built from (local permutation) ∘ (all_to_all block exchange) ∘
  (local permutation) under ``shard_map``. Shapes are static, so XLA
  compiles it once and reuses it every epoch; every row can land on every
  shard across epochs.

* **Host path** — for store-resident datasets: an arbitrary global
  permutation executed as a one-sided reshard through the store (each rank
  batch-fetches the rows the permutation assigns it, then atomically
  replaces its shard). This is the capability the reference's SC'23 paper
  attributes to ``MPI_Alltoallv`` but which is absent from the reference
  snapshot (verified, SURVEY §2.2) — implemented here as a target
  capability.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def all_to_all_rows(x: jax.Array, mesh: Mesh, axis: str = "dp") -> jax.Array:
    """Block exchange over `axis`: each shard splits its rows into
    `world` equal blocks and sends block j to peer j (a row-space
    transpose). Local row count must be divisible by the axis size."""

    def body(xs):
        world = jax.lax.psum(1, axis)
        blocks = xs.reshape((world, xs.shape[0] // world) + xs.shape[1:])
        out = jax.lax.all_to_all(blocks, axis, split_axis=0, concat_axis=0,
                                 tiled=False)
        return out.reshape(xs.shape)

    return jax.shard_map(body, mesh=mesh, in_specs=P(axis),
                         out_specs=P(axis))(x)


@partial(jax.jit, static_argnames=("mesh", "axis"))
def global_shuffle_epoch(x: jax.Array, key: jax.Array, *, mesh: Mesh,
                         axis: str = "dp") -> jax.Array:
    """Device-resident global shuffle with static shapes (compiles once,
    reused every epoch).

    local-perm ∘ all_to_all ∘ local-perm: the inner exchange moves every
    j-th block of every shard to shard j; the outer permutations are
    independent per shard and per epoch (key folded with the shard index),
    so the composition mixes rows across the whole global index space.
    """

    def body(xs, k):
        idx = jax.lax.axis_index(axis)
        world = jax.lax.psum(1, axis)
        k1, k2 = jax.random.split(jax.random.fold_in(k, idx))
        n = xs.shape[0]
        xs = jnp.take(xs, jax.random.permutation(k1, n), axis=0)
        blocks = xs.reshape((world, n // world) + xs.shape[1:])
        blocks = jax.lax.all_to_all(blocks, axis, split_axis=0,
                                    concat_axis=0, tiled=False)
        xs = blocks.reshape(xs.shape)
        # Second local permutation must differ across shards but not
        # correlate with the first; fold in world+idx.
        k3 = jax.random.fold_in(k2, world + idx)
        return jnp.take(xs, jax.random.permutation(k3, n), axis=0)

    return jax.shard_map(
        body, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(axis))(x, key)


@partial(jax.jit, static_argnames=("mesh", "axis"))
def exchange_rows(staged: jax.Array, inv: jax.Array, *, mesh: Mesh,
                  axis: str = "dp") -> jax.Array:
    """Deliver planner-staged rows to their destination shards over ICI.

    The device half of the device-collective fetch
    (``data/device_fetch.py``): each source shard holds a send buffer of
    ``world`` equal blocks (block j = the rows it sends to shard j,
    front-packed, padded to the plan's static per-pair capacity), and
    ``inv`` carries each destination shard's gather indices into its
    received ``(world * cap)`` rows — the inverse local permutation that
    restores exact batch order and drops the padding. Shapes depend only
    on (batch, mesh, world), never on the ownership pattern of one
    batch, so jit compiles this once per configuration.
    """

    def body(xs, inv_local):
        world = jax.lax.psum(1, axis)
        blocks = xs.reshape((world, xs.shape[0] // world) + xs.shape[1:])
        recv = jax.lax.all_to_all(blocks, axis, split_axis=0,
                                  concat_axis=0, tiled=False)
        flat = recv.reshape(xs.shape)
        return jnp.take(flat, inv_local, axis=0)

    return jax.shard_map(body, mesh=mesh, in_specs=(P(axis), P(axis)),
                         out_specs=P(axis))(staged, inv)


def permute_rows(x: jax.Array, perm: jax.Array, mesh: Mesh,
                 axis: str = "dp") -> jax.Array:
    """Arbitrary global row permutation of a device-sharded array:
    ``out[i] = x[perm[i]]``. Implemented as a sharded gather — XLA lowers
    the cross-shard movement to collectives over ICI. Use
    :func:`global_shuffle_epoch` when any good shuffle will do (cheaper);
    use this when the exact permutation matters."""
    sharding = NamedSharding(mesh, P(axis))
    taken = jnp.take(x, perm, axis=0)
    return jax.lax.with_sharding_constraint(taken, sharding)


def _shard_perm(total: int, begin: int, end: int, seed,
                rng: Optional[np.random.Generator]) -> np.ndarray:
    """perm[begin:end] of a seeded global permutation, O(end - begin)
    memory when total is large (every rank computes the SAME perm) —
    the dense-vs-Feistel policy lives in data/permute.py."""
    from ..data.permute import seeded_perm_slice
    return seeded_perm_slice(total, begin, end, seed, rng)


def _reject_ragged(store, name: str) -> None:
    """A ragged pair's {name}/index rows carry (values_start, length)
    pointers whose spans live in the SAME rank's values shard
    (store.add_ragged's locality invariant). Row-shuffling either half
    independently silently corrupts that invariant — index rows pointing
    at spans that moved, or values rows torn out of their samples. Route
    callers to ragged_global_shuffle, which moves spans with their rows."""
    base = name.rsplit("/", 1)[0] if "/" in name else name
    if name.endswith(("/index", "/values")) and store.is_ragged(base):
        raise ValueError(
            f"{name} is half of the ragged pair {base!r}; shuffling it "
            f"alone would corrupt the index->values locality invariant. "
            f"Use ragged_global_shuffle(store, {base!r}, seed).")
    if store.is_ragged(name):
        raise ValueError(
            f"{name} is a ragged variable; use ragged_global_shuffle.")


def host_global_shuffle(store, name: str, seed: int,
                        rng: Optional[np.random.Generator] = None) -> None:
    """Host-path global shuffle of a store variable, in place.

    Every rank computes the same seeded global permutation, batch-fetches
    the rows assigned to its shard (coalesced one-sided reads over the
    transport), waits at a barrier so all fetches complete against the OLD
    data, then atomically overwrites its shard. Collective: all ranks must
    call with the same seed. Index memory is O(shard) even at 1e9 rows
    (blocked Feistel permutation above ``_DENSE_MAX``).
    """
    _reject_ragged(store, name)
    info = store.query(name)
    total = info["total_rows"]
    begin, end = store.my_row_range(name)
    mine = _shard_perm(total, begin, end, seed, rng)
    fresh = store.get_batch(name, mine)     # reads see old data
    store.barrier()                          # everyone done reading
    store.update(name, fresh, 0)             # then everyone swaps
    store.barrier()


def ragged_global_shuffle(store, name: str, seed: int) -> None:
    """Global shuffle of a ragged variable: sample i's (index row +
    values span) move TOGETHER to wherever the permutation sends it, and
    the pair is re-registered so the locality invariant (each sample's
    elements inside its owner's values shard) holds by construction.
    This is the SC'23 atomistic-workload shuffle (SURVEY §2.2) the
    fixed-width path cannot express. Collective; same seed everywhere.
    """
    if not store.is_ragged(name):
        raise ValueError(f"{name!r} is not a ragged variable")
    total = store.ragged_total(name)
    begin, end = store.my_row_range(f"{name}/index")
    src = _shard_perm(total, begin, end, seed, rng=None)
    values, lengths = store.get_ragged_batch(name, src)  # old data
    store.barrier()                                      # all reads done
    samples = (np.split(values, np.cumsum(lengths)[:-1])
               if len(lengths) else [])
    store.free(f"{name}/values")
    store.free(f"{name}/index")
    store.add_ragged(name, samples)
