"""Epoch-wise global shuffle.

Two paths, matching the BASELINE.json north star ("the per-epoch global
shuffle lowers to jax.lax.all_to_all over ICI"):

* **Device path** — for device-resident datasets: a fixed-shape, jit-stable
  shuffle built from (local permutation) ∘ (all_to_all block exchange) ∘
  (local permutation) under ``shard_map``. Shapes are static, so XLA
  compiles it once and reuses it every epoch; every row can land on every
  shard across epochs.

* **Host path** — for store-resident datasets: an arbitrary global
  permutation executed as a one-sided reshard through the store (each rank
  batch-fetches the rows the permutation assigns it, then atomically
  replaces its shard). This is the capability the reference's SC'23 paper
  attributes to ``MPI_Alltoallv`` but which is absent from the reference
  snapshot (verified, SURVEY §2.2) — implemented here as a target
  capability.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def all_to_all_rows(x: jax.Array, mesh: Mesh, axis: str = "dp") -> jax.Array:
    """Block exchange over `axis`: each shard splits its rows into
    `world` equal blocks and sends block j to peer j (a row-space
    transpose). Local row count must be divisible by the axis size."""

    def body(xs):
        world = jax.lax.psum(1, axis)
        blocks = xs.reshape((world, xs.shape[0] // world) + xs.shape[1:])
        out = jax.lax.all_to_all(blocks, axis, split_axis=0, concat_axis=0,
                                 tiled=False)
        return out.reshape(xs.shape)

    return jax.shard_map(body, mesh=mesh, in_specs=P(axis),
                         out_specs=P(axis))(x)


@partial(jax.jit, static_argnames=("mesh", "axis"))
def global_shuffle_epoch(x: jax.Array, key: jax.Array, *, mesh: Mesh,
                         axis: str = "dp") -> jax.Array:
    """Device-resident global shuffle with static shapes (compiles once,
    reused every epoch).

    local-perm ∘ all_to_all ∘ local-perm: the inner exchange moves every
    j-th block of every shard to shard j; the outer permutations are
    independent per shard and per epoch (key folded with the shard index),
    so the composition mixes rows across the whole global index space.
    """

    def body(xs, k):
        idx = jax.lax.axis_index(axis)
        world = jax.lax.psum(1, axis)
        k1, k2 = jax.random.split(jax.random.fold_in(k, idx))
        n = xs.shape[0]
        xs = jnp.take(xs, jax.random.permutation(k1, n), axis=0)
        blocks = xs.reshape((world, n // world) + xs.shape[1:])
        blocks = jax.lax.all_to_all(blocks, axis, split_axis=0,
                                    concat_axis=0, tiled=False)
        xs = blocks.reshape(xs.shape)
        # Second local permutation must differ across shards but not
        # correlate with the first; fold in world+idx.
        k3 = jax.random.fold_in(k2, world + idx)
        return jnp.take(xs, jax.random.permutation(k3, n), axis=0)

    return jax.shard_map(
        body, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(axis))(x, key)


def permute_rows(x: jax.Array, perm: jax.Array, mesh: Mesh,
                 axis: str = "dp") -> jax.Array:
    """Arbitrary global row permutation of a device-sharded array:
    ``out[i] = x[perm[i]]``. Implemented as a sharded gather — XLA lowers
    the cross-shard movement to collectives over ICI. Use
    :func:`global_shuffle_epoch` when any good shuffle will do (cheaper);
    use this when the exact permutation matters."""
    sharding = NamedSharding(mesh, P(axis))
    taken = jnp.take(x, perm, axis=0)
    return jax.lax.with_sharding_constraint(taken, sharding)


def host_global_shuffle(store, name: str, seed: int,
                        rng: Optional[np.random.Generator] = None) -> None:
    """Host-path global shuffle of a store variable, in place.

    Every rank computes the same seeded global permutation, batch-fetches
    the rows assigned to its shard (coalesced one-sided reads over the
    transport), waits at a barrier so all fetches complete against the OLD
    data, then atomically overwrites its shard. Collective: all ranks must
    call with the same seed.
    """
    info = store.query(name)
    total = info["total_rows"]
    begin, end = store.my_row_range(name)
    g = rng or np.random.default_rng(seed)
    perm = g.permutation(total)
    mine = perm[begin:end]
    fresh = store.get_batch(name, mine)     # reads see old data
    store.barrier()                          # everyone done reading
    store.update(name, fresh, 0)             # then everyone swaps
    store.barrier()
