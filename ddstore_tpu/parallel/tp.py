"""Tensor parallelism, the GSPMD way.

On TPU the idiomatic megatron-style TP is not hand-written collectives but
**parameter sharding rules**: column-shard the first matmul of each pair
(qkv, MLP up) over the ``tp`` mesh axis, row-shard the second (proj, MLP
down), leave norms/embeddings replicated — then let XLA's SPMD partitioner
insert the all-reduces exactly where megatron would put them. The model
code never changes; only where its parameters live does.

(The reference has no TP at all — SURVEY §2.2; this module is part of the
full dp/tp/pp/sp/ep set the framework supports.)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["shard_pytree", "megatron_rules", "expert_rules",
           "pp_stage_rules", "shardings_of"]


def shard_pytree(tree, mesh: Mesh, rules: Callable):
    """device_put every leaf according to ``rules(path, leaf) -> P``.

    ``path`` is a tuple of string keys (flax param dict keys included).
    """

    def name_of(entry):
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                return str(getattr(entry, attr))
        return str(entry)

    def place(path, leaf):
        spec = rules(tuple(name_of(p) for p in path), leaf)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, tree)


def megatron_rules(axis: str = "tp") -> Callable:
    """Sharding rules for the transformer family's parameter names:

    ==================  ============================
    qkv/up kernel        P(None, tp)   (column)
    proj/down kernel     P(tp, None)   (row)
    up bias              P(tp)
    msg/upd GNN kernels  replicated
    everything else      replicated
    ==================  ============================
    """

    def rules(path, leaf):
        names = set(path)
        if leaf.ndim >= 2:
            if {"qkv", "up"} & names and path[-1] == "kernel":
                return P(*([None] * (leaf.ndim - 1) + [axis]))
            if {"proj", "down"} & names and path[-1] == "kernel":
                return P(*([axis] + [None] * (leaf.ndim - 1)))
            if "head" in names and path[-1] == "kernel":
                return P(None, axis)
        if leaf.ndim == 1 and "up" in names and path[-1] == "bias":
            return P(axis)
        return P()

    return rules


def pp_stage_rules(pp_axis: str = "pp",
                   tp_axis: Optional[str] = None,
                   ep_axis: Optional[str] = None) -> Callable:
    """Sharding rules for a STAGE-STACKED parameter pytree (leading dim =
    stage, sharded over ``pp_axis``) with optional megatron TP — and,
    for MoE stacks, expert parallelism — on the remaining dims (the
    pp×tp and pp×ep compositions). ``megatron_rules``/``expert_rules``
    cannot be reused directly here: their leading-dim cases land on the
    STAGE dim in a stacked stack.

    ==================  =================================
    every leaf           dim 0 = P(pp)
    qkv/up kernel        P(pp, None, tp)   (column)
    proj/down kernel     P(pp, tp, None)   (row)
    up bias              P(pp, tp)
    moe w1 / w2          P(pp, ep, None, tp) / P(pp, ep, tp, None)
    moe b1 / b2          P(pp, ep, tp) / P(pp, ep, None)
    everything else      P(pp, None, ...)
    ==================  =================================
    """

    def rules(path, leaf):
        nd = leaf.ndim
        spec = [pp_axis] + [None] * (nd - 1)
        names = set(path)
        if "moe" in names:
            if path[-1] in ("w1", "w2", "b1", "b2") and nd >= 3:
                spec[1] = ep_axis  # expert dim (None when ep unset)
            if tp_axis:
                if path[-1] == "w1" and nd == 4:
                    spec[3] = tp_axis
                elif path[-1] == "w2" and nd == 4:
                    spec[2] = tp_axis
                elif path[-1] == "b1" and nd == 3:
                    spec[2] = tp_axis
        elif tp_axis:
            if path[-1] == "kernel" and nd >= 3:
                if {"qkv", "up"} & names:
                    spec[-1] = tp_axis
                elif {"proj", "down"} & names:
                    spec[1] = tp_axis
            elif path[-1] == "bias" and nd == 2 and "up" in names:
                spec[1] = tp_axis
        return P(*spec)

    return rules


def expert_rules(ep_axis: str = "ep",
                 tp_axis: Optional[str] = None) -> Callable:
    """Expert parallelism: shard the leading (expert) dim of MoE weights
    over ``ep_axis``; optionally compose with megatron TP for everything
    else (and the experts' hidden dim)."""
    base = megatron_rules(tp_axis) if tp_axis else None

    def rules(path, leaf):
        if "moe" in set(path):
            if path[-1] == "w1":
                return P(ep_axis, None, tp_axis)
            if path[-1] == "w2":
                return P(ep_axis, tp_axis, None)
            if path[-1] == "b1":
                return P(ep_axis, tp_axis)
            if path[-1] == "b2":
                return P(ep_axis, None)
            return P()  # router replicated
        return base(path, leaf) if base else P()

    return rules


def shardings_of(tree):
    """The pytree of existing shardings (to pass as jit in_shardings)."""
    return jax.tree_util.tree_map(lambda x: x.sharding, tree)
