"""Device-mesh and sharding helpers.

The store's unit of distribution is the host process (one shard per
TPU-VM host); the unit of compute distribution is the device mesh. These
helpers build the meshes the rest of the framework assumes:

* ``dp`` — data parallel (batch dimension; the reference's only strategy,
  via torch DDP, SURVEY §2.2),
* ``tp`` — tensor parallel (model dims),
* ``sp`` — sequence/context parallel (ring attention),
* ``pp`` — pipeline stages,
* ``ep`` — expert parallel (MoE routing).

Axes the caller does not ask for are simply absent — XLA sees only the
mesh it is given.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("pp", "dp", "ep", "sp", "tp")  # outer→inner; tp innermost so
# tensor-parallel collectives ride the fastest ICI links.


def make_mesh(axes: Dict[str, int],
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with the given axis sizes, e.g. ``{"dp": 4, "tp": 2}``.

    Axis order follows AXIS_ORDER so that tensor-parallel groups map to
    adjacent devices (fastest links), data-parallel groups to the outer
    dimension — the standard TPU layout recipe.
    """
    if devices is None:
        devices = jax.devices()
    names = [a for a in AXIS_ORDER if a in axes]
    extra = set(axes) - set(names)
    if extra:
        names += sorted(extra)
    sizes = [axes[a] for a in names]
    n = int(np.prod(sizes)) if sizes else 1
    if n > len(devices):
        raise ValueError(f"mesh wants {n} devices, have {len(devices)}")
    dev = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(dev, tuple(names))


def data_parallel_mesh(n: Optional[int] = None) -> Mesh:
    """1-D dp mesh over (up to) all devices."""
    devs = jax.devices()
    n = len(devs) if n is None else n
    return make_mesh({"dp": n}, devs)


def local_mesh() -> Mesh:
    """Mesh over this process's addressable devices only (one ICI island /
    one host) — the device-side analogue of a replica group."""
    return make_mesh({"dp": len(jax.local_devices())}, jax.local_devices())


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Sharding for a batch: leading dim split over `axis`, rest replicated."""
    return NamedSharding(mesh, P(axis))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch, axis: str = "dp"):
    """Assemble a globally-sharded device array from this process's local
    batch — the device-staging step of the pipeline (reference analogue:
    ``data.to(device)`` in the DDP loop, vae-ddp.py:244; here it is a
    sharded transfer so each DP group gets its slice with no host gather).

    Works single-process (slices the local batch over local devices) and
    multi-process (each process contributes its slice of the global batch).
    """
    sharding = batch_sharding(mesh, axis)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(sharding, x), batch)
