"""Pipeline parallelism: GPipe scheduling over the ``pp`` mesh axis.

Layers are grouped into S stages whose parameters live stacked along a
leading stage dimension sharded over ``pp`` (so each device holds one
stage). Microbatches stream through the ring: at every schedule step each
device applies its stage to the activation it holds and ``ppermute``s the
result to the next stage, for M + S - 1 steps (the classic GPipe fill +
drain bubble — idle fraction (S-1)/(M+S-1)). The whole schedule is a
``lax.scan`` inside ``shard_map`` inside jit — reverse-mode
differentiable, so the backward pipeline comes from autodiff for free.

Composition with data parallelism: pass ``dp_axis`` and the microbatch
dimension of ``x`` is sharded across ``dp`` — each (dp, pp) device holds
1/dp of every microbatch and 1/pp of the parameters. The ``ppermute``
moves activations stage-to-stage within a dp slice only; nothing is
replicated (this fixes round-1's version, which kept the full microbatch
tensor on every device). Memory per device for activations is
O(M · mb/dp); pass ``remat=True`` to rematerialize each stage in the
backward pass (GPipe's activation-memory trick — with per-stage remat
the live set during backward is one stage's activations, the same
working set a 1F1B schedule targets).

(PP is absent in the reference — SURVEY §2.2; with tp.py, moe.py,
ring_attention.py and the DP loaders this completes dp/tp/pp/sp/ep.)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """Stack a list of S identically-structured stage pytrees along a new
    leading dim (shard it over ``pp`` with ``shard_pytree`` or let
    ``pipeline_apply``'s in_specs do it)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params)


def pipeline_apply(stage_fn: Callable, stage_params, x, *, mesh: Mesh,
                   axis: str = "pp", dp_axis: Optional[str] = None,
                   remat: bool = False):
    """Run ``x`` through S pipeline stages of ``stage_fn``.

    stage_fn: ``(params, act) -> act`` — one stage's computation; the
        activation shape must be stage-invariant.
    stage_params: pytree whose leaves have leading dim S (stage-stacked);
        sharded over ``axis``, replicated over the other mesh axes.
    x: ``(M, mb, ...)`` microbatches. With ``dp_axis`` the ``mb`` dim is
        sharded over it; otherwise x is replicated (small-input path).
    remat: rematerialize ``stage_fn`` in the backward pass.
    Returns ``(M, mb, ...)`` outputs with the same sharding as ``x``.
    """
    s = mesh.shape[axis]
    m = x.shape[0]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != s:
            # Without this check a (2S, ...) stack on an S-device axis
            # would silently run only every other stage.
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != pp axis "
                f"size {s}")
    if dp_axis is not None and x.shape[1] % mesh.shape[dp_axis]:
        raise ValueError(
            f"dp axis size {mesh.shape[dp_axis]} must divide microbatch "
            f"size {x.shape[1]}")
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(params, xs):
        stage = jax.lax.axis_index(axis)
        my = jax.tree_util.tree_map(lambda l: l[0], params)
        perm = [(j, (j + 1) % s) for j in range(s)]
        buf = jnp.zeros(xs.shape[1:], xs.dtype)

        def sched(buf, t):
            # Stage 0 injects microbatch t (clamped during drain); other
            # stages consume what arrived from upstream last step.
            inject = jax.lax.dynamic_index_in_dim(
                xs, jnp.minimum(t, m - 1), 0, keepdims=False)
            act = jnp.where(stage == 0, inject, buf)
            y = fn(my, act)
            return jax.lax.ppermute(y, axis, perm), y

        _, ys = jax.lax.scan(sched, buf, jnp.arange(m + s - 1))
        # ys[t] on the LAST stage at t >= s-1 is microbatch t-(s-1)'s
        # output; zero elsewhere and psum over pp so every stage's copy
        # of the (dp-sharded) output is identical.
        outs = jnp.where(stage == s - 1, ys[s - 1:], 0.0)
        return jax.lax.psum(outs, axis)

    xspec = P(None, dp_axis) if dp_axis is not None else P()
    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), xspec),
        out_specs=xspec,
        check_vma=False,
    )(stage_params, x)
