"""Pipeline parallelism: GPipe scheduling over the ``pp`` mesh axis.

Layers are grouped into S stages whose parameters live stacked along a
leading stage dimension sharded over ``pp`` (so each device holds one
stage). Microbatches stream through the ring: at every schedule step each
device applies its stage to the activation it holds and ``ppermute``s the
result to the next stage, for M + S - 1 steps (the classic GPipe fill +
drain bubble — idle fraction (S-1)/(M+S-1)). The whole schedule is a
``lax.scan`` inside ``shard_map`` inside jit — reverse-mode
differentiable, so the backward pipeline comes from autodiff for free.

Composition with data parallelism: pass ``dp_axis`` and the microbatch
dimension of ``x`` is sharded across ``dp`` — each (dp, pp) device holds
1/dp of every microbatch and 1/pp of the parameters. The ``ppermute``
moves activations stage-to-stage within a dp slice only; nothing is
replicated (this fixes round-1's version, which kept the full microbatch
tensor on every device). Memory per device for activations is
O(M · mb/dp); pass ``remat=True`` to rematerialize each stage in the
backward pass (GPipe's activation-memory trick — with per-stage remat
the live set during backward is one stage's activations, the same
working set a 1F1B schedule targets).

(PP is absent in the reference — SURVEY §2.2; with tp.py, moe.py,
ring_attention.py and the DP loaders this completes dp/tp/pp/sp/ep.)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply", "pipeline_1f1b", "pipeline_interleaved",
           "pipeline_interleaved_1f1b",
           "stack_stage_params", "interleave_stage_params",
           "interleave_order"]


def _manual_axes(axis: str, dp_axis: Optional[str]):
    """Mesh axes the pipeline schedules are MANUAL over. Every other axis
    (tp, sp, ep, fsdp) stays in the compiler's hands: a stage_fn whose
    parameters carry megatron shardings gets its all-reduces from GSPMD,
    and a stage_fn that rings attention over ``sp`` opens its own nested
    shard_map — both compose with the schedule instead of being frozen
    out by a fully-manual region (pp×tp / pp×sp, VERDICT r3 missing #1)."""
    return frozenset({axis} | ({dp_axis} if dp_axis is not None else set()))


def stack_stage_params(per_stage_params):
    """Stack a list of S identically-structured stage pytrees along a new
    leading dim (shard it over ``pp`` with ``shard_pytree`` or let
    ``pipeline_apply``'s in_specs do it)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params)


def pipeline_apply(stage_fn: Callable, stage_params, x, *, mesh: Mesh,
                   axis: str = "pp", dp_axis: Optional[str] = None,
                   remat: bool = False, with_aux: bool = False):
    """Run ``x`` through S pipeline stages of ``stage_fn``.

    stage_fn: ``(params, act) -> act`` — one stage's computation; the
        activation shape must be stage-invariant. With ``with_aux`` it
        returns ``(act, aux)`` where ``aux`` is a scalar side loss (e.g.
        MoE load balancing); bubble-step garbage contributions are
        masked out and the result is differentiable through autodiff.
    stage_params: pytree whose leaves have leading dim S (stage-stacked);
        sharded over ``axis``, replicated over the other mesh axes.
    x: ``(M, mb, ...)`` microbatches. With ``dp_axis`` the ``mb`` dim is
        sharded over it; otherwise x is replicated (small-input path).
    remat: rematerialize ``stage_fn`` in the backward pass.
    Returns ``(M, mb, ...)`` outputs with the same sharding as ``x``;
    with ``with_aux``, ``(outputs, aux)`` where ``aux`` is the
    per-microbatch mean of the summed stage auxes (dp-averaged).
    """
    s = mesh.shape[axis]
    m = x.shape[0]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != s:
            # Without this check a (2S, ...) stack on an S-device axis
            # would silently run only every other stage.
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != pp axis "
                f"size {s}")
    if dp_axis is not None and x.shape[1] % mesh.shape[dp_axis]:
        raise ValueError(
            f"dp axis size {mesh.shape[dp_axis]} must divide microbatch "
            f"size {x.shape[1]}")
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(params, xs):
        stage = jax.lax.axis_index(axis)
        my = jax.tree_util.tree_map(lambda l: l[0], params)
        perm = [(j, (j + 1) % s) for j in range(s)]
        buf = jnp.zeros(xs.shape[1:], xs.dtype)

        def sched(buf, t):
            # Stage 0 injects microbatch t (clamped during drain); other
            # stages consume what arrived from upstream last step.
            inject = jax.lax.dynamic_index_in_dim(
                xs, jnp.minimum(t, m - 1), 0, keepdims=False)
            act = jnp.where(stage == 0, inject, buf)
            if with_aux:
                y, aux = fn(my, act)
                # Stage s computes microbatch t-s at step t; fill/drain
                # steps chew garbage whose aux must not count.
                valid = (t >= stage) & (t - stage < m)
                aux = jnp.where(valid, aux.astype(jnp.float32), 0.0)
            else:
                y = fn(my, act)
                aux = jnp.zeros((), jnp.float32)
            return jax.lax.ppermute(y, axis, perm), (y, aux)

        _, (ys, auxs) = jax.lax.scan(sched, buf, jnp.arange(m + s - 1))
        # ys[t] on the LAST stage at t >= s-1 is microbatch t-(s-1)'s
        # output; zero elsewhere and psum over pp so every stage's copy
        # of the (dp-sharded) output is identical.
        outs = jnp.where(stage == s - 1, ys[s - 1:], 0.0)
        outs = jax.lax.psum(outs, axis)
        if not with_aux:
            return outs
        aux = jax.lax.psum(auxs.sum(), axis) / m
        if dp_axis is not None and mesh.shape.get(dp_axis, 1) > 1:
            aux = jax.lax.pmean(aux, dp_axis)
        return outs, aux

    xspec = P(None, dp_axis) if dp_axis is not None else P()
    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), xspec),
        out_specs=(xspec, P()) if with_aux else xspec,
        axis_names=_manual_axes(axis, dp_axis),
        check_vma=False,
    )(stage_params, x)


def interleave_order(n_stages: int, n_virtual: int):
    """THE device-major chunk order for :func:`pipeline_interleaved`:
    ``order[p]`` is the model-order chunk held at stack position ``p``,
    with position ``d·V + v`` holding chunk ``v·S + d``. Single source —
    the model-side splitters (``lm_to_stages``/``lm_from_stages``) must
    use this same list or devices would run the wrong chunks with no
    shape error. Identity at V=1."""
    return [v * n_stages + d for d in range(n_stages)
            for v in range(n_virtual)]


def interleave_stage_params(per_chunk_params, n_stages: int):
    """Stack V·S per-chunk pytrees for :func:`pipeline_interleaved`.

    Chunk ``k`` (model order) runs on device ``k mod S``; a plain
    ``P(pp)`` shard of the stacked leading dim hands device ``d`` the
    contiguous rows ``[d·V, (d+1)·V)``, so the stack must be built
    device-major (see :func:`interleave_order`).
    """
    c = len(per_chunk_params)
    if c % n_stages:
        raise ValueError(
            f"{c} chunks do not divide over {n_stages} stages")
    order = interleave_order(n_stages, c // n_stages)
    return stack_stage_params([per_chunk_params[k] for k in order])


def _check_interleave_args(s: int, n_virtual, stage_params, x, mesh: Mesh,
                           dp_axis: Optional[str]):
    """Shared argument validation for the two interleaved schedules.
    Returns ``(v, c, m)``. The M-divisibility constraint applies only at
    ``V > 1`` — ``V=1`` degenerates to the GPipe / plain-1F1B schedules,
    which take any M (the group tiling is what constrains the genuinely
    interleaved case)."""
    v = int(n_virtual)
    if v < 1:
        raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
    c = v * s
    m = x.shape[0]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != c:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != "
                f"n_virtual*pp = {c}")
    if v > 1 and m % s:
        raise ValueError(
            f"microbatch count {m} must be a multiple of the pp axis "
            f"size {s} (groups of S share a V·S-tick span)")
    if dp_axis is not None and x.shape[1] % mesh.shape[dp_axis]:
        raise ValueError(
            f"dp axis size {mesh.shape[dp_axis]} must divide microbatch "
            f"size {x.shape[1]}")
    return v, c, m


def pipeline_interleaved(stage_fn: Callable, stage_params, x, *,
                         mesh: Mesh, n_virtual: int, axis: str = "pp",
                         dp_axis: Optional[str] = None,
                         remat: bool = False, with_aux: bool = False):
    """Interleaved virtual-stage pipeline (Megatron-style looping) — the
    GPipe bubble ``(S-1)/(M+S-1)`` shrinks to ``(S-1)/(M·V+S-1)``.

    The model is split into ``C = V·S`` chunks instead of S stages;
    device ``d`` holds chunks ``{d, d+S, …, d+(V-1)S}``, so every
    activation hop — including chunk ``vS+d`` → ``vS+d+1`` across the
    wrap — is the same +1 ring ``ppermute``. Microbatches are injected
    in groups of S, group ``g`` offset by ``g·V·S`` ticks; device ``d``
    at tick ``t`` serves ``rel = t - d`` as group ``g = rel // VS``,
    local chunk ``v = (rel mod VS) // S``, microbatch
    ``i = g·S + rel mod S``. Each device is busy every tick of its
    span (the V·S tick residues within a group are exactly
    ``{j + vS}``), so the only idle time is the S-1-tick stagger —
    per-tick work is 1/V of a stage, hence the V× smaller bubble.
    ``n_virtual=1`` reduces to :func:`pipeline_apply`'s schedule.

    stage_fn: ``(chunk_params, act) -> act`` (``(act, aux)`` under
        ``with_aux``), activation shape chunk-invariant.
    stage_params: pytree with leading dim ``V·S`` in DEVICE-MAJOR order
        (build it with :func:`interleave_stage_params`), sharded over
        ``axis``.
    x: ``(M, mb, ...)`` microbatches, ``M`` divisible by S (pad the
        microbatch count if needed); ``mb`` sharded over ``dp_axis``.
    Returns ``(M, mb, ...)`` outputs (with ``with_aux``, ``(outputs,
    aux)`` like :func:`pipeline_apply`). Reverse-mode differentiable;
    the backward schedule is the scan reversed, with the same bubble.
    """
    s = mesh.shape[axis]
    v, c, m = _check_interleave_args(s, n_virtual, stage_params, x, mesh,
                                     dp_axis)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    ticks = m * v + s - 1

    def body(params, xs):
        d = jax.lax.axis_index(axis)
        perm = [(j, (j + 1) % s) for j in range(s)]
        buf = jnp.zeros(xs.shape[1:], xs.dtype)
        # O(M) output accumulator instead of stacking all M·V+S-1 tick
        # outputs (V× the GPipe stack for the same result).
        out0 = jnp.zeros((m,) + xs.shape[1:], xs.dtype)

        def sched(carry, t):
            buf, outs, aux_acc = carry
            rel = t - d
            active = (rel >= 0) & (rel < m * v)
            relc = jnp.clip(rel, 0, m * v - 1)
            g = relc // (v * s)
            vv = (relc % (v * s)) // s
            i = g * s + relc % s
            my = jax.tree_util.tree_map(
                lambda l: jax.lax.dynamic_index_in_dim(
                    l, vv, 0, keepdims=False), params)
            inject = jax.lax.dynamic_index_in_dim(xs, i, 0, keepdims=False)
            a_in = jnp.where((d == 0) & (vv == 0), inject, buf)
            if with_aux:
                y, aux = fn(my, a_in)
                aux_acc = aux_acc + jnp.where(
                    active, aux.astype(jnp.float32), 0.0)
            else:
                y = fn(my, a_in)
            final = active & (d == s - 1) & (vv == v - 1)
            prev = jax.lax.dynamic_index_in_dim(outs, i, 0, keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(final, y, prev), i, 0)
            return (jax.lax.ppermute(y, axis, perm), outs, aux_acc), None

        (_, outs, aux_acc), _ = jax.lax.scan(
            sched, (buf, out0, jnp.zeros((), jnp.float32)),
            jnp.arange(ticks))
        # Only the last device wrote real rows (the `final` mask is
        # device-gated); psum replicates them everywhere.
        outs = jax.lax.psum(outs, axis)
        if not with_aux:
            return outs
        aux = jax.lax.psum(aux_acc, axis) / m
        if dp_axis is not None and mesh.shape.get(dp_axis, 1) > 1:
            aux = jax.lax.pmean(aux, dp_axis)
        return outs, aux

    xspec = P(None, dp_axis) if dp_axis is not None else P()
    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), xspec),
        out_specs=(xspec, P()) if with_aux else xspec,
        axis_names=_manual_axes(axis, dp_axis),
        check_vma=False,
    )(stage_params, x)


def pipeline_1f1b(stage_fn: Callable, loss_fn: Callable, stage_params,
                  loss_params, x, aux, *, mesh: Mesh, axis: str = "pp",
                  dp_axis: Optional[str] = None,
                  with_aux: bool = False, aux_weight: float = 0.0):
    """1F1B pipeline schedule: fused forward+backward with O(S) activation
    stash per device instead of GPipe-autodiff's O(M).

    The GPipe path (:func:`pipeline_apply` under ``jax.value_and_grad``)
    runs the whole forward schedule, saving every scan step's activations,
    then the whole backward — the live set grows with the number of
    microbatches M. Here the backward of microbatch ``i`` starts as soon
    as its loss cotangent exists: each tick every device does one forward
    half (receive activation, stash the stage input, send downstream) and
    one backward half (receive cotangent from downstream, re-run its
    stage under ``jax.vjp`` from the stashed input, accumulate parameter
    grads, send the input cotangent upstream). Microbatch ``i``'s stash
    at stage ``s`` retires after ``2(S-1-s)`` ticks, so a circular buffer
    of ``2S-1`` slots bounds activation memory by the stage count — the
    classic 1F1B property (same bubble as non-interleaved GPipe, far less
    memory). Forward work is recomputed in the backward half
    (recompute-p, the same trade ``remat=True`` makes on the GPipe path).

    stage_fn: ``(params, act) -> act``, activation shape stage-invariant.
    loss_fn: ``(loss_params, act, aux_mb) -> scalar mean loss`` applied to
        the LAST stage's output (e.g. LM head + cross-entropy); its
        parameter gradients are accumulated on the last stage.
    stage_params: stage-stacked pytree (leading dim S, sharded over
        ``axis``); loss_params: replicated pytree.
    x / aux: ``(M, mb, ...)`` microbatched inputs / loss targets, ``mb``
        sharded over ``dp_axis`` if given.
    with_aux / aux_weight: when set, ``stage_fn`` returns ``(act,
        side_loss)`` (e.g. MoE load balancing) and the returned loss
        includes ``aux_weight * mean_microbatch(sum_stages side_loss)``.
        The side-loss gradient is injected locally: each stage's
        backward vjp receives ``aux_weight / M`` as the scalar cotangent
        alongside the activation cotangent — no extra communication.

    Returns ``(loss, stage_grads, loss_grads, dx)`` — the mean microbatch
    loss, gradients for the stage stack (sharded like it), for
    ``loss_params``, and for ``x`` (so the caller can chain upstream
    layers, e.g. the embedding, through ``jax.vjp``). All gradients are
    exact for ``mean_i loss_fn(loss_params, stages(x_i), aux_i)`` and are
    already averaged over ``dp_axis``.
    """
    # The fused schedule is the V=1 case of the interleaved one (the
    # tick decode degenerates to f = t - stage / b = t - (2S-2-stage));
    # one implementation, asserted tick-for-tick equivalent in
    # tests/test_pipeline.py::test_interleaved_1f1b_v1_equals_1f1b.
    return pipeline_interleaved_1f1b(
        stage_fn, loss_fn, stage_params, loss_params, x, aux, mesh=mesh,
        n_virtual=1, axis=axis, dp_axis=dp_axis, with_aux=with_aux,
        aux_weight=aux_weight)


def pipeline_interleaved_1f1b(stage_fn: Callable, loss_fn: Callable,
                              stage_params, loss_params, x, aux, *,
                              mesh: Mesh, n_virtual: int,
                              axis: str = "pp",
                              dp_axis: Optional[str] = None,
                              with_aux: bool = False,
                              aux_weight: float = 0.0):
    """Fused interleaved 1F1B: virtual stages AND the fused
    forward/backward schedule — the Megatron production combination.

    Forward is :func:`pipeline_interleaved`'s schedule (chunk ``k`` of
    microbatch ``i = g·S + j`` at tick ``τf = g·C + j + k`` on device
    ``k mod S``, ``C = V·S``); the backward of ``(i, k)`` runs at
    ``τb = g·C + j + 2(C-1) - k`` on the same device, its cotangent
    hopping the -1 ring one chunk per tick. Both halves decode
    uniquely from ``(t, d)``: the forward as in the interleaved
    schedule, the backward via ``u = ⌊(t + d - 2(C-1)) / S⌋ = g·V - w``
    with ``w ∈ [0, V)`` forcing ``g = ⌈u/V⌉``. Each tick every device
    does one chunk-forward and one chunk-backward (recompute-p via
    ``jax.vjp`` from the stashed chunk input, exactly like
    :func:`pipeline_1f1b`); fill+drain is ``(V+1)S-2`` ticks of 1/V-
    stage work versus plain 1F1B's ``(2S-2)·V`` — the bubble shrinks
    by ``2V/(V+1)``×. The input stash is a ``2C-1``-slot ring (an
    entry written at ``τf`` retires after ``2(C-1-k)`` ticks), so
    activation memory is bounded by the chunk count: more than plain
    1F1B's ``2S-1`` stage inputs, still independent of M — pick V so
    ``2·V·S < M`` and both wins hold. ``n_virtual=1`` IS
    :func:`pipeline_1f1b`'s schedule tick-for-tick.

    Arguments and returns exactly as :func:`pipeline_1f1b`, except
    ``stage_params`` carries the V·S device-major chunk stack (see
    :func:`interleave_order`) and, for ``n_virtual > 1``, M must be a
    multiple of the pp axis size (``V=1`` takes any M, like plain
    1F1B).
    """
    s = mesh.shape[axis]
    v, c, m = _check_interleave_args(s, n_virtual, stage_params, x, mesh,
                                     dp_axis)

    def body(params, lparams, xs, auxs):
        d = jax.lax.axis_index(axis)
        fperm = [(j, (j + 1) % s) for j in range(s)]
        bperm = [(j, (j - 1) % s) for j in range(s)]
        nstash = 2 * c - 1
        ticks = m * v + c + s - 2

        def sel(tree, idx):
            return jax.tree_util.tree_map(
                lambda l: jax.lax.dynamic_index_in_dim(
                    l, idx, 0, keepdims=False), tree)

        zerog = jax.tree_util.tree_map(
            lambda l: jnp.zeros(l.shape, jnp.float32), params)
        zerolg = jax.tree_util.tree_map(
            lambda l: jnp.zeros(l.shape, jnp.float32), lparams)
        carry0 = (
            jnp.zeros((nstash,) + xs.shape[1:], xs.dtype),  # input stash
            jnp.zeros(xs.shape[1:], xs.dtype),              # fwd in-flight
            jnp.zeros(xs.shape[1:], xs.dtype),              # bwd in-flight
            jnp.zeros((m,) + xs.shape[1:], xs.dtype),       # dx scatter
            zerog, zerolg,
            jnp.zeros((2,), jnp.float32),  # [head loss acc, side-aux acc]
        )

        def masked_add(pred, acc, delta):
            return jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(pred, g.astype(jnp.float32),
                                           0.0),
                acc, delta)

        def tick(carry, t):
            stash, fwd_buf, bwd_buf, dxacc, gacc, lgacc, lacc = carry

            # -- forward half: the interleaved schedule's decode -------
            rel = t - d
            active_f = (rel >= 0) & (rel < m * v)
            relc = jnp.clip(rel, 0, m * v - 1)
            vv = (relc % c) // s
            fi = (relc // c) * s + relc % s
            my_f = sel(params, vv)
            inject = jax.lax.dynamic_index_in_dim(xs, fi, 0,
                                                  keepdims=False)
            a_in = jnp.where((d == 0) & (vv == 0), inject, fwd_buf)
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, a_in, jnp.mod(t, nstash), 0)
            if with_aux:
                y, side = stage_fn(my_f, a_in)
            else:
                y = stage_fn(my_f, a_in)
                side = jnp.zeros((), jnp.float32)

            last_f = (d == s - 1) & (vv == v - 1)
            aux_mb = jax.lax.dynamic_index_in_dim(auxs, fi, 0,
                                                  keepdims=False)

            def do_loss(args):
                lp, yy, aa = args
                lval, vjp = jax.vjp(
                    lambda lp2, y2: loss_fn(lp2, y2, aa), lp, yy)
                dlp, dy = vjp(jnp.ones((), lval.dtype) / m)
                return lval, dlp, dy

            def no_loss(args):
                lp, yy, _ = args
                return (jnp.zeros((), jnp.float32),
                        jax.tree_util.tree_map(jnp.zeros_like, lp),
                        jnp.zeros_like(yy))

            lval, dlp, dy_last = jax.lax.cond(
                last_f, do_loss, no_loss, (lparams, y, aux_mb))

            # -- backward half: τb = g·C + j + 2(C-1) - (w·S + d) ------
            r = t + d - 2 * (c - 1)
            jb = jnp.mod(r, s)
            u = (r - jb) // s          # floor: = g·V - w
            gb = (u + v - 1) // v      # ceil(u / V) — forces w ∈ [0, V)
            w = gb * v - u
            bi = gb * s + jb
            active_b = (gb >= 0) & (bi < m)
            wc = jnp.clip(w, 0, v - 1)
            bic = jnp.clip(bi, 0, m - 1)
            # The stashed input for (bi, w·S+d) was written at its
            # forward tick g·C + j + k.
            tf_b = gb * c + jb + w * s + d
            a_stash = jax.lax.dynamic_index_in_dim(
                stash, jnp.mod(tf_b, nstash), 0, keepdims=False)
            my_b = sel(params, wc)
            cot_in = jnp.where((d == s - 1) & (w == v - 1), dy_last,
                               bwd_buf).astype(y.dtype)
            _, svjp = jax.vjp(stage_fn, my_b, a_stash)
            if with_aux:
                side_cot = jnp.where(active_b, aux_weight / m, 0.0)
                dmy, da = svjp((cot_in, side_cot.astype(jnp.float32)))
            else:
                dmy, da = svjp(cot_in)

            gacc = jax.tree_util.tree_map(
                lambda a, g: a.at[wc].add(
                    jnp.where(active_b, g.astype(jnp.float32), 0.0)),
                gacc, dmy)
            lgacc = masked_add(active_f & last_f, lgacc, dlp)
            lacc = lacc + jnp.stack([
                jnp.where(active_f & last_f, lval.astype(jnp.float32),
                          0.0),
                jnp.where(active_f, side.astype(jnp.float32), 0.0),
            ])
            # Chunk 0 (w == 0 on device 0) emits dL/dx for microbatch
            # bi; scatter keeps the buffer O(M) instead of O(ticks).
            prev = jax.lax.dynamic_index_in_dim(dxacc, bic, 0,
                                                keepdims=False)
            dxacc = jax.lax.dynamic_update_index_in_dim(
                dxacc, jnp.where((d == 0) & (w == 0) & active_b, da,
                                 prev), bic, 0)

            fwd_buf = jax.lax.ppermute(y, axis, fperm)
            bwd_buf = jax.lax.ppermute(da, axis, bperm)
            return (stash, fwd_buf, bwd_buf, dxacc, gacc, lgacc,
                    lacc), None

        final, _ = jax.lax.scan(tick, carry0, jnp.arange(ticks))
        (_, _, _, dxacc, gacc, lgacc, lacc) = final
        dx = jax.lax.psum(dxacc, axis)
        accs = jax.lax.psum(lacc, axis) / m
        loss = accs[0] + aux_weight * accs[1]
        lgrads = jax.tree_util.tree_map(lambda l: jax.lax.psum(l, axis),
                                        lgacc)
        if dp_axis is not None and mesh.shape.get(dp_axis, 1) > 1:
            loss = jax.lax.pmean(loss, dp_axis)
            gacc = jax.tree_util.tree_map(
                lambda l: jax.lax.pmean(l, dp_axis), gacc)
            lgrads = jax.tree_util.tree_map(
                lambda l: jax.lax.pmean(l, dp_axis), lgrads)
            dx = dx / mesh.shape[dp_axis]
        return loss, gacc, lgrads, dx

    xspec = P(None, dp_axis) if dp_axis is not None else P()
    loss_, gstack, lgrads, dx = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P(), xspec, xspec),
        out_specs=(P(), P(axis), P(), xspec),
        axis_names=_manual_axes(axis, dp_axis),
        check_vma=False,
    )(stage_params, loss_params, x, aux)
    gstack = jax.tree_util.tree_map(lambda g, p: g.astype(p.dtype), gstack,
                                    stage_params)
    lgrads = jax.tree_util.tree_map(lambda g, p: g.astype(p.dtype), lgrads,
                                    loss_params)
    return loss_, gstack, lgrads, dx
