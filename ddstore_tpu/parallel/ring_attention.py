"""Ring attention: exact attention over a sequence-parallel mesh axis.

Long-context support the reference lacks entirely (SURVEY §2.2 lists
SP/CP/ring attention as absent). Q, K, V are sharded along the sequence
dimension over the ``sp`` mesh axis; each device keeps its Q chunk
resident and the K/V chunks rotate around the ring with
``jax.lax.ppermute`` (XLA lowers this to ICI neighbor exchanges that
overlap with the per-step attention compute). Per-step partial results
combine with the same online-softmax algebra flash attention uses across
key blocks — each step yields ``(out_i, lse_i)`` and the running pair is
reweighted by ``exp(lse - m)`` — so the result is EXACT attention over the
full sequence, with O(S/n) memory per device and n ring steps.

On TPU the per-step block computation is the Pallas flash kernel, so each
ring step is O(block) memory — without it each step materializes an
(S/n)×(S/n) score matrix, capping exactly the context length the sp axis
exists to extend. The kernel takes its causal offsets statically, while
the ring offsets are traced (``axis_index``); with equal chunks every
(device, step) pair is one of three STATIC cases — kv chunk fully in the
past (unmasked flash), the diagonal chunk (plain causal flash at zero
offset), or fully in the future (skipped) — so a ``lax.cond`` selects
between statically-configured kernels. Non-TPU backends default to the
XLA path (:func:`ddstore_tpu.ops.attention.mha_reference`).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import flash_attention, mha_reference

__all__ = ["ring_attention", "ring_self_attention"]


def _combine(acc_out, acc_lse, out_i, lse_i):
    """Merge two normalized attention partials (f32 math)."""
    m = jnp.maximum(acc_lse, lse_i)
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.where(jnp.isfinite(acc_lse), jnp.exp(acc_lse - safe_m), 0.0)
    w2 = jnp.where(jnp.isfinite(lse_i), jnp.exp(lse_i - safe_m), 0.0)
    denom = jnp.maximum(w1 + w2, 1e-30)
    out = (acc_out * w1[..., None] + out_i.astype(jnp.float32)
           * w2[..., None]) / denom[..., None]
    lse = jnp.where(jnp.isfinite(m), safe_m + jnp.log(denom), -jnp.inf)
    return out, lse


def _ring_body(q, k, v, idx_chunk, *, axis: str, n: int, causal: bool,
               use_flash: bool):
    """shard_map body: local chunks (B, H, S/n, D). ``idx_chunk`` is this
    device's slice of an arange over the ring axis — the ring position.
    NOT ``jax.lax.axis_index``: its lowering computes the position from
    the full device id, which re-binds every mesh axis and breaks when
    this shard_map is nested inside another manual region (pp×sp)."""
    idx = idx_chunk[0]
    sq, sk = q.shape[2], k.shape[2]
    q_off = idx * sq
    perm = [(j, (j + 1) % n) for j in range(n)]

    def masked(args):
        return (jnp.zeros(q.shape, q.dtype),
                jnp.full(q.shape[:3], -jnp.inf, jnp.float32))

    acc_out = jnp.zeros(q.shape, jnp.float32)
    acc_lse = jnp.full(q.shape[:3], -jnp.inf, jnp.float32)
    for step in range(n):
        # After `step` rotations this device holds the kv chunk originally
        # owned by (idx - step) mod n.
        src = (idx - step) % n
        kv_off = src * sk

        if use_flash:
            # The kernel's offsets are static; the traced ring position
            # reduces to three static mask shapes (module docstring).
            def attend_past(args):
                qq, kk, vv = args
                return flash_attention(qq, kk, vv, causal=False)

            def attend_diag(args):
                qq, kk, vv = args
                return flash_attention(qq, kk, vv, causal=True)

            if causal:
                out_i, lse_i = jax.lax.cond(
                    src == idx, attend_diag,
                    lambda args: jax.lax.cond(src < idx, attend_past,
                                              masked, args),
                    (q, k, v))
            else:
                out_i, lse_i = attend_past((q, k, v))
        else:
            def attend(args):
                qq, kk, vv = args
                return mha_reference(qq, kk, vv, causal=causal,
                                     q_offset=q_off, kv_offset=kv_off)

            if causal:
                # A kv chunk entirely in this q chunk's future is fully
                # masked: skip its O(S²/n²) compute on devices where that
                # holds (half of all (device, step) pairs — the ring-level
                # twin of the flash kernel's per-block `live` predicate).
                out_i, lse_i = jax.lax.cond(src <= idx, attend, masked,
                                            (q, k, v))
            else:
                out_i, lse_i = attend((q, k, v))
        acc_out, acc_lse = _combine(acc_out, acc_lse, out_i, lse_i)
        if step < n - 1:
            k = jax.lax.ppermute(k, axis, perm)
            v = jax.lax.ppermute(v, axis, perm)
    return acc_out.astype(q.dtype), acc_lse


@functools.lru_cache(maxsize=64)
def _eager_ring(mesh, bspec, hspec, axis, n, causal, use_flash):
    """Jitted ring shard_map for EAGER callers, cached on everything the
    trace depends on (shapes re-key inside jax.jit itself)."""
    body = functools.partial(_ring_body, axis=axis, n=n, causal=causal,
                             use_flash=use_flash)
    spec = P(bspec, hspec, axis, None)
    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, P(axis)),
        out_specs=(spec, P(bspec, hspec, axis)),
        axis_names=frozenset(a for a in (axis, bspec, hspec)
                             if a is not None),
        check_vma=False,
    ))


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh: Mesh, axis: str = "sp", causal: bool = False,
                   batch_axis: Optional[str] = None,
                   heads_axis: Optional[str] = None, impl: str = "auto"
                   ) -> Tuple[jax.Array, jax.Array]:
    """Exact attention over (B, H, S, D) with S sharded over ``axis``.

    Returns ``(out, lse)`` like the ops-level kernels. ``batch_axis``
    optionally shards B over a data-parallel mesh axis (defaults to "dp"
    when the mesh has one); ``heads_axis`` shards H over a tensor-parallel
    axis (sp×tp composition: heads are independent in attention, so each
    tp shard rings only its own heads and the two axes compose without
    any cross-communication). Callable inside jit: shard_map composes.

    impl: "flash" (Pallas kernel per ring step — O(block) memory),
    "xla" (mha_reference), or "auto" (flash on TPU when chunk shapes
    allow, xla otherwise).
    """
    n = mesh.shape[axis]
    if batch_axis is None and "dp" in mesh.shape:
        batch_axis = "dp"
    bspec = batch_axis if (batch_axis and mesh.shape.get(batch_axis, 1) > 1) \
        else None
    hspec = heads_axis if (heads_axis
                           and mesh.shape.get(heads_axis, 1) > 1) else None
    # Nesting (pp×sp): when called from inside another shard_map (e.g. a
    # pipeline stage manual over pp/dp), the inner shard_map must use the
    # CONTEXT abstract mesh, and axes that context already split manually
    # (dp inside the pipeline body) must drop out of the specs — the
    # arrays in hand are already local chunks along them.
    sm_mesh = mesh
    ctx = jax.sharding.get_abstract_mesh()
    if ctx is not None and not ctx.empty:
        Manual = jax.sharding.AxisType.Manual
        already = {name for name, t in zip(ctx.axis_names, ctx.axis_types)
                   if t == Manual}
        if already:
            if axis in already:
                raise ValueError(
                    f"ring axis {axis!r} is already manual in the "
                    f"enclosing shard_map; ring attention cannot re-split "
                    f"it")
            sm_mesh = ctx
            if bspec in already:
                bspec = None
            if hspec in already:
                hspec = None
    spec = P(bspec, hspec, axis, None)
    sq, sk = q.shape[2] // n, k.shape[2] // n
    if impl == "auto":
        use_flash = (jax.default_backend() == "tpu"
                     and sq == sk and sq % 8 == 0)
    elif impl in ("flash", "xla"):
        # The static three-case causal split needs aligned equal chunks.
        use_flash = impl == "flash"
        if use_flash and (sq != sk or sq % 8):
            raise ValueError(f"impl='flash' needs equal tile-aligned "
                             f"chunks, got ({sq},{sk})")
    else:
        raise ValueError(f"unknown impl: {impl!r}")
    if n == 1:
        if use_flash:
            return flash_attention(q, k, v, causal=causal)
        return mha_reference(q, k, v, causal=causal)
    body = functools.partial(_ring_body, axis=axis, n=n, causal=causal,
                             use_flash=use_flash)
    # Partial-manual: only the axes the ring actually uses are manual;
    # anything else (tp on the head dim, fsdp on params upstream) stays
    # with the compiler so the two compose.
    if isinstance(q, jax.core.Tracer):
        fn = jax.shard_map(
            body, mesh=sm_mesh,
            in_specs=(spec, spec, spec, P(axis)),
            out_specs=(spec, P(bspec, hspec, axis)),
            axis_names=frozenset(a for a in (axis, bspec, hspec)
                                 if a is not None),
            check_vma=False,
        )
    else:
        # Partial-manual shard_map (axis_names ⊂ mesh axes) only lowers
        # correctly under jit in current JAX — the eager path trips a
        # bogus "out_specs refers to <other axis>" check. Production
        # calls are always inside a jitted step; this keeps direct eager
        # use (model.init with a mesh-carrying model, notebooks) working
        # — through a CACHED jit wrapper, or a fresh jax.jit per call
        # would recompile every invocation.
        fn = _eager_ring(sm_mesh, bspec, hspec, axis, n, causal, use_flash)
    return fn(q, k, v, jnp.arange(n, dtype=jnp.int32))


def ring_self_attention(x_heads, *, mesh: Mesh, axis: str = "sp",
                        causal: bool = True) -> jax.Array:
    """Convenience: q = k = v = x_heads (B, H, S, D); returns out only."""
    out, _ = ring_attention(x_heads, x_heads, x_heads, mesh=mesh, axis=axis,
                            causal=causal)
    return out
