"""Fully-sharded data parallelism (ZeRO-3), the GSPMD way.

FSDP on TPU is a **placement decision, not an algorithm**: shard every
parameter (and, via ``zeros_like`` inheritance, every optimizer moment)
across the ``fsdp`` mesh axis, shard the batch across the same axis, and
let XLA's SPMD partitioner insert the all-gathers before each layer's
compute and reduce-scatters for the gradients — the exact communication
schedule hand-written ZeRO implementations build manually. Per-device
parameter + optimizer memory drops by the axis size while the math stays
bit-identical to plain DP (the oracle tests pin this).

Rules pick, per leaf, the largest dimension divisible by the axis size
(so uneven shapes degrade to replication instead of erroring), with one
name-aware override: the LM head kernel shards along its *feature* dim,
keeping the vocab dim whole so the fused cross-entropy's vocab-block scan
(ops/xent.py) stays a local slice instead of a GSPMD gather.

The reference has no parameter sharding of any kind (its model is fully
replicated under torch DDP, /root/reference/examples/vae/vae-ddp.py:207);
this module extends the dp/tp/pp/sp/ep set with the strategy TPU pods
actually train large models with.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["fsdp_rules", "fsdp_compose", "place_zero3", "data_axes"]


def _lmhead_feature_spec(path, shape, size: int, axis: str):
    """THE keep-vocab-whole rule for the LM head kernel, shared by
    :func:`fsdp_rules` and :func:`fsdp_compose`: shard the feature dim
    over ``axis`` (or replicate when it doesn't divide) — never the
    vocab dim, whose shard would make the fused cross-entropy's
    vocab-block scan gather the whole kernel every block. Returns None
    when the leaf is not the head kernel (keyed on the full
    lmhead/head/kernel path, not any module named "head")."""
    if "lmhead" in set(path) and path[-2:] == ("head", "kernel") \
            and len(shape) == 2:
        return P(axis, None) if shape[0] % size == 0 else P()
    return None


def fsdp_rules(mesh: Mesh, axis: str = "fsdp") -> Callable:
    """Sharding rules for :func:`ddstore_tpu.parallel.tp.shard_pytree`.

    Every leaf with a dimension divisible by ``mesh.shape[axis]`` is
    sharded along its largest such dimension; everything else (norm
    scales, odd shapes) is replicated — they are a rounding error of the
    footprint. 1-D leaves shard too (biases at scale are fsdp-sharded in
    ZeRO as well).
    """
    size = mesh.shape[axis]

    def rules(path, leaf):
        shape = getattr(leaf, "shape", ())
        if not shape:
            return P()
        head = _lmhead_feature_spec(path, shape, size, axis)
        if head is not None:
            return head
        best = None
        for i, d in enumerate(shape):
            if d % size == 0 and d >= size:
                if best is None or d > shape[best]:
                    best = i
        if best is None:
            return P()
        spec = [None] * len(shape)
        spec[best] = axis
        return P(*spec)

    return rules


def place_zero3(params, tx, mesh: Mesh, rules: Optional[Callable] = None):
    """THE shared ZeRO-3 placement step for every model family: shard
    params by ``rules`` (default :func:`fsdp_rules`), init the optimizer
    on the placed params (moments inherit via zeros_like), and replicate
    any straggler leaves (optimizer scalars like adam's count) so one
    jit never mixes meshes. Returns ``(params, opt_state, step0)`` —
    the positional fields of every family's TrainState, so callers
    assemble theirs as ``TrainState(*place_zero3(...))``."""
    import jax.numpy as jnp

    from .tp import shard_pytree

    params = shard_pytree(params, mesh, rules or fsdp_rules(mesh))
    opt_state = tx.init(params)
    repl = NamedSharding(mesh, P())
    fix = lambda x: x if isinstance(getattr(x, "sharding", None),
                                    NamedSharding) else \
        jax.device_put(x, repl)
    return (params, jax.tree_util.tree_map(fix, opt_state),
            jax.device_put(jnp.zeros((), jnp.int32), repl))


def data_axes(mesh: Mesh, axis: str = "dp") -> Optional[Tuple[str, ...]]:
    """Batch-dimension mesh axes: ``axis`` plus ``fsdp`` when present
    (under ZeRO the batch shards over BOTH — params and data split the
    same axis). None when neither axis is >1 (replicated batch)."""
    return tuple(a for a in (axis, "fsdp")
                 if mesh.shape.get(a, 1) > 1) or None


def fsdp_compose(base_rules: Optional[Callable], mesh: Mesh,
                 axis: str = "fsdp") -> Callable:
    """Layer ZeRO-3 sharding ON TOP of another rule set (fsdp×tp /
    fsdp×ep — VERDICT r3 missing #1 replaced a hard refusal at
    transformer.py's create_train_state with this).

    Per leaf: take the base spec (megatron / expert rules), then shard
    the largest base-unsharded dimension divisible by the fsdp axis size
    over ``axis``. A leaf with no such dimension keeps just its base
    spec — replication across fsdp of a tp-sharded leaf still holds
    1/tp of it per device. The LM head kernel keeps fsdp_rules' special
    case whenever the base left it unsharded (fsdp×ep: expert rules
    return P() for it): shard the FEATURE dim, never the vocab dim —
    a vocab shard would make the fused cross-entropy's vocab-block scan
    gather the whole kernel every block (the auto-enable check only
    knows about tp). Under megatron TP the base already shards vocab
    (which disables fused-xent) and fsdp takes the feature dim via the
    general path.
    """
    size = mesh.shape[axis]

    def rules(path, leaf):
        shape = getattr(leaf, "shape", ())
        base = tuple(base_rules(path, leaf)) if base_rules else ()
        spec = list(base) + [None] * (len(shape) - len(base))
        if all(s is None for s in spec):
            head = _lmhead_feature_spec(path, shape, size, axis)
            if head is not None:
                return head
        best = None
        for i, d in enumerate(shape):
            if spec[i] is None and d % size == 0 and d >= size:
                if best is None or d > shape[best]:
                    best = i
        if best is not None:
            spec[best] = axis
        return P(*spec)

    return rules
