"""Host capability report: ``python -m ddstore_tpu.diag``.

One screenful that answers "which data planes can THIS host actually
run?" before any store exists — the io_uring probe (the uring wire
backend and O_DIRECT cold serving hang off it), the CMA fast path's
kernel preconditions, the core budget every tuner scales by, and a
page-cache-vs-O_DIRECT verdict for the cold-tier directory. The bench
embeds the same dict in its extras (``capabilities``), so a
TCP-fallback or mmap-only run is diagnosable from its artifacts alone.

Report keys (``capability_report()``):
  uring          — :func:`ddstore_tpu.binding.uring_probe` verbatim
                   (supported, IORING_FEAT_* mask, per-opcode flags,
                   reason)
  cma            — {available, reason}: Yama ptrace_scope verdict plus
                   a live process_vm_readv self-read (the actual
                   syscall, not just the sysctl)
  cores          — os.cpu_count() (lane pools, async width and the
                   uring burst budget all scale by it)
  cold_direct    — {dir, o_direct, gate, verdict}: can the cold-tier
                   directory serve O_DIRECT, and does the
                   DDSTORE_URING_COLD gate currently want it?
"""

from __future__ import annotations

import ctypes
import errno
import json
import os
import tempfile


def _probe_cma() -> dict:
    """CMA feasibility: Yama scope plus a real process_vm_readv
    self-read (gVisor-class kernels return ENOSYS regardless of the
    sysctl; a container may also drop the capability)."""
    reason = []
    scope = None
    try:
        with open("/proc/sys/kernel/yama/ptrace_scope") as f:
            scope = int(f.read().strip())
        if scope >= 2:
            reason.append(f"yama ptrace_scope={scope} blocks "
                          "cross-process reads")
        elif scope == 1:
            reason.append("yama ptrace_scope=1 (peers must "
                          "PR_SET_PTRACER or share a parent)")
    except OSError:
        pass  # no Yama — nothing to report
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        src = (ctypes.c_char * 16)(*b"ddstore-cma-prob")
        dst = (ctypes.c_char * 16)()

        class _IoVec(ctypes.Structure):
            _fields_ = [("iov_base", ctypes.c_void_p),
                        ("iov_len", ctypes.c_size_t)]

        liov = _IoVec(ctypes.cast(dst, ctypes.c_void_p), 16)
        riov = _IoVec(ctypes.cast(src, ctypes.c_void_p), 16)
        n = libc.process_vm_readv(os.getpid(), ctypes.byref(liov), 1,
                                  ctypes.byref(riov), 1, 0)
        if n != 16 or dst.raw != src.raw:
            err = ctypes.get_errno()
            reason.append("process_vm_readv: "
                          f"{os.strerror(err) if err else 'short read'}")
            return {"available": False, "reason": "; ".join(reason)}
    except Exception as e:  # noqa: BLE001 — report, never crash diag
        reason.append(f"process_vm_readv probe failed: {e}")
        return {"available": False, "reason": "; ".join(reason)}
    if os.environ.get("DDSTORE_CMA", "").strip() == "0":
        reason.append("DDSTORE_CMA=0 disables it")
        return {"available": False, "reason": "; ".join(reason)}
    # scope 1 still works between a store's pooled peers (PR_SET_PTRACER
    # handshake) — available, with the caveat in reason.
    return {"available": scope is None or scope < 2,
            "reason": "; ".join(reason) or "ok"}


def _probe_cold_direct(uring_supported: bool) -> dict:
    """Can the cold-tier directory serve O_DIRECT, and does the
    DDSTORE_URING_COLD gate want it? The verdict names the regime the
    tiered store will actually run in."""
    d = os.environ.get("DDSTORE_TIER_COLD_DIR", "").strip() or \
        tempfile.gettempdir()
    gate = os.environ.get("DDSTORE_URING_COLD", "auto").strip().lower() \
        or "auto"
    o_direct = False
    detail = ""
    try:
        fd, path = tempfile.mkstemp(dir=d)
        try:
            os.write(fd, b"\0" * 4096)
            os.close(fd)
            dfd = os.open(path, os.O_RDONLY | os.O_DIRECT)
            os.close(dfd)
            o_direct = True
        finally:
            os.unlink(path)
    except OSError as e:
        detail = f"O_DIRECT open in {d}: " \
                 f"{errno.errorcode.get(e.errno, e.errno)}"
    if not uring_supported:
        verdict = "page-cache mmap (no io_uring)"
    elif not o_direct:
        verdict = f"page-cache mmap ({detail})"
    elif gate in ("0", "off", "false"):
        verdict = "page-cache mmap (DDSTORE_URING_COLD=0)"
    elif gate in ("1", "on", "true"):
        verdict = "O_DIRECT via submission ring (forced on)"
    else:
        verdict = "O_DIRECT via submission ring when " \
                  "DDSTORE_TRANSPORT=uring engages (gate=auto)"
    return {"dir": d, "o_direct": o_direct, "gate": gate,
            "verdict": verdict}


def capability_report() -> dict:
    """The full report as one JSON-ready dict (see module docstring)."""
    from .binding import uring_probe

    uring = uring_probe()
    return {
        "uring": uring,
        "cma": _probe_cma(),
        "cores": os.cpu_count() or 1,
        "cold_direct": _probe_cold_direct(bool(uring["supported"])),
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m ddstore_tpu.diag",
        description="Report this host's data-plane capabilities "
                    "(io_uring, CMA, cores, cold-tier O_DIRECT).")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (the same dict the "
                         "bench embeds in extras)")
    args = ap.parse_args(argv)
    rep = capability_report()
    if args.json:
        print(json.dumps(rep, indent=2, sort_keys=True))
        return 0
    u = rep["uring"]
    ops = [k[3:] for k in ("op_send", "op_recv", "op_sendmsg",
                           "op_recvmsg", "op_read", "op_read_fixed")
           if u.get(k)]
    print(f"io_uring:    {'yes' if u['supported'] else 'NO'} "
          f"({u['reason']})")
    if u["supported"]:
        print(f"  features:  0x{u['features']:x}"
              f"{' +ext_arg' if u['ext_arg'] else ''}")
        print(f"  opcodes:   {' '.join(ops)}")
    c = rep["cma"]
    print(f"cma:         {'yes' if c['available'] else 'NO'} "
          f"({c['reason']})")
    print(f"cores:       {rep['cores']}")
    cd = rep["cold_direct"]
    print(f"cold tier:   {cd['verdict']}")
    print(f"  dir:       {cd['dir']} "
          f"(O_DIRECT {'ok' if cd['o_direct'] else 'refused'})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
