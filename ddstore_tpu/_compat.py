"""Version portability for the jax surface this codebase targets.

The framework is written against the current jax API (``jax.shard_map``,
``jax.P``, ``jax.NamedSharding`` as top-level names). Older runtimes
(e.g. 0.4.x) ship the same functionality under ``jax.experimental`` /
``jax.sharding`` only; this module aliases the missing names at package
import so every layer (and ``__graft_entry__``) runs unchanged on both.
Attributes that already exist are never touched.
"""

from __future__ import annotations

# True when this runtime lacks a native jax.shard_map and got the
# experimental-API adapter below. Pre-AbstractMesh runtimes cannot lower
# every partial-manual composition (e.g. the 4-axis dp×pp×tp×sp step);
# tests pinning those compositions key their expected-failure on this.
SHIMMED_SHARD_MAP = False


def install() -> None:
    global SHIMMED_SHARD_MAP
    try:
        import jax
    except Exception:  # pragma: no cover — host-only installs skip jax
        return
    if not hasattr(jax, "P"):
        from jax.sharding import PartitionSpec
        jax.P = PartitionSpec
    if not hasattr(jax, "NamedSharding"):  # pragma: no cover
        from jax.sharding import NamedSharding
        jax.NamedSharding = NamedSharding
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _esm

        def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                      check_vma=None, check_rep=None, auto=None):
            # Current-API surface over the experimental implementation:
            # ``axis_names`` (the manual subset) maps to its complement
            # ``auto``; ``check_vma`` is the renamed ``check_rep``.
            if auto is None:
                auto = (frozenset(mesh.axis_names)
                        - frozenset(axis_names)) if axis_names \
                    else frozenset()
            if auto:
                # The experimental implementation accepts `auto` but its
                # partial-manual lowering is unsound on this runtime —
                # observed: a hard C++ abort (not an exception) compiling
                # a ring nested in a pipeline stage, which would kill the
                # whole test process. Refuse cleanly instead; full-manual
                # compositions (auto empty) are solid.
                raise NotImplementedError(
                    f"partial-manual shard_map (auto axes "
                    f"{sorted(auto)}) is not supported on "
                    f"pre-AbstractMesh jax {jax.__version__}; only "
                    f"fully-manual compositions lower soundly here")
            if check_rep is None:
                check_rep = True if check_vma is None else bool(check_vma)
            return _esm(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=check_rep,
                        auto=frozenset())

        jax.shard_map = shard_map
        SHIMMED_SHARD_MAP = True
    if not hasattr(jax.sharding, "get_abstract_mesh"):
        # Callers probe the enclosing manual mesh to compose nested
        # shard_maps; pre-AbstractMesh runtimes have no such context —
        # report "none" and the nesting-aware paths fall through to
        # their flat behavior.
        jax.sharding.get_abstract_mesh = lambda: None


install()
