"""High-level distributed sample store.

API parity with the reference's ``PyDDStore``
(/root/reference/src/pyddstore.pyx:58-131 — ``add/get/init/update/
epoch_begin/epoch_end/free``) plus the capabilities it lacked: batched
multi-row fetch, replica-width groups in the core (the reference documents
``ddstore_width`` but implements it only in the example dataset adapter,
README.md:154-172 / distdataset.py:25-30), dtype/shape agreement enforced at
registration (the reference checks only ``disp`` via MPI_Allreduce MAX,
ddstore.hpp:78-82), and sample-major indexing (one global row == one sample).
"""

from __future__ import annotations

import os
import socket
import uuid
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .binding import (ERR_ADMISSION, ERR_CORRUPT, ERR_PEER_LOST,
                      DDStoreError, NativeStore)
from .rendezvous import (ProcessGroup, SingleGroup, ThreadGroup,
                         auto_group)

__all__ = ["AsyncBatchRead", "DDStore", "DDStoreError"]


class AsyncBatchRead:
    """Handle to an in-flight background :meth:`DDStore.get_batch`.

    The read fills the preallocated ``out`` buffer on the native store's
    background pool; the handle keeps ``out`` (and the index array)
    alive until completion. ``wait()`` blocks (GIL released — the wait
    is a native condition variable), returns the filled buffer, and
    releases the native ticket; ``done()`` polls. There is no mid-flight
    cancel: ``release()`` on an unfinished read blocks until it
    completes — the teardown barrier that guarantees no worker is still
    writing into ``out`` when the caller drops it.
    """

    __slots__ = ("_native", "_ticket", "out", "_idx", "_released",
                 "_error", "done_mono_s")

    def __init__(self, native, ticket: int, out: np.ndarray,
                 idx: np.ndarray):
        self._native = native
        self._ticket = ticket
        self.out = out
        self._idx = idx  # starts are copied natively; held for debugging
        self._released = False
        self._error: Optional[int] = None  # the read's error code, if any
        #: completion time on the time.monotonic() clock, set by the
        #: first successful wait (readahead producer-idle accounting).
        self.done_mono_s: Optional[float] = None

    def done(self) -> bool:
        """Poll without blocking. Raises (and frees the ticket) if the
        read failed."""
        if self._released:
            if self._error is not None:
                raise DDStoreError(self._error, "get_batch_async")
            return True
        status, ts = self._native.async_wait(self._ticket, 0)
        if status < 0:
            self._error = status
            self.release()
            raise DDStoreError(status, "get_batch_async")
        if status == 1:
            self.done_mono_s = ts
        return status == 1

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the read completes; returns the filled buffer and
        releases the ticket. Raises TimeoutError if ``timeout`` (seconds)
        elapses first, DDStoreError if the read failed — including on a
        repeat call after a failure already surfaced (the buffer was
        never filled; returning it would look like success)."""
        if self._released:
            if self._error is not None:
                raise DDStoreError(self._error, "get_batch_async")
            return self.out
        ms = -1 if timeout is None else max(0, int(timeout * 1000))
        status, ts = self._native.async_wait(self._ticket, ms)
        if status == 0:
            raise TimeoutError(
                f"async get_batch not done after {timeout}s")
        if status < 0:
            self._error = status
        self.release()
        if status < 0:
            raise DDStoreError(status, "get_batch_async")
        self.done_mono_s = ts
        return self.out

    def release(self) -> None:
        """Free the native ticket, blocking until the read finishes (a
        worker must never be left writing into ``out``). Idempotent and
        non-raising — this is the teardown barrier."""
        if not self._released:
            self._released = True
            self._native.async_release(self._ticket)


def _check_varname(name: str) -> None:
    """Control characters are the native registry's namespace
    machinery (\\x01 mirrors, \\x02 tenant scopes, \\x03 snapshot
    views) — a user name carrying one could alias a hidden variable."""
    if not name:
        raise ValueError("variable name must be non-empty")
    if any(ord(c) < 0x20 for c in name):
        raise ValueError(f"variable name {name!r} contains control "
                         f"characters (reserved for the native "
                         f"namespace machinery)")


def _row_disp(sample_shape: Tuple[int, ...]) -> int:
    """Row displacement (elements per sample) — THE single derivation
    shared by add/init/add_mmap and the elastic rejoin path."""
    return int(np.prod(sample_shape, dtype=np.int64)) if sample_shape else 1


def _my_host() -> str:
    host = os.environ.get("DDSTORE_HOST")
    if host:
        return host
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def _resolve_iface(token: str) -> str:
    """An IPv4 address passes through; anything else is treated as an
    interface name and resolved via SIOCGIFADDR (the reference's
    FABRIC_IFACE takes a fabric interface name the same way,
    common.cxx:32,54-59)."""
    try:
        socket.inet_aton(token)
        return token
    except OSError:
        pass
    import fcntl
    import struct
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        packed = struct.pack("256s", token.encode()[:255])
        try:
            addr = fcntl.ioctl(s.fileno(), 0x8915, packed)[20:24]  # SIOCGIFADDR
        except OSError as e:
            raise ValueError(f"DDSTORE_IFACES: cannot resolve interface "
                             f"{token!r}: {e}") from None
    return socket.inet_ntoa(addr)


def _my_ifaces() -> list:
    """Per-NIC addresses this rank advertises and binds outgoing
    connections to (DDSTORE_IFACES=addr-or-ifname[,addr-or-ifname...]).
    Empty list = single-NIC default (_my_host)."""
    env = os.environ.get("DDSTORE_IFACES", "")
    return [_resolve_iface(t.strip()) for t in env.split(",") if t.strip()]


class _VarMeta:
    __slots__ = ("dtype", "sample_shape", "disp", "all_nrows", "pinned",
                 "readonly", "tier")

    def __init__(self, dtype: np.dtype, sample_shape: Tuple[int, ...],
                 disp: int, all_nrows: Sequence[int],
                 pinned: Optional[np.ndarray] = None,
                 readonly: bool = False, tier: str = "hot"):
        self.dtype = dtype
        self.sample_shape = sample_shape
        self.disp = disp
        self.all_nrows = list(all_nrows)
        # With copy=False the native core borrows this buffer; holding it
        # here keeps it alive for the lifetime of the variable.
        self.pinned = pinned
        # True for read-only mmap backings: `update` must refuse rather
        # than memcpy into unwritable pages (SIGSEGV).
        self.readonly = readonly
        # Storage tier of the backing ("hot" = RAM/shm, "cold" =
        # file-backed mmap over NVMe page cache). Mirrored natively
        # (set_var_tier) for the cold_vars/cold_bytes gauges.
        self.tier = tier


class DDStore:
    """Distributed in-memory sample store over a process group.

    Each member of the (replica-)group owns one shard of every registered
    variable; the global row space is the concatenation of shards in group
    rank order; any member reads any row one-sidedly.

    Parameters
    ----------
    group: control-plane group (auto-detected if None).
    backend: "local" (in-process transport), "tcp" (DCN transport), or
        "auto" (local for single/thread groups, tcp otherwise).
    width: if set, split `group` into replica groups of `width` consecutive
        ranks; this store then spans only the caller's replica group (one
        full dataset copy per group — e.g. one store per TPU host or ICI
        island).
    copy: copy shards into store-owned memory at `add` (reference behavior)
        or borrow the caller's buffer (zero-copy; caller keeps it alive).
    epoch_collective: whether epoch_begin/end are collective fences
        (reference MPI behavior, src/ddstore.cxx:51-77) or local no-ops
        (its libfabric behavior). Default False — the fence-per-batch is an
        anti-pattern on TPU pods; use the explicit `barrier()` when needed.
    """

    def __init__(self, group: Optional[ProcessGroup] = None,
                 backend: str = "auto", width: Optional[int] = None,
                 copy: bool = True, epoch_collective: bool = False,
                 port: int = 0):
        self.world_group = group if group is not None else auto_group()
        if width is not None and width > 0:
            self.replica_id = self.world_group.rank // width
            self.group = self.world_group.split(self.replica_id)
            self.num_replicas = (self.world_group.size + width - 1) // width
        else:
            self.replica_id = 0
            self.group = self.world_group
            self.num_replicas = 1

        if backend == "auto":
            # Env override first (the reference selects its backend the
            # same way: DDSTORE_METHOD, distdataset.py:32), then by
            # group kind.
            backend = os.environ.get("DDSTORE_BACKEND", "").strip() \
                or ("local" if isinstance(self.group,
                                          (SingleGroup, ThreadGroup))
                    else "tcp")
        if backend == "local" and self.group.size > 1 and not isinstance(
                self.group, (SingleGroup, ThreadGroup)):
            # The local backend's registry is per-process; with ranks in
            # separate processes every rank would wait forever for peers
            # that can never join its registry. Size-1 groups of any kind
            # are trivially process-local.
            raise ValueError(
                "backend 'local' requires all ranks in one process "
                f"(got {type(self.group).__name__} of size "
                f"{self.group.size}); use 'tcp'")
        self.backend = backend
        self.copy = copy
        self._meta: Dict[str, _VarMeta] = {}
        # One metadata registry per NAMED tenant, shared by every handle
        # of that tenant (see tenant/handle.py): a second attach — a
        # snapshot reader included — must resolve the tenant's variables.
        self._tenant_meta: Dict[str, Dict[str, _VarMeta]] = {}
        self._barrier_tag = 1 << 32  # distinct from epoch tags

        rank, world = self.group.rank, self.group.size
        # Elastic-recovery bookkeeping (ddstore_tpu.elastic): which
        # endpoint each peer currently lives at, what this rank
        # advertises, and how many recovery generations have committed.
        self._advertised = None
        self._endpoints = None
        self._generation = 0
        # Peer-topology listeners (see add_peer_listener): the cost-model
        # scheduler replans when elastic recovery swaps an endpoint OR
        # the heartbeat detector suspects a peer (check_health).
        self._peer_listeners = []
        # Suspect view already delivered to listeners (check_health
        # fires them only on CHANGE).
        self._known_suspects = frozenset()
        if backend == "local":
            gid = self.group.broadcast(uuid.uuid4().hex)
            self._gid = gid
            self._native = NativeStore.create_local(gid, rank, world)
        elif backend == "tcp":
            self._gid = None
            # DDSTORE_TRANSPORT=uring swaps the per-lane wire loop for
            # the io_uring batch backend (one io_uring_enter per frame
            # burst). Everything else — peers, lanes, CMA routing,
            # faults, failover, gateway — is the inherited TcpTransport
            # machinery, and on an io_uring-less kernel the handle
            # still constructs and serves plain TCP (uring_state()==0,
            # uring_reason() says why). Unset/"tcp" is pinned
            # byte-identical to the pre-uring tree.
            wire = os.environ.get("DDSTORE_TRANSPORT", "").strip().lower()
            if wire == "uring":
                self._native = NativeStore.create_uring(rank, world, port)
            elif wire in ("", "tcp"):
                self._native = NativeStore.create_tcp(rank, world, port)
            else:
                raise ValueError(
                    f"DDSTORE_TRANSPORT={wire!r}: expected 'tcp' or "
                    "'uring' (CMA is a per-read route, not a backend)")
            # Multi-NIC: advertise every DDSTORE_IFACES address (the
            # server listens on INADDR_ANY, so one port serves all NICs)
            # and bind outgoing pool connections to them round-robin.
            ifaces = _my_ifaces()
            advertised = ",".join(ifaces) if ifaces else _my_host()
            endpoints = self.group.allgather(
                (advertised, self._native.server_port))
            hosts = [h for h, _ in endpoints]
            ports = [p for _, p in endpoints]
            self._native.set_peers(hosts, ports)
            if ifaces:
                self._native.set_ifaces(ifaces)
            self._advertised = advertised
            self._endpoints = [tuple(e) for e in endpoints]
        else:
            raise ValueError(f"unknown backend: {backend}")
        self._native.set_epoch_collective(epoch_collective)

    # -- registration ------------------------------------------------------

    def add(self, name: str, arr: np.ndarray,
            copy: Optional[bool] = None, readonly: bool = False) -> None:
        """Register this rank's shard. ``arr`` is sample-major: shape
        ``(nrows, *sample_shape)``; one global row == one sample (fixing the
        reference adapter's flattened-blob indexing trap,
        distdataset.py:63,84 where ``disp=1`` made row != sample).
        ``copy`` overrides the store default (False borrows the buffer —
        how mmap-backed tiering serves from page cache)."""
        _check_varname(name)
        copy = self.copy if copy is None else copy
        arr = np.ascontiguousarray(arr)
        if arr.ndim == 0:
            raise ValueError("shard must have a leading sample dimension")
        nrows = arr.shape[0]
        sample_shape = tuple(arr.shape[1:])
        disp = _row_disp(sample_shape)
        metas = self.group.allgather(
            (nrows, arr.dtype.str, sample_shape))
        shapes = {(d, s) for _, d, s in metas}
        if len(shapes) != 1:
            raise DDStoreError(-9, f"add({name}): ranks disagree on "
                                   f"dtype/sample shape: {sorted(shapes)}")
        all_nrows = [m[0] for m in metas]
        self._native.add(self._wname(name), arr, all_nrows, copy=copy)
        # A borrowed buffer the caller can't write (e.g. a frombuffer
        # view over an immutable bytes object) must refuse update() with
        # a DDStoreError, not let the native memcpy SIGSEGV on the
        # unwritable pages.
        if not copy and not arr.flags.writeable:
            readonly = True
        self._meta[name] = _VarMeta(arr.dtype, sample_shape, disp, all_nrows,
                                    pinned=None if copy else arr,
                                    readonly=readonly)
        # `add` is collective in the reference (MPI_Win_create,
        # ddstore.hpp:56-62); completing it with a barrier gives the same
        # guarantee: once any rank returns, every shard is readable.
        self._finish_collective_add(name)

    def init(self, name: str, nrows: int, sample_shape: Tuple[int, ...],
             dtype) -> None:
        """Register a zero-filled shard for deferred population (reference
        ``init``, pyddstore.pyx:112-113)."""
        _check_varname(name)
        dtype = np.dtype(dtype)
        disp = _row_disp(tuple(sample_shape))
        metas = self.group.allgather((int(nrows), dtype.str,
                                      tuple(sample_shape)))
        shapes = {(d, s) for _, d, s in metas}
        if len(shapes) != 1:
            raise DDStoreError(-9, f"init({name}): ranks disagree")
        all_nrows = [m[0] for m in metas]
        self._native.init(self._wname(name), nrows, disp,
                          dtype.itemsize, all_nrows)
        self._meta[name] = _VarMeta(dtype, tuple(sample_shape), disp,
                                    all_nrows)
        self._finish_collective_add(name)

    def _finish_collective_add(self, name: str) -> None:
        """The barrier → replicate → barrier tail of ``add``/``init``,
        made CRASH-CONSISTENT: a peer DEATH mid-fence (the barrier
        aborts with the classified ``ERR_PEER_LOST``, in O(heartbeat)
        when the detector is on) rolls the LOCAL registration back —
        native variable freed, metadata dropped — before re-raising.
        In the common case every survivor's oracle converges on the
        same dead member and all of them abort the same fence, so a
        subsequent ``elastic.recover`` + retried ``add`` finds the
        clean pre-add state everywhere — no half-registered variable
        poisoning later collectives with ``ERR_EXISTS`` on some ranks
        only. The abort is not GUARANTEED unanimous (a victim that
        partially disseminated its barrier notifies can let one
        survivor complete the fence others aborted — the same window
        the fence state machine heals with ``fence_reset`` at
        recovery); a retried ``add`` that hits ``ERR_EXISTS`` on such
        a completed rank is realigned by a collective ``free(name)`` +
        re-add. A plain barrier TIMEOUT (``ERR_TRANSPORT``, no
        suspect) deliberately does NOT unwind: a slow-but-alive peer
        may have completed the fence and kept the variable, and a
        one-sided rollback would widen exactly that divergence (the
        pre-hardening behavior — keep the registration, surface the
        error)."""
        try:
            self.barrier()
            self._replicate_after_add(name)
        except DDStoreError as e:
            if e.code == ERR_PEER_LOST:
                try:
                    self._native.free_var(self._wname(name))
                except DDStoreError:
                    pass  # best-effort rollback; the raise is the news
                self._meta.pop(name, None)
            raise

    def _replicate_after_add(self, name: str) -> None:
        """R-way shard replication (``DDSTORE_REPLICATION``): after the
        registration barrier every rank pulls read-only mirrors of the
        next R-1 ranks' shards (chain placement), then a second barrier
        makes the replica chain live before any read can need it.
        No-op (and byte-identical to the pre-replication tree) at the
        default R=1. A failed mirror pull is DEGRADED COVERAGE, not a
        failed add: raising here would skip the trailing barrier and
        stall every healthy rank in it — the replica router already
        tolerates a missing mirror (next holder / classified loss), and
        ``refresh_mirrors`` or the next epoch fence retries the pull."""
        if self.replication > 1 and self.world > 1:
            try:
                self._native.replicate(self._wname(name))
            except DDStoreError as e:
                import warnings

                warnings.warn(
                    f"add({name}): mirror replication incomplete on "
                    f"rank {self.rank} ({e}); reads stay correct, "
                    f"failover coverage is reduced until the next "
                    f"refresh", RuntimeWarning, stacklevel=3)
            self.barrier()

    def update(self, name: str, arr: np.ndarray, row_offset: int = 0) -> None:
        """Overwrite local rows [row_offset, row_offset+len(arr)) (reference
        ``update``, pyddstore.pyx:115-131 — bounds-checked here)."""
        m = self._require(name)
        if m.readonly:
            raise DDStoreError(
                -1, f"update({name}): refused — the shard is a "
                    f"read-only {m.tier}-tier file-backed mapping "
                    f"(registered via add_file/add_mmap/spill_to_disk "
                    f"with copy=False); re-register with mode='r+' or "
                    f"tier='hot' to keep update() usable")
        arr = np.ascontiguousarray(arr, dtype=m.dtype)
        if tuple(arr.shape[1:]) != m.sample_shape:
            raise ValueError(
                f"update({name}): sample shape {tuple(arr.shape[1:])} != "
                f"registered {m.sample_shape}")
        self._native.update(self._wname(name), arr, row_offset)

    # -- reads -------------------------------------------------------------

    def get(self, name: str, start: int, count: int = 1,
            out: Optional[np.ndarray] = None) -> np.ndarray:
        """Read `count` consecutive global rows starting at `start`. The
        range must lie within one rank's shard (single-peer read, as the
        reference enforces, ddstore.hpp:210-214); use :meth:`get_batch` for
        arbitrary index sets."""
        m = self._require(name)
        out = self._check_out(name, m, out, count)
        try:
            self._native.get(self._rname(name), out, start, count,
                             tenant=self._read_tenant())
        except DDStoreError as e:
            raise self._classify(e, name,
                                 np.arange(start, start + count)) from None
        return out

    def get_batch(self, name: str, indices, out: Optional[np.ndarray] = None
                  ) -> np.ndarray:
        """Read arbitrary global rows, coalesced per owner and fetched from
        distinct peers in parallel — the batched fetch path the reference
        lacks (it issues one blocking get per sample, SURVEY §3.2)."""
        m = self._require(name)
        idx = np.ascontiguousarray(indices, dtype=np.int64).reshape(-1)
        out = self._check_out(name, m, out, len(idx))
        try:
            self._native.get_batch(self._rname(name), out, idx,
                                   tenant=self._read_tenant())
        except DDStoreError as e:
            raise self._classify(e, name, idx) from None
        return out

    def get_batch_async(self, name: str, indices,
                        out: Optional[np.ndarray] = None) -> AsyncBatchRead:
        """Issue :meth:`get_batch` on the native background pool and
        return immediately with an :class:`AsyncBatchRead` handle — the
        epoch-readahead engine keeps the next window's bulk fetch in
        flight this way while the current window is consumed. ``out``
        must not be read (or dropped) until the handle completes."""
        m = self._require(name)
        idx = np.ascontiguousarray(indices, dtype=np.int64).reshape(-1)
        out = self._check_out(name, m, out, len(idx))
        ticket = self._native.get_batch_async(self._rname(name), out, idx,
                                              tenant=self._read_tenant())
        return AsyncBatchRead(self._native, ticket, out, idx)

    def read_runs_async(self, name: str, out: np.ndarray, targets,
                        src_offsets, dst_offsets,
                        nbytes) -> AsyncBatchRead:
        """Issue pre-coalesced per-peer runs (byte spans) in the
        background — the readahead window fast path: the window planner
        already sorted/deduped/coalesced its rows, so the native side
        executes O(runs) work instead of re-planning O(rows). Run i
        reads ``nbytes[i]`` at byte offset ``src_offsets[i]`` of
        ``targets[i]``'s shard into ``out`` at byte ``dst_offsets[i]``.
        Same completion contract as :meth:`get_batch_async`."""
        self._require(name)
        ticket = self._native.read_runs_async(
            self._rname(name), out, targets, src_offsets, dst_offsets,
            nbytes, tenant=self._read_tenant())
        return AsyncBatchRead(self._native, ticket, out, None)

    def async_pending(self) -> int:
        """In-flight / unreleased async reads (0 after clean teardown)."""
        return self._native.async_pending

    def _classify(self, e: DDStoreError, name: str,
                  idx: np.ndarray) -> DDStoreError:
        """Re-raise helper for failed reads: a permanent owner loss
        (``ERR_PEER_LOST`` — the bounded signal the native retry layer
        emits when its budget exhausts against one peer) is augmented
        with WHICH owner died and WHICH requested rows were lost, so the
        caller can hand exactly that to ``elastic.recover``; a data
        integrity failure (``ERR_CORRUPT``) is augmented the same way —
        which owner's bytes disagree with the published checksums and
        which requested rows are affected (the flight recorder already
        dumped; nothing died, so elastic.recover is NOT the next step —
        inspect/rebuild the named shard). Everything else passes
        through unchanged."""
        if e.code == ERR_CORRUPT:
            peer = int(self.integrity_stats().get("last_corrupt_peer",
                                                  -1))
            bad = idx
            try:
                if peer >= 0:
                    owners = self.owner_of_rows(name, idx)
                    bad = idx[owners == peer]
            except Exception:  # noqa: BLE001 — diagnostics must not mask e
                pass
            preview = ", ".join(str(int(r)) for r in bad[:4])
            more = "..." if len(bad) > 4 else ""
            holders = (f"and every readable mirror holder "
                       if self.replication > 1 else "")
            return DDStoreError(
                e.code,
                f"{name}: owner rank {peer} {holders}serve(s) bytes "
                f"disagreeing with the published checksums at a stable "
                f"content version; {len(bad)} requested rows affected "
                f"(rows {preview}{more}) — the delivered batch was NOT "
                f"silently used; inspect trace_flight_dump() and the "
                f"named shard")
        if e.code == ERR_ADMISSION:
            # Defer-not-peer-lost: NOTHING died — the serving gateway
            # refused admission to protect another tenant's SLO (or the
            # rank is draining). Surface the retry-after hint so callers
            # (GatewaySession, the loader's degraded ladder) back off
            # with seeded jitter instead of escalating to elastic.recover.
            try:
                hint = int(self._native.gateway_stats()
                           .get("last_retry_after_ms", 0))
            except Exception:  # noqa: BLE001 — diagnostics must not mask e
                hint = 0
            err = DDStoreError(
                e.code,
                f"{name}: admission refused by the serving gateway "
                f"(defer, not peer-lost — no rows were lost); retry "
                f"after ~{hint} ms with jittered backoff")
            err.retry_after_ms = hint
            return err
        if e.code != ERR_PEER_LOST:
            return e
        peer = int(self._native.fault_stats().get("last_error_peer", -1))
        lost = idx
        try:
            if peer >= 0:
                owners = self.owner_of_rows(name, idx)
                lost = idx[owners == peer]
        except Exception:  # noqa: BLE001 — diagnostics must not mask e
            pass
        preview = ", ".join(str(int(r)) for r in lost[:4])
        more = "..." if len(lost) > 4 else ""
        r = self.replication
        how = (f"owner rank {peer} and all {r - 1} mirror holder(s) "
               f"unreachable" if r > 1
               else f"owner rank {peer} unreachable after bounded "
                    f"retries")
        err = DDStoreError(
            e.code,
            f"{name}: {how}; {len(lost)} requested rows lost "
            f"(rows {preview}{more}) — invoke elastic.recover")
        return err

    @staticmethod
    def _check_out(name: str, m: "_VarMeta", out: Optional[np.ndarray],
                   count: int) -> np.ndarray:
        want = (count,) + m.sample_shape
        if out is None:
            return np.empty(want, dtype=m.dtype)
        # The native core writes count*row_bytes blindly; a wrong dtype or
        # shape here would be heap corruption, so reject rather than coerce.
        if out.dtype != m.dtype or tuple(out.shape) != want:
            raise ValueError(
                f"get({name}): out must be {want} {m.dtype}, got "
                f"{tuple(out.shape)} {out.dtype}")
        return out

    # -- disk / NVMe tiering ----------------------------------------------
    #
    # Shards larger than host RAM: register an mmap-backed buffer with
    # copy=False — the store serves one-sided reads straight out of the OS
    # page cache, so the kernel tiers hot rows in RAM and cold rows on
    # NVMe. The reference holds everything in MPI_Alloc_mem'd RAM and
    # doubles it at registration (ddstore.hpp:43-49); this is the
    # capability BASELINE.md's billion-edge / host↔NVMe config asks for.

    def add_file(self, name: str, path: str, dtype,
                 sample_shape: Tuple[int, ...], tier: str = "cold",
                 mode: str = "r") -> None:
        """Register a file-backed shard (collective) — the first-class
        cold-tier entry point. ``nrows`` is inferred from the file
        size.

        ``tier="cold"`` (the default) registers an ``np.memmap`` with
        ``copy=False``: the store serves one-sided reads straight out
        of the OS page cache, so the kernel tiers hot rows in RAM and
        cold rows on NVMe — the servable dataset per node scales with
        the NVMe/RAM ratio, not RAM. Every serving leg (local memcpy,
        /dev/shm CMA, TCP iovec streaming), replication mirrors,
        integrity sums and tenant quotas work on a cold shard
        unchanged; pair it with ``DDSTORE_TIER_CACHE_BYTES`` so the
        readahead planner's window row lists prefetch upcoming cold
        rows into the RAM hot-row cache. ``mode="r"`` shards refuse
        ``update()`` (the error names the tier); ``mode="r+"`` keeps
        it usable. ``tier="hot"`` loads the file INTO RAM instead
        (a store-owned copy — the pre-tiering behavior for data that
        fits)."""
        if tier not in ("cold", "hot"):
            raise ValueError(f"add_file({name}): tier must be 'cold' or "
                             f"'hot', got {tier!r}")
        dtype = np.dtype(dtype)
        disp = _row_disp(tuple(sample_shape))
        row_bytes = disp * dtype.itemsize
        size = os.path.getsize(path)
        if size % row_bytes:
            raise ValueError(f"add_file({name}): {path} size {size} is not "
                             f"a multiple of row bytes {row_bytes}")
        nrows = size // row_bytes
        if tier == "hot":
            arr = np.fromfile(path, dtype=dtype).reshape(
                (nrows,) + tuple(sample_shape))
            self.add(name, arr, copy=True)
            return
        if nrows:
            arr = np.memmap(path, dtype=dtype, mode=mode,
                            shape=(nrows,) + tuple(sample_shape))
        else:  # a rank may own zero rows; mmap of an empty file is invalid
            arr = np.empty((0,) + tuple(sample_shape), dtype)
        self.add(name, arr, copy=False, readonly=(mode == "r"))
        self._meta[name].tier = "cold"
        self._native.set_var_tier(self._wname(name), 1)
        # O_DIRECT serving (DDSTORE_URING_COLD): readonly cold shards
        # only — a writable mmap's updates would be invisible to
        # page-cache-bypassing direct reads. Refusal (no io_uring, fs
        # without O_DIRECT) keeps the var on the mmap path silently.
        if mode == "r" and nrows and self._cold_direct_wanted():
            self._native.set_var_file(self._wname(name), path)

    def _cold_direct_wanted(self) -> bool:
        """DDSTORE_URING_COLD gate for O_DIRECT cold-tier serving:
        1/0 force it on/off; ``auto`` (default) follows the wire
        backend — on exactly when this store's io_uring transport
        engaged (same kernel verdict; the cold ring reuses the same
        probe). Registration itself may still refuse (filesystem
        without O_DIRECT) — that is per-var and silent."""
        v = os.environ.get("DDSTORE_URING_COLD", "auto").strip().lower()
        if v in ("1", "on", "true"):
            return True
        if v in ("0", "off", "false"):
            return False
        return self.backend == "tcp" and self._native.uring_state() == 1

    def add_mmap(self, name: str, path: str, dtype,
                 sample_shape: Tuple[int, ...], mode: str = "r") -> None:
        """Register a file-backed shard (collective) — the historical
        alias of :meth:`add_file` with ``tier="cold"``."""
        self.add_file(name, path, dtype, sample_shape, tier="cold",
                      mode=mode)

    def spill_to_disk(self, name: str, directory: str,
                      chunk_rows: int = 65536) -> str:
        """Move this variable's local shard from RAM to a file-backed
        mapping (collective: every rank spills its own shard). Remote
        readers are unaffected: the shard is first written to disk, then
        the backing memory is swapped to the mmap ATOMICALLY under the
        native store's exclusive lock (``Rebind``) — a concurrent remote
        read is served from either the old RAM buffer or the new page
        cache mapping, both holding identical bytes; there is no window
        where the variable is missing (the free+re-add alternative had
        one). The on-disk artifact is a checkpoint shard
        (``utils.save_shard`` format, JSON sidecar included), so a
        spilled variable restores across restarts with
        ``utils.load_shard(..., mmap=True)``."""
        from .utils.checkpoint import save_shard

        m = self._require(name)
        path = save_shard(self, name, directory, chunk_rows=chunk_rows)
        nrows = m.all_nrows[self.rank]
        if nrows:
            arr = np.memmap(path, dtype=m.dtype, mode="r",
                            shape=(nrows,) + tuple(m.sample_shape))
        else:  # mmap of an empty file is invalid
            arr = np.empty((0,) + tuple(m.sample_shape), m.dtype)
        self._native.rebind(self._wname(name), arr)
        m.pinned = arr  # keep the mapping alive; old pin (if any) drops
        m.readonly = True
        m.tier = "cold"
        self._native.set_var_tier(self._wname(name), 1)
        # Spilled shards are readonly by construction — eligible for
        # O_DIRECT serving under the same gate as add_file.
        if nrows and self._cold_direct_wanted():
            self._native.set_var_file(self._wname(name), path)
        # Collective completion: once any rank returns, every rank's swap
        # is done (mirrors add()'s barrier guarantee).
        self.barrier()
        return path

    # -- ragged variables --------------------------------------------------
    #
    # Variable-length samples (graphs, token sequences) — a capability the
    # reference lacks entirely (rows are fixed-width, uniform `disp`
    # enforced via MPI_Allreduce MAX, ddstore.hpp:78-82). A ragged variable
    # is stored as two fixed-width variables:
    #   {name}/values — the flattened elements (one global row == one
    #       element of shape item_shape), and
    #   {name}/index  — per-sample (global_values_start, length) int64.
    # Every sample's elements lie wholly inside its owner's values shard,
    # so a sample read is a single-peer contiguous read, and batched reads
    # coalesce per owner exactly like fixed-width get_batch.

    def add_ragged(self, name: str, samples: Sequence[np.ndarray]) -> None:
        """Register this rank's ragged shard: ``samples[i]`` has shape
        ``(len_i, *item_shape)`` with ``len_i`` varying per sample."""
        if f"{name}/values" in self._meta:
            raise DDStoreError(-8, f"add_ragged({name}): already exists")
        samples = [np.ascontiguousarray(s) for s in samples]
        if samples:
            item_shape = tuple(samples[0].shape[1:])
            dtype = samples[0].dtype
            for s in samples:
                if tuple(s.shape[1:]) != item_shape or s.dtype != dtype:
                    raise ValueError(
                        f"add_ragged({name}): inconsistent item shape/dtype")
            flat = np.concatenate(samples, axis=0)
        else:  # a rank may hold zero samples
            item_shape, dtype = (), np.dtype(np.float32)
            flat = np.empty((0,), dtype)
        # Ranks with no samples can't infer item shape/dtype locally; adopt
        # the group consensus (add() below still enforces agreement).
        metas = self.group.allgather((len(samples), dtype.str, item_shape))
        nonempty = [(d, s) for n, d, s in metas if n > 0]
        if not samples and nonempty:
            dtype = np.dtype(nonempty[0][0])
            item_shape = nonempty[0][1]
            flat = np.empty((0,) + item_shape, dtype)
        lengths = np.array([s.shape[0] for s in samples], np.int64)
        self.add(f"{name}/values", flat)
        begin, _ = self.my_row_range(f"{name}/values")
        starts = begin + np.concatenate(([0], np.cumsum(lengths)[:-1]))\
            if len(lengths) else np.empty((0,), np.int64)
        index = np.stack([starts, lengths], axis=1) if len(lengths) \
            else np.empty((0, 2), np.int64)
        try:
            self.add(f"{name}/index", index.astype(np.int64))
        except DDStoreError as e:
            # Ragged-level crash consistency: each add() already
            # unwinds ITSELF on a death mid-fence, but a death during
            # the SECOND add would otherwise leave the values half of
            # the pair registered — a partial ragged variable
            # is_ragged() rejects yet whose shard RAM lingers.
            if e.code == ERR_PEER_LOST:
                try:
                    self._native.free_var(self._wname(f"{name}/values"))
                except DDStoreError:
                    pass  # best-effort; the raise below is the news
                self._meta.pop(f"{name}/values", None)
            raise

    def is_ragged(self, name: str) -> bool:
        return f"{name}/index" in self._meta and f"{name}/values" in self._meta

    def ragged_total(self, name: str) -> int:
        """Number of ragged samples across all ranks."""
        return self.total_rows(f"{name}/index")

    def get_ragged(self, name: str, idx: int) -> np.ndarray:
        """Read one variable-length sample (shape ``(len, *item_shape)``)."""
        start, length = self.get(f"{name}/index", idx)[0]
        m = self._require(f"{name}/values")
        out = np.empty((int(length),) + m.sample_shape, m.dtype)
        if length:
            self._native.get(self._rname(f"{name}/values"), out,
                             int(start), int(length),
                             tenant=self._read_tenant())
        return out

    def get_ragged_batch(self, name: str, indices):
        """Read many variable-length samples in two batched rounds (index
        rows, then all element spans coalesced per owner). Returns
        ``(values, lengths)`` where ``values`` is the concatenation of the
        requested samples in request order — the natural input to
        pack-and-pad batching for XLA's static shapes."""
        idx = np.ascontiguousarray(indices, dtype=np.int64).reshape(-1)
        index = self.get_batch(f"{name}/index", idx)
        starts, lengths = index[:, 0], index[:, 1]
        m = self._require(f"{name}/values")
        if len(idx) == 0:
            return (np.empty((0,) + m.sample_shape, m.dtype),
                    np.empty((0,), np.int64))
        # Element row ids: concatenated aranges, built vectorized (this is
        # the hot fetch path — a Python loop over thousands of small
        # samples would dominate latency). Adjacent elements of one sample
        # coalesce into one contiguous run in the native core.
        total = int(lengths.sum())
        prefix = np.concatenate(([0], np.cumsum(lengths)[:-1]))
        rows = (np.repeat(starts - prefix, lengths)
                + np.arange(total, dtype=np.int64))
        values = np.empty((total,) + m.sample_shape, m.dtype)
        if total:
            self._native.get_batch(self._rname(f"{name}/values"),
                                   values, rows,
                                   tenant=self._read_tenant())
        return values, lengths.astype(np.int64)

    # -- metadata ----------------------------------------------------------

    def query(self, name: str) -> dict:
        info = self._native.query(self._rname(name))
        m = self._require(name)
        info["dtype"] = m.dtype
        info["sample_shape"] = m.sample_shape
        return info

    def total_rows(self, name: str) -> int:
        return int(self._native.query(self._rname(name))["total_rows"])

    def local_rows(self, name: str) -> int:
        return int(self._native.query(self._rname(name))["local_rows"])

    def my_row_range(self, name: str) -> Tuple[int, int]:
        """Global [begin, end) owned by this rank."""
        m = self._require(name)
        begin = int(sum(m.all_nrows[: self.rank]))
        return begin, begin + m.all_nrows[self.rank]

    def row_starts(self, name: str) -> np.ndarray:
        """Cumulative shard starts: ``row_starts[r]`` is the first global
        row owned by rank r (length world+1; the trailing entry is
        ``total_rows``). THE owner table the scatter-read planner
        binary-searches in the native core, surfaced to Python for the
        device-collective fetch planner."""
        m = self._require(name)
        return np.concatenate(
            ([0], np.cumsum(np.asarray(m.all_nrows, np.int64))))

    def owner_of_rows(self, name: str, indices) -> np.ndarray:
        """Owning group rank of each global row index (vectorized
        binary search over :meth:`row_starts`)."""
        idx = np.ascontiguousarray(indices, dtype=np.int64).reshape(-1)
        starts = self.row_starts(name)
        if idx.size and (idx.min() < 0 or idx.max() >= starts[-1]):
            raise IndexError(f"owner_of_rows({name}): index out of "
                             f"range [0, {int(starts[-1])})")
        return np.searchsorted(starts, idx, side="right") - 1

    def row_nbytes(self, name: str) -> int:
        """Bytes of one sample row (the bytes-moved ledger unit)."""
        m = self._require(name)
        return int(m.disp * m.dtype.itemsize)

    def variables(self):
        return sorted(self._meta)

    # -- epochs / sync -----------------------------------------------------

    def _classify_collective(self, e: DDStoreError,
                             what: str) -> DDStoreError:
        """Collective-failure analogue of :meth:`_classify`: a barrier
        or epoch fence aborted by the failure detector surfaces
        ``ERR_PEER_LOST`` naming the dead member (the native side
        already rolled the fence state machine back and fed the suspect
        registry), and the fix is the same elastic.recover handoff a
        lost read gets. A plain timeout (no suspect) passes through as
        the generic transport error — slow is not dead."""
        if e.code != ERR_PEER_LOST:
            return e
        peer = int(self._native.fault_stats().get("last_error_peer", -1))
        suspects = self.suspected_peers()
        return DDStoreError(
            e.code,
            f"{what}: peer rank {peer} died mid-collective (suspected: "
            f"{suspects}) — detected by the failure detector in "
            f"O(heartbeat), not a {what} timeout; the collective was "
            f"rolled back to a recoverable state. Invoke "
            f"elastic.recover, then re-enter the epoch/collective")

    def epoch_begin(self) -> None:
        try:
            self._native.epoch_begin()
        except DDStoreError as e:
            raise self._classify_collective(e, "epoch_begin") from None

    def epoch_end(self) -> None:
        try:
            self._native.epoch_end()
        except DDStoreError as e:
            raise self._classify_collective(e, "epoch_end") from None

    def fence_reset(self) -> None:
        """Force the epoch-fence state machine closed (local,
        idempotent). A fence abort need not be unanimous — a victim
        that died after partially disseminating its barrier notifies
        can let some survivors COMPLETE the fence while others roll
        back — so :func:`elastic.recover` calls this on every rank,
        realigning the group on one pre-fence state before the first
        post-recovery epoch."""
        self._native.fence_reset()

    def barrier(self) -> None:
        """Collective barrier over the store group (data-plane, cheap).
        Failure-aware: a member the heartbeat/ladder already declared
        dead aborts the wait in O(heartbeat) with the classified
        ``ERR_PEER_LOST`` naming it, never a flat
        ``DDSTORE_BARRIER_TIMEOUT_S`` sleep."""
        self._barrier_tag += 1
        try:
            self._native.barrier(self._barrier_tag)
        except DDStoreError as e:
            raise self._classify_collective(e, "barrier") from None

    # -- teardown ----------------------------------------------------------

    def free(self, name: Optional[str] = None) -> None:
        # Collective, like MPI_Win_free in the reference
        # (src/ddstore.cxx:79-96): no rank drops its shard while a peer may
        # still be reading it.
        self.barrier()
        if name is None:
            for n in list(self._meta):
                self._native.free_var(self._wname(n))
                del self._meta[n]
        else:
            self._native.free_var(self._wname(name))
            self._meta.pop(name, None)

    def close(self) -> None:
        try:
            self.barrier()
        except Exception:
            pass  # best effort: peers may already be gone on error paths
        self._native.close()

    # -- props -------------------------------------------------------------

    @property
    def cma_ops(self) -> int:
        """Ops served by the same-host CMA fast path (shared-memory
        mapped gather, or process_vm_readv for borrowed shards)."""
        return self._native.cma_ops

    def plan_stats(self) -> dict:
        """Cumulative scatter-read planner statistics (:meth:`get_batch`):
        batches/rows planned, coalesced runs, per-peer run lists, dedup
        hits, scratch staging, plus the derived ``plan_coalesce_ratio``
        and ``plan_runs_per_peer_list``. Counters are monotone since store
        creation; diff two snapshots for a per-epoch view (that is what
        ``DeviceLoader.metrics`` reports)."""
        return self._native.plan_stats()

    def fault_stats(self) -> dict:
        """Fault-injection and transient-retry counters (see
        :meth:`NativeStore.fault_stats`): injector draws/injections plus
        this store's retry/reconnect/backoff/giveup accounting. Monotone;
        diff snapshots for per-epoch views — ``DeviceLoader.metrics``
        wires this in as ``summary()["faults"]``."""
        return self._native.fault_stats()

    # -- ddtrace: event rings, spans, flight recorder ----------------------
    #
    # Process-global (rings belong to threads; in-process ThreadGroup
    # "ranks" share one trace — every event carries its rank), default
    # OFF with a one-relaxed-load off state. DDSTORE_TRACE=1 or
    # binding.trace_configure(1) turns recording on.

    def trace_dump(self):
        """Every live trace event of this process as a structured
        numpy array (``binding.TRACE_EVENT_DTYPE``), time-sorted.
        Feed per-rank dumps to ``python -m ddstore_tpu.obs merge`` for
        Chrome trace-event JSON, or ``obs.span_tree`` for text."""
        from . import binding

        return binding.trace_dump()

    def trace_flight_dump(self):
        """The last flight-recorder snapshot (taken automatically when
        ``kErrPeerLost``/``kErrQuota`` surfaces, a suspect verdict
        lands, or the readahead layer gives up on a window)."""
        from . import binding

        return binding.trace_flight_dump()

    def trace_stats(self) -> dict:
        """Trace counters (``binding.TRACE_STAT_KEYS``): ring/thread
        gauges + monotone captured/dropped/flight/span totals."""
        from . import binding

        return binding.trace_stats()

    def trace_summary(self) -> dict:
        """The ``summary()["trace"]`` payload: counters, ring
        occupancy, and (while tracing) measured span-latency p50/p99
        per (op class, route, peer) from the ring data.
        ``DeviceLoader.metrics`` wires this in automatically."""
        from . import binding
        from .obs import trace_summary

        st = binding.trace_stats()
        events = binding.trace_dump() if st.get("enabled") else None
        return trace_summary(st, events)

    # -- ddmetrics: live latency histograms + SLO monitor ------------------
    #
    # Per-store (unlike the process-global trace rings), always-on
    # (DDSTORE_METRICS, default 1): log2-bucketed latency/bytes
    # histograms per (op class, route, peer, reading tenant), updated
    # at op end with a few relaxed atomic increments — live
    # p50/p90/p99 WITHOUT tracing. ``cluster_metrics`` pulls every
    # peer's snapshot over the control plane and merges one cluster
    # view; the SLO monitor evaluates per-tenant objectives over
    # per-window deltas of the same histograms.

    def metrics_configure(self, enabled: int) -> None:
        """Flip this store's histograms at runtime (0/1; -1 keeps).
        Load-time knob: ``DDSTORE_METRICS`` (default on)."""
        self._native.metrics_configure(enabled)

    def metrics_enabled(self) -> bool:
        return self._native.metrics_enabled()

    def metrics_reset(self) -> None:
        self._native.metrics_reset()

    def metrics_snapshot(self):
        """This rank's live histogram cells
        (``binding.METRICS_CELL_DTYPE`` structured array)."""
        return self._native.metrics_snapshot()

    def metrics_pull(self, target: int):
        """One peer's cells over the control plane (``kOpMetrics`` on
        the dedicated heartbeat connection — never a data lane, never
        an injector draw; bounded by the control-retry ladder). Raises
        ``DDStoreError(ERR_PEER_LOST)`` for a suspected/dead peer."""
        return self._native.metrics_pull(target)

    def cluster_metrics(self):
        """The CLUSTER latency surface: every reachable rank's cells
        merged bucket-wise (``obs.merge_metrics``). Returns
        ``(cells, dead)`` where ``dead`` lists peers that could not be
        pulled (suspected/unreachable — the view assembles around
        them, no give-up, no exception)."""
        from .binding import DDStoreError
        from .obs import merge_metrics

        snaps = []
        dead = []
        for r in range(self.world):
            try:
                snaps.append(self.metrics_snapshot() if r == self.rank
                             else self.metrics_pull(r))
            except DDStoreError:
                dead.append(r)
        return merge_metrics(snaps), dead

    def metrics_stats(self) -> dict:
        """Histogram registry counters
        (``binding.METRICS_STAT_KEYS``)."""
        return self._native.metrics_stats()

    def metrics_summary(self) -> dict:
        """The ``summary()["latency"]`` payload: per-cell count/mean/
        p50/p90/p99 (``obs.latency_table`` over this rank's live
        cells). ``DeviceLoader.metrics`` wires this in automatically
        and reports per-epoch deltas."""
        from .obs import latency_table

        return latency_table(self.metrics_snapshot())

    def set_tenant_slos(self, spec: str) -> None:
        """Replace the per-tenant latency objectives
        (``"t=p99:5ms,t2=p50:200us"``; a bare ``"p99:5ms"`` names the
        default tenant; empty clears). Evaluation windows restart at
        NOW. Load-time knob: ``DDSTORE_TENANT_SLOS``."""
        self._native.slo_configure(spec)
        self._last_slo_breaches = []

    def evaluate_slos(self) -> list:
        """Evaluate every objective over the histogram delta since the
        last evaluation (rate-limited by ``DDSTORE_SLO_WINDOW_MS``).
        Returns breach dicts ``{tenant, pct, threshold_ms,
        measured_ms, count}``; each breach has already emitted a
        ``slo_breach`` trace event and dumped the flight recorder
        (while tracing is on). The loader calls this at epoch
        boundaries and fires the scheduler's replan trigger per
        breached tenant."""
        evals_before = self._native.slo_stats()["evaluations"]
        rows = self._native.slo_evaluate()
        out = []
        if rows:
            tenants = self._native.metrics_tenants()
            for slot, pct, thr_ns, low_ns, count in rows:
                tenant = tenants[slot] if 0 <= slot < len(tenants) \
                    else f"slot{slot}"
                out.append({"tenant": tenant, "pct": int(pct),
                            "threshold_ms": thr_ns / 1e6,
                            "measured_ms": low_ns / 1e6,
                            "count": int(count)})
        # A rate-limited call (inside DDSTORE_SLO_WINDOW_MS) is not an
        # evaluation: keep the previous verdict on the books.
        if rows or \
                self._native.slo_stats()["evaluations"] != evals_before:
            self._last_slo_breaches = out
        return out

    def slo_stats(self) -> dict:
        """SLO monitor counters (``binding.SLO_STAT_KEYS``)."""
        return self._native.slo_stats()

    def slo_summary(self) -> dict:
        """The ``summary()["slo"]`` payload: monitor counters plus the
        most recent evaluation's breach list."""
        out = dict(self.slo_stats())
        out["last_breaches"] = list(
            getattr(self, "_last_slo_breaches", []))
        return out

    # -- replication / failover / health ----------------------------------

    @property
    def replication(self) -> int:
        """Replication factor in force (``DDSTORE_REPLICATION`` clamped
        to ``[1, world]``). At R > 1 every rank hosts read-only mirrors
        of the next R-1 ranks' shards; reads to a dead/suspected peer
        transparently fail over to its replica chain, and
        ``kErrPeerLost`` fires only when all R holders are gone."""
        return self._native.replication

    def replica_set(self, owner: int) -> list:
        """Replica chain of ``owner``'s shard, primary first (chain
        placement: ``[owner, owner-1, ..., owner-R+1] mod world``)."""
        return self._native.replica_set(owner)

    def refresh_mirrors(self) -> None:
        """Re-pull every mirror this rank hosts, creating missing ones
        — the elastic-recovery rebuild (collective discipline is the
        caller's; :func:`elastic.recover`/``rejoin`` barrier around
        it). Suspected owners are skipped: their mirror keeps the last
        good bytes, which is exactly the copy failover is serving."""
        self._native.refresh_mirrors()

    def health_state(self) -> list:
        """Per-peer suspicion flags (heartbeat verdicts ∪ data-path
        ladder give-ups), one bool per rank."""
        return self._native.health_state()

    def suspected_peers(self) -> list:
        """Ranks currently suspected dead."""
        return [r for r, s in enumerate(self.health_state()) if s]

    def mark_suspect(self, target: int, suspected: bool = True) -> None:
        """Force a peer into (or out of) the suspect set (test hook;
        the failover router short-circuits suspected peers)."""
        self._native.mark_suspect(target, suspected)

    def heartbeat_configure(self, interval_ms: int,
                            suspect_n: int = 0) -> None:
        """(Re)start the heartbeat detector (``interval_ms`` <= 0
        stops it; ``suspect_n`` <= 0 keeps the env/default)."""
        self._native.heartbeat_configure(interval_ms, suspect_n)

    def failover_stats(self) -> dict:
        """Replicated-read failover + heartbeat counters (see
        :data:`binding.FAILOVER_STAT_KEYS`). Monotone except the
        gauges; ``DeviceLoader.metrics`` wires this in as
        ``summary()["failover"]``."""
        return self._native.failover_stats()

    # -- end-to-end data integrity -----------------------------------------

    @property
    def verify_mode(self) -> bool:
        """Reader-side checksum verification in force
        (``DDSTORE_VERIFY=1`` or :meth:`integrity_configure`). Off by
        default — the unverified tree is byte-, error-code- and
        seeded-fault-counter-identical to the pre-integrity store."""
        return bool(self._native.integrity_stats().get("verify_mode"))

    def integrity_configure(self, verify: int = -1,
                            scrub_ms: int = -1) -> None:
        """Runtime integrity toggles: ``verify`` -1 keeps / 0 off / 1
        on; ``scrub_ms`` -1 keeps / 0 stops the background scrubber /
        >0 (re)starts it at that per-mirror tick (load-time:
        ``DDSTORE_VERIFY`` / ``DDSTORE_SCRUB_MS``)."""
        self._native.integrity_configure(verify, scrub_ms)

    def integrity_stats(self) -> dict:
        """Integrity counters (``binding.INTEGRITY_STAT_KEYS``):
        verified reads/bytes, the mismatch → seq-retry →
        primary-retry → replica ladder's activity, surfaced
        ``ERR_CORRUPT`` errors, and the scrubber's
        checked/divergent/repaired ledger. Monotone except the gauges;
        ``DeviceLoader.metrics`` wires this in as
        ``summary()["integrity"]``."""
        return self._native.integrity_stats()

    def row_sums(self, name: str, row0: int = 0,
                 count: Optional[int] = None):
        """This rank's per-row checksum table slice for ``name`` as
        ``(sums, seq)`` (test/debug hook; the verified-read machinery
        fetches peers' tables over the control plane itself)."""
        return self._native.integrity_sums(self._rname(name), row0,
                                           count)

    def scrub_once(self) -> int:
        """One synchronous scrub pass over every mirror this rank
        hosts (the deterministic test/bench hook; ``DDSTORE_SCRUB_MS``
        runs the same check one mirror per tick in the background).
        Returns the number of divergent mirrors found; repairs (the
        row-aligned re-pull) run inline and are counted in
        :meth:`integrity_stats`."""
        return self._native.integrity_scrub()

    # -- tiered storage: hot-row cache + cold placement --------------------

    def tier_configure(self, cache_bytes: int = -1) -> None:
        """Runtime hot-row cache budget (bytes; 0 disables and evicts
        everything, < 0 keeps; load-time:
        ``DDSTORE_TIER_CACHE_BYTES``). The readahead engine warms the
        cache with upcoming windows' row lists automatically whenever
        the budget is non-zero — size it to hold (ring depth +
        prefetch depth + 1) windows of the active variables."""
        self._native.tier_configure(cache_bytes)

    def set_tier_placement(self, tenant: str, cold: bool) -> None:
        """Placement policy for ``tenant``'s replication mirrors and
        snapshot kept copies: ``cold`` lands them as file-backed
        mappings under ``DDSTORE_TIER_COLD_DIR`` (NVMe page cache,
        evictable) instead of pinned RAM — a busy trainer pins RAM, an
        eval snapshot reader tolerates NVMe latency. Load-time:
        ``DDSTORE_TIER_PLACEMENT``."""
        self._check_tenant_label(tenant)
        self._native.set_tier_placement(tenant, cold)

    def var_tier(self, name: str) -> str:
        """The registered storage tier of ``name``: ``"hot"`` (RAM) or
        ``"cold"`` (file-backed)."""
        return "cold" if self._native.var_tier(self._rname(name)) else \
            "hot"

    def cache_prefetch(self, name: str, rows, window: int = 0) -> None:
        """Warm the hot-row cache with sorted-unique global ``rows`` of
        ``name`` under eviction key ``window`` (advisory; the fill runs
        detached on the native async pool and is charged against the
        reading tenant's byte quota until eviction). The readahead
        engine calls this with its upcoming windows' row lists — a free
        lookahead, the plan exists before the window is issued."""
        self._require(name)
        self._native.cache_prefetch(self._rname(name), rows,
                                    window=window,
                                    tenant=self._read_tenant())

    def cache_evict(self, window: int = -1) -> int:
        """Evict window ``window``'s hot-cache entries (< 0: every
        entry); returns the count evicted. The readahead engine evicts
        each window as its last batch is consumed."""
        return self._native.cache_evict(window)

    def tiering_stats(self) -> dict:
        """Tiering counters (:data:`binding.TIERING_STAT_KEYS`): cache
        budget/occupancy gauges, cold-tier registrations, and the
        monotone hit/miss/fill/evict ledger. Monotone except the
        gauges; ``DeviceLoader.metrics`` wires this in as
        ``summary()["tiering"]``."""
        return self._native.tiering_stats()

    def check_health(self) -> list:
        """Poll the liveness view and fire the peer listeners exactly
        once per NEW suspect (the scheduler replans routes/lanes off a
        dead peer immediately instead of at the next deadline burn).
        Returns the newly suspected ranks."""
        now = frozenset(self.suspected_peers())
        fresh = sorted(now - self._known_suspects)
        self._known_suspects = now
        if fresh:
            self._fire_peer_listeners()
        return fresh

    def set_retry_deadline(self, seconds: float) -> None:
        """Override this store's transient-retry deadline (seconds;
        ``<= 0`` restores ``DDSTORE_OP_DEADLINE_S``). The degraded
        readahead path shares one deadline budget across a window
        give-up and its per-batch refetch through this; per-store, so
        other stores keep their full budgets."""
        self._native.set_retry_deadline(seconds)

    def lane_state(self) -> dict:
        """Striped-lane autotuner snapshot (TCP backend): configured
        pool size (``DDSTORE_TCP_LANES``), the lane count striped reads
        currently engage, whether the tuner parked, and the best
        measured stripe bandwidth. ``{}`` for the local backend."""
        return self._native.lane_state()

    def lane_bytes(self, target: int = -1) -> list:
        """Per-lane response bytes over the wire path since store
        creation (``target >= 0`` for one peer, ``-1`` summed across
        peers). Monotone — ``DeviceLoader.metrics`` diffs this per epoch
        into ``summary()["bytes_moved"]``'s lane view. ``[]`` for the
        local backend."""
        return self._native.lane_bytes(target)

    def transport_facts(self) -> dict:
        """First-class wire-backend verdict: ``backend`` (the store
        backend), ``wire`` ("uring" when the io_uring loop is engaged,
        else "tcp"/"local"), ``uring_engaged`` and ``uring_reason``
        (the capability probe's words when a requested uring backend
        fell back — never a crash). Bench/diag record this so a
        TCP-fallback run is diagnosable from its artifacts alone."""
        facts = {"backend": self.backend, "wire": self.backend,
                 "uring_engaged": False, "uring_reason": ""}
        if self.backend != "tcp":
            return facts
        state = self._native.uring_state()
        if state < 0:  # plain TCP handle
            return facts
        facts["uring_engaged"] = state == 1
        facts["uring_reason"] = self._native.uring_reason()
        facts["wire"] = "uring" if state == 1 else "tcp"
        return facts

    # -- cost-model scheduler hooks ---------------------------------------

    def sched_cells(self) -> list:
        """Warm-window measurement cells (router + lane tuners) for the
        cost-model scheduler (:mod:`ddstore_tpu.sched`): one dict per
        (source, class, knob) cell with its EWMA bytes/s and clean
        sample count. ``[]`` for the local backend."""
        return self._native.sched_cells()

    def sched_pin_route(self, cls: int, mode: int) -> None:
        """Planner route pin (0 = CMA, 1 = TCP, -1 = release) for one
        traffic class. No-op on the local backend (no router)."""
        try:
            self._native.sched_pin_route(cls, mode)
        except DDStoreError:
            pass  # non-TCP backend: nothing to pin

    def sched_pin_lanes(self, cls: int, lanes: int) -> None:
        """Planner lane-width pin (>= 1, or -1 to release) for one
        traffic class. No-op on the local backend (no lanes)."""
        try:
            self._native.sched_pin_lanes(cls, lanes)
        except DDStoreError:
            pass

    def set_async_width(self, n: int) -> None:
        """Async admission width override (<= 0 restores the
        ``DDSTORE_ASYNC_THREADS`` / core-ladder default)."""
        self._native.set_async_width(n)

    @property
    def async_width(self) -> int:
        """The async admission width currently in force."""
        return self._native.async_width

    def add_peer_listener(self, cb) -> None:
        """Register a zero-arg callable invoked after any peer endpoint
        changes (:meth:`update_peer` — elastic recovery re-pointing a
        rank at a replacement process). The cost-model scheduler hooks
        its topology-change replan here: the native tuners AND the
        planner pins reset on a peer swap, so the plan must be rebuilt
        from fresh samples."""
        self._peer_listeners.append(cb)

    def update_peer(self, target: int, host: str, port: int) -> None:
        """Re-point one peer at a new endpoint (elastic recovery) and
        notify peer listeners (scheduler replan). Native side closes the
        stale connections, re-probes CMA, resets the adaptive tuners,
        releases every planner pin and clears the peer's suspicion (the
        replacement gets a clean liveness slate)."""
        self._native.update_peer(target, host, port)
        self._known_suspects = self._known_suspects - {target}
        self._fire_peer_listeners()

    def _fire_peer_listeners(self) -> None:
        # Prune dead listeners first (a collected Scheduler advertises
        # its death via the closure's `alive` attribute) — long-lived
        # stores see one registration per discarded loader.
        self._peer_listeners = [
            cb for cb in self._peer_listeners
            if getattr(cb, "alive", lambda: True)()]
        for cb in list(self._peer_listeners):
            try:
                cb()
            except Exception:
                pass  # observability hook; never fails recovery

    @property
    def rank(self) -> int:
        return self.group.rank

    @property
    def world(self) -> int:
        return self.group.size

    # -- tenant namespaces / snapshot epochs -------------------------------
    #
    # The root DDStore IS the default tenant "": both hooks are the
    # identity, so every pre-tenancy call path (and its native names) is
    # byte-identical. ``attach()`` returns a TenantHandle whose hooks
    # scope registrations to "\x02<tenant>\x02<name>" and (for
    # ``snapshot=True``) wrap reads in a pinned snapshot view.

    def _wname(self, name: str) -> str:
        """Native registry name for writes/registration."""
        return name

    def _rname(self, name: str) -> str:
        """Native registry name for reads/metadata."""
        return name

    def _read_tenant(self) -> str:
        """Tenant label async reads are admitted (QoS shares) and
        ledgered under. "" on the root store = derive from the variable
        name, the pre-tenancy behavior; a TenantHandle reports its own
        label so reads of the SHARED default namespace still count
        against the reading tenant's share."""
        return ""

    def attach(self, tenant: str = "", snapshot: bool = False):
        """Attach a tenant-scoped handle to this (long-lived, shared)
        store. The handle shares the native store, group and rank but
        scopes every registration to its own namespace — handles of
        different tenants cannot see, read, update, or free each
        other's variables. The DEFAULT namespace (variables registered
        through this root store) stays readable from every handle —
        that is how an eval or inference job attaches to the resident
        training shards.

        ``snapshot=True`` additionally pins the CURRENT content version
        of every shard on every rank: the handle is read-only and its
        reads stay byte-stable while the owner keeps calling
        ``update()`` + epoch fences (copy-on-publish keeps the pinned
        version for updated shards only; ``detach()`` — or the context
        manager exit — releases the pins and reclaims kept copies on
        last detach). The acquire places pins rank by rank, so do not
        race it against a writer's ``update``: attach at a quiescent
        point (between epoch fences, or after a ``barrier()`` with the
        writer) or the snapshot may pin different content versions on
        different ranks. Updates landing AFTER the acquire are exactly
        what the pins protect against."""
        from .tenant import TenantHandle

        return TenantHandle(self, tenant, snapshot=snapshot)

    def set_tenant_quota(self, tenant: str, max_bytes: int,
                         max_vars: int = -1) -> None:
        """Byte/var registration budget for ``tenant`` (< 0 =
        unlimited; runtime equivalent of ``DDSTORE_TENANT_QUOTAS``).
        An over-budget ``add``/``init`` raises ``DDStoreError`` with
        code ``ERR_QUOTA`` (-11) — admission refused, nothing died."""
        self._check_tenant_label(tenant)
        self._native.tenant_set_quota(tenant, max_bytes, max_vars)

    def set_tenant_share(self, tenant: str, share: int) -> None:
        """Async-admission weight (runtime equivalent of
        ``DDSTORE_TENANT_SHARES``): with any share configured, each
        tenant runs at most ``max(1, width * share / total)``
        concurrent async batched reads — one tenant's readahead cannot
        starve another's scatter reads."""
        self._check_tenant_label(tenant)
        self._native.tenant_set_share(tenant, share)

    def set_tenant_lane_budget(self, tenant: str, lanes: int) -> None:
        """QoS lane budget: cap the transport lanes ``tenant``'s
        striped reads engage (<= 0 clears; the cost-model scheduler
        plans these from the shares). No-op on non-TCP backends."""
        self._check_tenant_label(tenant)
        self._native.tenant_set_lane_budget(tenant, lanes)

    @staticmethod
    def _check_tenant_label(tenant: str) -> None:
        """Every native entry point keyed by a tenant label goes
        through here: control characters collide with the native
        name-scoping / names-CSV formats, and the env-spec delimiters
        would desynchronize the Python ledger from the native gate."""
        from .tenant.handle import _check_tenant_label

        _check_tenant_label(tenant)

    def tenant_stats(self) -> Dict[str, dict]:
        """Per-tenant ledger: ``{tenant: {bytes, vars, quota_*,
        read/served traffic, async admissions/deferrals, snapshot
        pins, share}}`` (see ``binding.TENANT_STAT_KEYS``). Monotone
        counters diff per epoch via ``summary()["tenants"]``."""
        return {t: self._native.tenant_stats(t)
                for t in self._native.tenant_names()}

    def snapshot_stats(self) -> dict:
        """This rank's snapshot gauges: active pins, kept versions and
        their RAM cost (the copy-on-publish ledger), plus
        ``reclaimed_pins`` — the monotone count of stranded pins the
        stale-pin reaper released (TTL-expired or dead-owner)."""
        return self._native.snapshot_stats()

    # -- serving gateway ---------------------------------------------------

    def gateway_configure(self, enabled: int = -1, lease_ms: int = -1,
                          defer_ms: int = -1, queue_cap: int = -1,
                          admit_margin_pct: int = -1,
                          lane_share: int = -1,
                          pin_ttl_ms: int = -1) -> None:
        """Runtime serving-gateway (re)configuration; -1 keeps each
        field. ``enabled=1`` clears a previous drain and (re)arms the
        lease reaper; ``pin_ttl_ms`` arms stranded-snapshot-pin
        reclaim even with the gateway off. Load-time knobs:
        ``DDSTORE_GATEWAY`` / ``DDSTORE_GW_*`` /
        ``DDSTORE_SNAP_PIN_TTL_MS``."""
        self._native.gateway_configure(
            enabled, lease_ms, defer_ms, queue_cap, admit_margin_pct,
            lane_share, pin_ttl_ms)

    def gateway_session(self, tenant: str = "", snapshot: bool = False,
                        quota_bytes: int = 0, target: int = -1,
                        max_retries: int = None, seed: int = None):
        """Open an ephemeral reader session against ``target``'s
        gateway (< 0 = this rank): a lease-renewed
        :class:`~ddstore_tpu.gateway.GatewaySession` whose reads honor
        admission control (``ERR_ADMISSION`` → seeded-jitter backoff
        using the retry-after hint). Use as a context manager; a
        reader SIGKILLed mid-session is reaped within O(lease) — its
        pins, quota reservation and lane share released."""
        from .gateway import GatewaySession

        self._check_tenant_label(tenant)
        return GatewaySession(self, tenant=tenant, snapshot=snapshot,
                              quota_bytes=quota_bytes, target=target,
                              max_retries=max_retries, seed=seed)

    def gateway_drain(self, deadline_ms: int = 1000) -> bool:
        """Graceful drain: stop admitting, let in-flight reads finish
        under the deadline, shed the rest with ``ERR_ADMISSION``.
        True when the gateway went quiet. ``elastic.recover`` drains a
        leaving rank through this instead of RSTing its readers;
        ``gateway_configure(enabled=1)`` re-opens."""
        return self._native.gateway_drain(deadline_ms)

    def gateway_reap(self) -> int:
        """One synchronous lease/stale-pin reap pass (the
        deterministic hook for what the background reaper does on its
        cadence). Returns the number of stranded pins reclaimed."""
        return self._native.gateway_reap()

    def gateway_stats(self) -> dict:
        """Gateway counters (``binding.GATEWAY_STAT_KEYS``): session
        gauges, monotone attach/expiry and admission verdicts, and the
        last retry-after hint."""
        return self._native.gateway_stats()

    def _require(self, name: str) -> _VarMeta:
        if name not in self._meta:
            raise KeyError(f"unknown variable {name!r}; registered: "
                           f"{self.variables()}")
        return self._meta[name]

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
