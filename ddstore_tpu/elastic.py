"""In-run elastic recovery: survive a rank death without restarting the job.

The reference's failure story is fatal: a transport error prints to stderr
and the job dies (/root/reference/src/common.cxx:100-111 ``exit(1)``), and
SURVEY §5 records "failure detection / elastic recovery: none" as the gap.
The restart-time half (bounded timeouts + world-size re-sharding,
``utils/checkpoint.py``) landed in round 4; this module is the in-run half:

* Survivors hit a bounded-timeout :class:`DDStoreError` on reads to the
  dead rank, then call :func:`recover` — a collective over the NEW world.
* A supervisor relaunches the dead rank, which calls :func:`rejoin`: it
  builds a fresh ``DDStore`` and re-registers every variable from its
  last checkpoint shard (``utils.save_shard`` format).
* Everyone meets at a **generation-stamped rendezvous directory**
  (``<root>/gen<G>``): survivors target their local generation + 1, the
  replacement reads the last committed generation from ``<root>/GENERATION``
  — so repeated recoveries in one run compose, and a late replacement can
  never join a stale generation.
* Endpoints are re-exchanged; survivors re-point every joiner rank (and
  any peer whose endpoint changed) via native ``UpdatePeer`` — stale
  connections closed, CMA re-probed against the new pid — while the
  replacement gets the full table via the normal construction path.
  Barrier sequence numbers are re-synced to the max so the data-plane
  dissemination barrier stays aligned.

Scope: the recovered shard holds the dead rank's LAST CHECKPOINT — rows
updated after that checkpoint are rolled back on that shard (the same
contract every checkpoint/restore system has). Works for any number of
simultaneous deaths as long as at least one rank survives; call it between
training steps (with the default non-collective epochs there is no other
in-flight store state to reconcile).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from .binding import DDStoreError
from .rendezvous import FileGroup
from .store import DDStore, _row_disp, _VarMeta

__all__ = ["recover", "rejoin"]

_GEN_FILE = "GENERATION"


def _default_timeout() -> float:
    """The rendezvous must outlast the slowest death-detection path.
    With the heartbeat detector ON, a survivor wedged in a barrier or
    epoch fence aborts in O(heartbeat) (the detector-integrated
    barrier); the worst case is the detector-OFF one — a survivor
    notices only after DDSTORE_BARRIER_TIMEOUT_S (default 300 s).
    Every survivor must reach recover() before the first one's
    rendezvous expires, so the default waits that long plus margin."""
    try:
        barrier_s = float(os.environ.get("DDSTORE_BARRIER_TIMEOUT_S", 300))
    except ValueError:
        barrier_s = 300.0
    return max(120.0, barrier_s + 60.0)


def _gen_dir(root: str, gen: int) -> str:
    return os.path.join(root, f"gen{gen}")


def _read_generation(root: str) -> int:
    try:
        with open(os.path.join(root, _GEN_FILE)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return 0


def _commit_generation(root: str, gen: int) -> None:
    # Every participant writes the same value; os.replace is atomic, so
    # concurrent writers are idempotent.
    path = os.path.join(root, _GEN_FILE)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(str(gen))
    os.replace(tmp, path)


def _vars_meta(store: DDStore) -> dict:
    """Variable registry a survivor publishes: identity fields first
    (dtype/shape/row counts — every rank must agree on these) plus the
    tiering state (``readonly`` == the shard is served from a read-only
    mmap). Tiering is EXCLUDED from the agreement check: a survivor may
    have spilled a variable after the victim's last checkpoint, which
    changes where its bytes live but not what they are."""
    return {name: (m.dtype.str, list(m.sample_shape), list(m.all_nrows),
                   bool(m.readonly))
            for name, m in store._meta.items()}


def _identity(meta: dict) -> dict:
    """The agreement-checked subset of :func:`_vars_meta`."""
    return {name: tuple(v[:3]) for name, v in meta.items()}


def _sync_state(store: DDStore, group, *, joiner: bool,
                ckpt_dir: Optional[str]) -> list:
    """Second collective of a recovery generation: align barrier sequence
    numbers and variable registries. Survivors publish their metadata;
    the joiner re-registers every variable from its checkpoint shard.
    Returns the list of joiner ranks (the ones that published no
    metadata) — survivors re-point those peers UNCONDITIONALLY, endpoint
    change or not (a replacement relaunched on the same host:port is
    still a new process whose CMA pid must be re-probed)."""
    info = group.allgather(
        (store._barrier_tag, store._native.barrier_seq,
         None if joiner else _vars_meta(store)))
    # Everyone adopts the max barrier tag AND the transport's collective
    # sequence count, so the next data-plane barrier lines up on all
    # ranks (a joiner starts both from zero; survivors are already at
    # the max — their adoption is a no-op).
    store._barrier_tag = max(t for t, _, _ in info)
    store._native.set_barrier_seq(max(s for _, s, _ in info))
    joiners = [r for r, (_, _, v) in enumerate(info) if v is None]
    metas = [v for _, _, v in info if v is not None]
    if not metas:
        raise DDStoreError(-7, "elastic recovery: no surviving rank has "
                               "variable metadata to rebuild from")
    ref = metas[0]
    for other in metas[1:]:
        if _identity(other) != _identity(ref):
            raise DDStoreError(-9, "elastic recovery: survivors disagree "
                                   "on variable metadata")
    if not joiner:
        if _identity(_vars_meta(store)) != _identity(ref):
            raise DDStoreError(-9, "elastic recovery: this rank's variable "
                                   "registry diverged from the group's")
        return joiners
    if ckpt_dir is None:
        raise ValueError("rejoin() needs ckpt_dir to rebuild the shard")
    from .utils.checkpoint import _stem

    for name in sorted(ref):
        dt, sshape, all_nrows = ref[name][:3]
        # Tiering follows the group: when EVERY survivor serves the
        # variable from a read-only mapping (it was spilled/add_mmap'd),
        # the replacement must come back the same way — mmap the
        # checkpoint shard instead of re-materializing it in RAM, or one
        # recovery would silently un-spill a variable that was spilled
        # precisely because it does not fit.
        tiered = all(v[name][3] for v in metas)
        dtype = np.dtype(dt)
        sample_shape = tuple(sshape)
        nrows = int(all_nrows[store.rank])
        stem = _stem(ckpt_dir, name, store.rank)
        if nrows:
            try:
                with open(stem + ".json") as f:
                    side = json.load(f)
            except OSError as e:
                raise DDStoreError(
                    -7, f"rejoin: no checkpoint sidecar for {name!r} at "
                        f"{stem}.json ({e}) — was save_shard called before "
                        "the crash?") from None
            if side["nrows"] != nrows or side["dtype"] != dtype.str \
                    or tuple(side["sample_shape"]) != sample_shape:
                raise DDStoreError(
                    -9, f"rejoin: checkpoint {stem}.bin holds "
                        f"{side['nrows']} rows of {side['dtype']} "
                        f"{tuple(side['sample_shape'])} but the group "
                        f"expects {nrows} rows of {dtype.str} "
                        f"{sample_shape} — stale or foreign checkpoint")
            if tiered:
                arr = np.memmap(stem + ".bin", dtype=dtype, mode="r",
                                shape=(nrows,) + sample_shape)
            else:
                arr = np.fromfile(stem + ".bin", dtype=dtype).reshape(
                    (nrows,) + sample_shape)
        else:
            arr = np.empty((0,) + sample_shape, dtype)
        if tiered:
            # Serve straight from page cache (the rejoin half of
            # spill_to_disk): the mapping is pinned in the meta exactly
            # like add_file's cold tier, and update stays refused.
            store._native.add(name, arr, all_nrows, copy=False)
            store._meta[name] = _VarMeta(dtype, sample_shape,
                                         _row_disp(sample_shape),
                                         all_nrows, pinned=arr,
                                         readonly=True, tier="cold")
            store._native.set_var_tier(name, 1)
        else:
            store._native.add(name, np.ascontiguousarray(arr), all_nrows,
                              copy=True)
            store._meta[name] = _VarMeta(dtype, sample_shape,
                                         _row_disp(sample_shape),
                                         all_nrows)
    return joiners


def recover(store: DDStore, root: str,
            timeout: Optional[float] = None) -> None:
    """Survivor side. Collective over the new world: EVERY surviving rank
    must call this after a peer death, and blocks until the supervisor's
    replacement rank has joined via :func:`rejoin`. Detection is a
    bounded-timeout :class:`DDStoreError` on a read or barrier; a
    survivor whose access pattern never touches the dead rank must be
    told out of band (or reach the next collective, which will error).
    The default ``timeout`` covers the SLOWEST detection path — a peer
    wedged in a data-plane barrier notices only after
    ``DDSTORE_BARRIER_TIMEOUT_S`` — so early detectors wait for it.

    On return the store serves every global row again: survivors kept
    their shards, the replacement restored its shard from its last
    checkpoint, and the control-plane group has been swapped for the new
    generation's."""
    if store._endpoints is None:
        raise ValueError("recover() requires the tcp backend")
    if store.group is not store.world_group:
        # width=... replica-split stores: the generation bookkeeping in
        # `root` is one sequence, not one per replica group — two
        # replicas recovering would cross-wire each other's rendezvous.
        raise ValueError("elastic recovery does not support replica-"
                         "split (width=...) stores yet; recover the "
                         "full-world store")
    if timeout is None:
        timeout = _default_timeout()
    # Serving gateway: quiesce ephemeral readers BEFORE the topology
    # swap. Drain stops admitting, lets in-flight reads finish under a
    # short deadline, and sheds the rest with ERR_ADMISSION
    # (defer-not-peer-lost: sessions back off on the retry-after hint
    # and resume) — instead of their reads dying on re-pointed sockets
    # mid-swap and masquerading as a second failure. Re-enabled after
    # the post-recovery barrier proves the new world.
    gw_draining = False
    try:
        if store.gateway_stats().get("enabled", 0):
            store.gateway_drain(deadline_ms=1000)
            gw_draining = True
    except Exception:  # noqa: BLE001 — a gateway-less store recovers fine
        pass
    gen = store._generation + 1
    group = FileGroup(_gen_dir(root, gen), store.rank, store.world, timeout)
    endpoints = group.allgather(
        (store._advertised, store._native.server_port))
    joiners = _sync_state(store, group, joiner=False, ckpt_dir=None)
    for r, ep in enumerate(endpoints):
        ep = tuple(ep)
        # Joiner ranks are re-pointed even at an UNCHANGED endpoint: a
        # relaunch on the same host:port is still a new process — stale
        # sockets must close and CMA must re-probe the new pid.
        if r != store.rank and (r in joiners
                                or ep != store._endpoints[r]):
            # Through the DDStore wrapper, not the native handle: the
            # cost-model scheduler's peer listeners must see the
            # topology change (the native tuners and planner pins reset
            # on the swap; the plan must be rebuilt).
            store.update_peer(r, ep[0], ep[1])
    store._endpoints = [tuple(e) for e in endpoints]
    store.group = group
    store._generation = gen
    _commit_generation(root, gen)
    # Fence realignment: a fence abort need not have been unanimous (a
    # victim that partially disseminated its notifies can let some
    # survivors complete the fence others aborted), so every survivor
    # forces its fence state machine closed here — the group re-enters
    # its first post-recovery epoch from one agreed state. Idempotent
    # and local; the replacement's fresh store starts closed anyway.
    store.fence_reset()
    # Data-plane barrier proves end-to-end connectivity of the new world
    # before anyone resumes training. RE-ENTERABLE: this (and the
    # replication rebuild) can itself abort if ANOTHER rank dies
    # mid-recovery — the failure-aware barrier classifies that in
    # O(heartbeat) — and by this point the generation is committed, so
    # the survivors simply run another recover() round (targeting
    # generation gen+1) for the newly dead rank.
    try:
        store.barrier()
        _restore_replication(store)
        if gw_draining:
            # New world proven end-to-end: reopen for ephemeral
            # readers (clears the sticky drain flag; deferred sessions
            # re-admit on their next backoff retry).
            store.gateway_configure(enabled=1)
    except DDStoreError as e:
        raise DDStoreError(
            e.code,
            f"elastic recovery generation {gen}: a peer died during "
            f"the post-recovery collective ({e}); the generation is "
            f"committed — call recover() again to run the next "
            f"recovery round for the newly dead rank") from None


def rejoin(root: str, rank: int, world: int, ckpt_dir: str, *,
           timeout: Optional[float] = None, port: int = 0) -> DDStore:
    """Replacement side: called by the relaunched process in place of the
    normal construction path. Joins the recovery generation's rendezvous,
    builds a fresh tcp :class:`DDStore` (normal endpoint exchange — the
    survivors' :func:`recover` participates in it), re-registers every
    variable from ``ckpt_dir``, and returns the ready store."""
    if timeout is None:
        timeout = _default_timeout()
    gen = _read_generation(root) + 1
    group = FileGroup(_gen_dir(root, gen), rank, world, timeout)
    store = DDStore(group, backend="tcp", port=port)
    _sync_state(store, group, joiner=True, ckpt_dir=ckpt_dir)
    store._generation = gen
    _commit_generation(root, gen)
    store.barrier()
    _restore_replication(store)
    return store


def _restore_replication(store: DDStore) -> None:
    """Third phase of a recovery generation (collective, after the
    connectivity barrier): rebuild the mirror chains for the new world.
    Survivors re-pull the replacement's restored shard into their
    mirrors (it may have rolled back to the checkpoint — a mirror
    holding newer pre-crash bytes would serve rows the owner no longer
    has); the replacement builds its whole chain from scratch. The
    closing barrier makes the restored replication factor live before
    anyone resumes training — a second death right after recovery is
    already covered again."""
    if store.replication <= 1:
        return
    store.refresh_mirrors()
    store.barrier()
