// TCP one-sided read transport for TPU-VM hosts (DCN path).
//
// TPU-VM hosts have no MPI and no RDMA verbs fabric; the equivalent of the
// reference's one-sided backends (MPI_Get under passive-target lock,
// /root/reference/include/ddstore.hpp:219-238, and libfabric fi_read,
// /root/reference/src/common.cxx:311-376) is a per-host serving thread that
// exposes the shard memory over TCP: readers send (var, offset, nbytes) and
// the server replies with the bytes, never involving the target's
// application/training thread. Deliberate non-reproductions of the
// reference's scars: no per-call memory registration (common.cxx:314-323
// re-registers an MR on every read and leaks it), no spin-polling
// (common.cxx:359-373), no fixed 80K-rank static peer tables (common.h:11),
// and requests to one peer are pipelined instead of one blocking op at a
// time. Scattered many-row reads are framed into vectored requests (one
// op-list frame -> one concatenated response scatter-received straight
// into the destination buffers), so a random-permutation batch costs
// syscalls per frame, not per row.

#ifndef DDSTORE_TPU_TCP_TRANSPORT_H_
#define DDSTORE_TPU_TCP_TRANSPORT_H_

#include <atomic>
#include <condition_variable>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

#include "cma.h"
#include "measure.h"
#include "store.h"
#include "thread_annotations.h"
#include "worker_pool.h"

namespace dds {

// Split "a,b,c" into non-empty tokens (endpoint/NIC address lists on the
// wire and in env vars all use this format).
inline std::vector<std::string> SplitCsv(const std::string& s) {
  std::vector<std::string> out;
  size_t pos = 0;
  while (pos <= s.size()) {
    size_t next = s.find(',', pos);
    if (next == std::string::npos) next = s.size();
    if (next > pos) out.push_back(s.substr(pos, next - pos));
    pos = next + 1;
  }
  return out;
}

class TcpTransport : public Transport {
 public:
  // Starts the serving thread immediately; binds to `port` (0 = ephemeral).
  TcpTransport(int rank, int world, int port);
  ~TcpTransport() override;

  // The port actually bound (for rendezvous). -1 if the server failed.
  int server_port() const { return server_port_; }

  // Called once the owning Store exists; the server reads shards through it.
  void Attach(Store* store) { store_ = store; }

  // Peer endpoint table, from the caller's rendezvous (the reference
  // exchanges endpoints with MPI_Allgather, common.cxx:285-302; here the
  // Python layer does it). Must be called before any Read/Barrier. Each
  // host entry may be a comma-separated address list (one per NIC): the
  // members of that peer's connection pool are spread round-robin across
  // the advertised addresses, so striped reads ride every DCN NIC — the
  // reference can only force ONE fabric interface (FABRIC_IFACE,
  // common.cxx:32,54-59).
  int SetPeers(const std::vector<std::string>& hosts,
               const std::vector<int>& ports);

  // Elastic recovery: the dissemination barrier matches notifies by the
  // transport's own collective sequence number, so a rejoined rank must
  // adopt the group's current count before its first barrier. Survivors
  // report theirs (identical across them — collectives are lockstep);
  // everyone adopts the max (a no-op for survivors).
  int64_t barrier_seq();
  void SetBarrierSeq(int64_t seq);

  // Elastic recovery: re-point ONE peer at a new endpoint (a relaunched
  // replacement process — the in-run half of SURVEY §5's "elastic
  // recovery", where the reference exits fatally, common.cxx:100-111).
  // Closes the peer's pooled connections (they belonged to the dead
  // process) and resets its CMA state so the next read reconnects to
  // the new endpoint and re-probes the new pid.
  int UpdatePeer(int target, const std::string& host_csv, int port);

  // Local source addresses (one per NIC) to bind outgoing connections to,
  // round-robin by pool index; empty = kernel default. Mirrors
  // DDSTORE_IFACES on the receive side of the same NIC-spreading story.
  void SetLocalIfaces(const std::vector<std::string>& addrs) {
    local_addrs_ = addrs;
  }

  // Owned shards are backed by /dev/shm data files when the CMA registry
  // is up: same-host peers mmap them once and serve batched reads with
  // plain memcpy — the scatter-read fast path (see cma.h). malloc
  // fallback when shm is unavailable (the shard then rides the
  // process_vm_readv / TCP paths instead).
  void* AllocShard(const std::string& name, int64_t nbytes) override {
    if (cma_reg_ && nbytes > 0) {
      uint64_t id;
      if (void* p = cma_reg_->AllocData(nbytes, &id)) return p;
    }
    return ::malloc(nbytes > 0 ? static_cast<size_t>(nbytes) : 1);
  }
  void FreeShard(const std::string& name, void* base) override {
    if (cma_reg_ && cma_reg_->FreeData(base)) return;
    ::free(base);
  }

  // Variable-lifecycle hooks (Store calls these under its exclusive
  // lock): publish/clear the local shard mapping in the CMA registry so
  // same-host peers can read it one-sidedly (see cma.h).
  void PublishVar(const std::string& name, const void* base,
                  int64_t nbytes) override {
    if (cma_reg_) cma_reg_->Publish(name, base, nbytes);
  }
  void UnpublishVar(const std::string& name) override {
    if (cma_reg_) cma_reg_->Unpublish(name);
  }
  // Ops served via the CMA fast path since construction (observability +
  // tests asserting the path actually engaged).
  int64_t cma_ops() const { return cma_ops_.load(); }

  // Successful dials of the same-host Unix-domain fast lane since
  // construction (observability: distinguishes "loopback peers rode the
  // UDS lane" from "silently fell back to loopback TCP" in bench JSON).
  int64_t uds_conns() const { return uds_conns_.load(); }

  // Adaptive routing state snapshot for one traffic class (0 = bulk,
  // 1 = scatter) — observability: exported into bench extras so routing
  // regressions are diagnosable from the JSON record alone.
  void RoutingState(int cls, double* cma_bw, double* tcp_bw,
                    int64_t* decisions, int64_t* crossovers, int* via_tcp,
                    int* calibrated);

  // Lane (striped-connection) observability. LaneState fills
  // [max_lanes, active_lanes, parked, autotune, samples,
  //  best_bw_bytes_per_s, scatter_active_lanes, scatter_parked] —
  // indices 1-5 describe the bulk-stripe tuner (the headline), 6-7 the
  // scatter-class tuner. LaneBytes fills per-lane byte totals served
  // over TCP/UDS (target >= 0: that peer's lanes; -1: summed across
  // peers, lane-index-aligned) and returns the lane count written
  // (bounded by `cap`).
  void LaneState(int64_t out[8]);
  int LaneBytes(int target, int64_t* out, int cap);

  // Planner pins (the cost-model scheduler's runtime knob setters, see
  // ddstore_tpu/sched/planner.py). A pin OVERRIDES the corresponding
  // adaptive tuner's decision without stopping its measurement: samples
  // keep folding into the warm-window cells so a later replan sees
  // fresh numbers. The USER-level env pins (DDSTORE_CMA_BULK/SCATTER,
  // DDSTORE_TCP_LANES) still rank above these — the planner never sets
  // a pin for a knob the user froze. UpdatePeer releases both pins
  // (they were planned against the old peer set; the scheduler replans
  // and re-applies on its peer-change hook).
  int PinRoute(int cls, int mode);   // mode: 0=CMA, 1=TCP, -1=release
  int PinLanes(int cls, int lanes);  // lanes >= 1 pins width, -1 release

  // Warm-window substrate snapshot for the planner: writes up to `cap`
  // rows of 5 doubles [source (0=route, 1=lanes), cls (0=bulk,
  // 1=scatter), knob (route: 0=cma/1=tcp; lanes: lane count),
  // ewma_bytes_per_s, clean_samples] and returns the row count (keep in
  // sync with binding.py SCHED_CELL_COLS).
  int SchedCells(double* out, int cap);

  int Read(int target, const std::string& name, int64_t offset, int64_t nbytes,
           void* dst) override;
  int ReadV(int target, const std::string& name, const ReadOp* ops,
            int64_t n) override;
  // Fan-out across peers AND across each peer's striped connections from
  // one flattened leaf-task list on the persistent pool (no per-call
  // thread spawns — VERDICT round-1 weak #5).
  int ReadVMulti(const std::string& name, const PeerReadV* reqs,
                 int64_t nreqs,
                 const std::string& as_tenant = std::string()) override;

  // Every read leaf carries its own bounded reconnect-and-retry (see
  // ReadVOnRetry); the Store must not add a second layer on top.
  bool RetriesInternally() const override { return true; }
  // Heartbeat probe on a DEDICATED control-plane connection (never a
  // data lane: a lane mutex held across a long striped read would read
  // as death; and ping frames draw nothing from the data path's fault
  // injector — seeded chaos schedules are identical detector on/off).
  // The EXCLUDES set is the machine-readable form of "never hold a
  // data-lane mutex during Ping": acquiring any data-path mutex here
  // fails lint.
  bool Ping(int target, long timeout_ms) override
      DDS_EXCLUDES(Conn::mu, route_mu_, lane_mu_);
  // Content-version probe of a peer's shard, over the SAME dedicated
  // control-plane connection the heartbeat uses (never a data lane, no
  // DATA-PLANE fault-injector draw — the server side draws from the
  // separate ctrl domain, and this client side absorbs those faults
  // with the bounded ControlRetry contract below). -1 on any failure —
  // the mirror refresh then pulls unconditionally, the safe default.
  int64_t ReadVarSeq(int target, const std::string& name) override
      DDS_EXCLUDES(Conn::mu, route_mu_, lane_mu_);
  // Integrity sum fetch (kOpRowSums), over the same dedicated control
  // connection: `count` per-row checksums of the peer's shard starting
  // at owner-local row `row0`, plus the content version they describe.
  // Never a data lane, never a fault-injector draw.
  int ReadRowSums(int target, const std::string& name, int64_t row0,
                  int64_t count, int64_t* seq, uint64_t* sums) override
      DDS_EXCLUDES(Conn::mu, route_mu_, lane_mu_);
  // Snapshot-epoch pin/release, over the same dedicated control
  // connection (never a data lane, no fault-injector draw — seeded
  // chaos schedules are identical with snapshots in play).
  int SnapshotControl(int target, int64_t snap_id, bool pin,
                      const std::string& tenant) override
      DDS_EXCLUDES(Conn::mu, route_mu_, lane_mu_);
  // Serving-gateway session control (kOpAttach/kOpDetach/kOpLease),
  // same dedicated control connection and bounded-retry ladder as
  // SnapshotControl. Never a data lane, never a DATA-plane injector
  // draw (the ctrl arm — including ctrl-conndrop — injects
  // server-side).
  int GatewayControl(int target, int verb, const std::string& tenant,
                     int64_t arg, int64_t arg2, int64_t* token_out)
      override DDS_EXCLUDES(Conn::mu, route_mu_, lane_mu_);
  // ddmetrics histogram pull (kOpMetrics), over the same dedicated
  // control connection: the peer's packed CellRecord snapshot lands in
  // `out`. Never a data lane, never a DATA-plane injector draw (the
  // ctrl arm injects server-side; the bounded control-retry ladder
  // here absorbs it); a suspected peer short-circuits to kErrPeerLost.
  int64_t ReadMetrics(int target, void* out, int64_t cap) override
      DDS_EXCLUDES(Conn::mu, route_mu_, lane_mu_);
  // Per-tenant QoS lane budget: striped reads of `tenant`'s variables
  // engage at most `lanes` lanes (the cost-model scheduler plans these
  // as share-weighted splits of the tuned width; <= 0 clears). No
  // budgets configured = zero cost on the read path.
  int SetTenantLaneBudget(const std::string& tenant, int lanes) override;
  // The leaf retry layer's most recent failed target (failover names
  // the dead member of a multi-peer batch with this).
  int last_failed_peer() const override {
    int64_t out[7];
    retry_.Snapshot(out);
    return static_cast<int>(out[6]);
  }
  // The store's suspect view, consulted between leaf retry attempts so
  // a ladder against a detector-declared-dead peer aborts in
  // O(heartbeat) instead of O(deadline).
  void SetSuspectOracle(std::function<bool(int)> oracle) override {
    std::lock_guard<std::mutex> lock(oracle_mu_);
    suspect_oracle_ = std::move(oracle);
  }
  // Per-store deadline share (see Store::SetRetryDeadline): applied to
  // every leaf's RetryTransientLoop while set.
  void SetRetryDeadline(double seconds) override {
    retry_deadline_ns_.store(
        seconds > 0.0 ? static_cast<int64_t>(seconds * 1e9) : 0,
        std::memory_order_relaxed);
  }
  // Leaf-level retry/reconnect counters ([transient, retries, reconnects,
  // backoff_ms, giveups, fatal, last_peer] — see RetryStats).
  void RetryCounters(int64_t out[7]) const { retry_.Snapshot(out); }
  // Requester-side gather counters: frames admitted into the pipeline
  // vs sendmsg bursts that carried them. frames/sends > 1 means the
  // half-window writev gather is coalescing multi-frame request bursts
  // into single syscalls (the per-frame sentry tax the uring backend
  // attacks where io_uring is unavailable).
  void ReqSendCounters(int64_t out[2]) const {
    out[0] = req_frames_.load(std::memory_order_relaxed);
    out[1] = req_sends_.load(std::memory_order_relaxed);
  }
  // Dissemination barrier: ceil(log2 P) one-way notify rounds per fence
  // (round k: notify rank+2^k, wait for rank-2^k) instead of the round-1
  // flat O(P) notify loop / O(P^2) total messages. FAILURE-AWARE: the
  // per-round wait polls the store's suspect oracle, so a member the
  // detector declared dead aborts the whole barrier in O(heartbeat)
  // with kErrPeerLost naming the suspect (retry_.last_peer), instead
  // of sleeping out DDSTORE_BARRIER_TIMEOUT_S per round. A timeout
  // with NO suspect stays kErrTransport (the peer may just be slow).
  int Barrier(int64_t tag) override;
  int rank() const override { return rank_; }
  int world() const override { return world_; }
  WorkerPool* worker_pool() override { return &pool_; }

 protected:
  // Protected, not private: UringTransport (uring_transport.h) reuses the
  // whole lane/peer machinery — pools, autotuner, retry ladder, CMA,
  // suspect oracle — and overrides ONLY the per-lane wire loop (ReadVOn).
  // One TCP connection to a peer — a "lane". A peer owns a small pool of
  // these (DDSTORE_TCP_LANES; legacy alias DDSTORE_CONNS_PER_PEER): a
  // single stream can't saturate loopback/DCN, and each lane gets its
  // own serving thread on the target, so large reads stripe across
  // streams and server cores. How many of the pooled lanes a striped
  // read actually engages is governed by the lane autotuner (LaneTuner
  // below) unless DDSTORE_TCP_LANES_AUTOTUNE=0 pins it at the pool size.
  struct Conn {
    int fd DDS_GUARDED_BY(Conn::mu) = -1;
    int idx = 0;    // position in the pool; picks the NIC pairing
    // Same-host fast lane: whether this slot already probed the peer's
    // Unix-domain listener (probe once; a failed probe falls back to TCP
    // permanently until UpdatePeer swaps the endpoint).
    bool uds_tried DDS_GUARDED_BY(Conn::mu) = false;
    std::mutex mu;  // serializes use of this connection (a data-lane
    //                 mutex: legitimately held across blocking wire
    //                 I/O, so deliberately NOT DDS_NO_BLOCKING — the
    //                 control plane instead EXCLUDES it, see Ping)
    // Response payload bytes this lane has carried (per-peer per-lane
    // observability: lane utilization/balance is diagnosable from the
    // BENCH json alone). Atomic: LaneBytes snapshots without taking mu.
    std::atomic<int64_t> bytes{0};
  };
  struct Peer {
    // Endpoint table: written under ALL of the peer's conn mutexes
    // (SetPeers/UpdatePeer), read by EnsureConnected under its one —
    // any single Conn::mu is a read guard, the full set the write
    // guard. The analyzer models this at class granularity.
    std::vector<std::string> hosts
        DDS_GUARDED_BY(Conn::mu);  // one entry per advertised NIC
    int port DDS_GUARDED_BY(Conn::mu) = -1;
    std::vector<std::unique_ptr<Conn>> conns;
    // CMA (same-host process_vm_readv) state: 0 = unprobed, 1 = usable,
    // -1 = TCP only, 2 = probe in flight. Probed lazily on first read
    // to the peer, OUTSIDE this mutex: the prober claims the probe by
    // flipping 0 -> 2 under cma_mu, runs the dial+info exchange with
    // no lock held (the wire leg serializes on its lane's own
    // Conn::mu), and publishes the verdict under cma_mu — concurrent
    // classification peeks see state 2 and ride TCP instead of
    // blocking a DDS_NO_BLOCKING mutex for a network round trip.
    // cma_gen invalidates an in-flight probe crossed by UpdatePeer
    // (the opened mapping would belong to the dead process).
    std::mutex cma_mu DDS_NO_BLOCKING;
    int cma_state DDS_GUARDED_BY(cma_mu) = 0;
    uint64_t cma_gen DDS_GUARDED_BY(cma_mu) = 0;
    std::unique_ptr<CmaPeer> cma DDS_GUARDED_BY(cma_mu);
    // CmaPeers retired by UpdatePeer (elastic recovery). Raw pointers
    // returned by EnsureCmaPeer may still be mid-TryReadV on pool
    // threads with no lock held, so a retired peer is parked here —
    // alive but inert (reads against the dead pid fail fast) — and
    // freed at transport teardown. Bounded: one entry per recovery.
    std::vector<std::unique_ptr<CmaPeer>> cma_retired
        DDS_GUARDED_BY(cma_mu);
  };

  // Probe/return the peer's CMA mapping (nullptr = use TCP).
  CmaPeer* EnsureCmaPeer(Peer& p, int target);
  // EnsureCmaPeer's dial+info exchange on lane 0, run with the lane's
  // own (data) mutex held and NO cma_mu — the probe must never block a
  // DDS_NO_BLOCKING mutex for a network round trip.
  bool ProbeCmaInfoLocked(Peer& p, Conn& c, std::string* payload)
      DDS_REQUIRES(Conn::mu);

  int EnsureConnected(Peer& p, Conn& c) DDS_REQUIRES(Conn::mu);
  // The pipelined request/response loop over one connection. Virtual:
  // the io_uring backend substitutes a batched-SQE submission for the
  // sendmsg/recvmsg loop while keeping the byte stream (and therefore
  // the server-side fault-draw schedule) identical.
  virtual int ReadVOn(Peer& p, Conn& c, const std::string& name,
                      const ReadOp* ops, int64_t n);
  // Route label the wire (non-CMA) leg of ReadVMulti attributes to the
  // histogram plane. The uring backend overrides this with kRouteUring
  // so (class, route, peer, tenant) keys distinguish the backends.
  virtual int WireRouteLabel() const;
  // ReadVOn + transient classification + bounded exponential-backoff
  // retry. Transport-level failures (reset, truncated frame, read
  // timeout) are TRANSIENT; server-reported data errors are FATAL; an
  // exhausted budget returns kErrPeerLost. Retries ROTATE across the
  // `nlanes` lanes starting at `lane0`: a transient fault on one lane
  // re-runs only that stripe, on the next (surviving, likely still
  // connected) lane — the failed lane was closed by ReadVOn's fail() and
  // redials lazily on its next use. With nlanes == 1 every attempt lands
  // back on the same lane: the exact pre-lane retry contract.
  // `lane_off` shifts the whole window to pool index (lane_off + i) %
  // pool — the tenant QoS rotation; 0 (all unbudgeted traffic) is the
  // pool prefix, the exact pre-tenancy indexing.
  int ReadVOnRetry(Peer& p, int lane0, int nlanes, const std::string& name,
                   const ReadOp* ops, int64_t n, int target,
                   int lane_off = 0);
  void AcceptLoop(int lfd, bool is_tcp);
  void HandleConnection(int fd);
  // Send one one-way barrier notify for (tag, round) to `target`.
  bool SendBarrierNotify(int target, int64_t tag, int round);

  const int rank_;
  const int world_;
  std::atomic<bool> stopping_{false};
  Store* store_ = nullptr;

  int listen_fd_ = -1;
  int server_port_ = -1;
  std::thread accept_thread_;  // joined first in ~TcpTransport (freezes
  //                              conn_fds_/conn_threads_ growth)
  // Same-host fast lane: a second listener on an abstract-namespace
  // Unix-domain socket named after the TCP port (which is unique per
  // network namespace, so the name cannot collide between instances).
  // Loopback-addressed peers dial it instead of TCP — same framing
  // protocol, same serving loop, but the stream skips the (emulated)
  // TCP/IP stack entirely: on the sandboxed 2-core bench kernel that is
  // a measured ~1.6x per-byte saving, which is exactly the scatter
  // class's bottleneck (it is CPU-bound on copies, not latency-bound).
  int uds_listen_fd_ = -1;
  std::thread uds_accept_thread_;
  std::atomic<int64_t> uds_conns_{0};  // UDS dials that succeeded
  // Requester-side gather counters (see ReqSendCounters).
  std::atomic<int64_t> req_frames_{0};
  std::atomic<int64_t> req_sends_{0};
  std::mutex conns_mu_;
  std::vector<std::thread> conn_threads_ DDS_GUARDED_BY(conns_mu_);
  std::vector<int> conn_fds_ DDS_GUARDED_BY(conns_mu_);

  std::vector<std::unique_ptr<Peer>> peers_;
  std::vector<std::string> local_addrs_;

  // Heartbeat control plane: one dedicated connection per peer, dialed
  // lazily with a bounded non-blocking connect. Never shared with data
  // lanes (see Ping above). UpdatePeer closes the slot so a replacement
  // process gets a fresh dial.
  // hosts/port are the ping thread's OWN endpoint copy, updated under
  // `mu` by SetPeers/UpdatePeer — the data path's Peer fields are
  // guarded by the lane mutexes, which the ping must never touch.
  // EVERY advertised NIC address is kept and the dial rotates across
  // them on failure: a multi-homed peer whose first NIC is down must
  // not read as dead while its data lanes (round-robin over the same
  // list) still work.
  struct PingConn {
    int fd DDS_GUARDED_BY(PingConn::mu) = -1;
    std::vector<std::string> hosts DDS_GUARDED_BY(PingConn::mu);
    size_t next_host DDS_GUARDED_BY(PingConn::mu) = 0;
    int port DDS_GUARDED_BY(PingConn::mu) = -1;
    std::mutex mu;  // control-plane round trips are bounded by their
    //                 own timeout; blocking under it is the design
  };
  std::vector<std::unique_ptr<PingConn>> ping_conns_;
  // Shared dial/ensure half of Ping/ReadVarSeq: returns the connected
  // control fd (dialing within timeout_ms if needed, rotating across
  // the peer's advertised addresses on failure) or -1. Caller holds
  // pc.mu.
  int EnsureControlConn(PingConn& pc, long timeout_ms)
      DDS_REQUIRES(PingConn::mu);
  // One control-plane request/response over the peer's dedicated
  // connection (the shared body of Ping/ReadVarSeq/SnapshotControl/
  // ReadRowSums): sends `op` (+ name for ops that carry one; `tag`
  // rides the frame's tag field — the snapshot id; `offset`/`nbytes`
  // ride their frame fields — the row-sum range), receives `resp` and,
  // when `payload` is non-null and the response announces up to
  // `payload_cap` body bytes, the payload too. False on a TRANSPORT
  // failure (connection closed for a fresh redial); a well-formed
  // in-band error keeps the connection and returns true — callers
  // check resp->status. Caller holds pc.mu.
  bool ControlRoundTrip(PingConn& pc, uint32_t op,
                        const std::string& name, long timeout_ms,
                        void* resp, int64_t tag = 0, int64_t offset = 0,
                        int64_t nbytes = 0, std::string* payload = nullptr,
                        int64_t payload_cap = 0)
      DDS_REQUIRES(PingConn::mu);
  // Snapshot the store-installed suspect oracle (one oracle_mu_
  // acquisition; the returned callable is lock-free). Null when no
  // store attached / single rank. Consumed by the barrier wait and the
  // control-op retry loops: everything on the PingConn EXCEPT the
  // heartbeat Ping itself carries the RetryTransientLoop contract
  // scaled down to control ops — a detector-declared-dead peer
  // short-circuits BEFORE any dial (a fence's var-seq probes and a
  // snapshot acquire's pin placement must not serially burn per-peer
  // control timeouts against a corpse), and a transport-failed round
  // trip redials and retries up to control_retry_max_ times with short
  // bounded backoff (ControlBackoffMs).
  std::function<bool(int)> SuspectSnapshot();

  // Store-installed suspect oracle for the leaf retry layer (null =
  // never suspected). ReadVOnRetry snapshots it ONCE per leaf under
  // oracle_mu_ (set-once at store construction; the lock only guards
  // against an in-flight leaf racing SetSuspectOracle) — the
  // per-attempt suspect checks are then lock-free.
  std::mutex oracle_mu_ DDS_NO_BLOCKING;
  std::function<bool(int)> suspect_oracle_ DDS_GUARDED_BY(oracle_mu_);

  // Leaf read tasks (one per peer-connection stripe) run here; threads are
  // created lazily and persist for the transport's lifetime.
  WorkerPool pool_;

  // CMA fast path (DDSTORE_CMA=0 disables): our published mappings and
  // the fast-path op counter.
  std::unique_ptr<CmaRegistry> cma_reg_;
  std::atomic<int64_t> cma_ops_{0};

  // Adaptive bulk routing. process_vm_readv normally beats sockets for
  // bulk same-host reads (one kernel copy, no framing), but sandboxed
  // kernels can emulate it far below socket speed; rather than trust
  // either assumption, measure both paths and route bulk (>= 8 MiB)
  // reads down the faster one. Small reads always prefer CMA (it wins on
  // latency wherever process_vm_readv works at all). One estimate per
  // transport, not per peer: the decision only matters on same-host
  // peers, which all share one kernel. Guarded by route_mu_.
  std::mutex route_mu_ DDS_NO_BLOCKING;
  // One adaptive preference per traffic class: "bulk" (>= kBulkBytes in
  // one request — bandwidth-dominated) and "scatter" (many small ops,
  // modest bytes — per-op-overhead-dominated; a DistributedSampler
  // permutation batch). The classes bottleneck differently (one kernel
  // copy vs per-iovec walk), so one class's winner says nothing about
  // the other's.
  struct RouteClass {
    const char* name;     // log/observability label
    const char* pin_env;  // env var pinning the choice
    // Flip threshold for STEADY-STATE crossovers (the faster path must
    // beat the current one by this factor). The scatter class runs a
    // tighter band than bulk: its per-op-overhead bottleneck makes the
    // paths land closer together, and a 1.25x band left it parked on a
    // measurably slower path (auto_batch ~18% under the best forced
    // path in BENCH r6).
    double hysteresis = 1.25;
    int cls = 0;  // 0 = bulk, 1 = scatter (pin/snapshot index)
    // Per-path warm-window cells (the shared measurement substrate,
    // measure.h): EWMA bytes/s + clean-sample count + warm-up state.
    // The router keeps collecting until both reach kWarmMinSamples.
    WarmStat cma;
    WarmStat tcp;
    int64_t decisions = 0;
    int64_t crossovers = 0;  // preference flips (observability: a
    //                          flapping policy shows up as a count,
    //                          diagnosable from BENCH json alone)
    int cold_skips = 0;  // connect-tainted seeds discarded (bounded,
    //                      shared across both cells — measure.h rule 1)
    // Probes run as consecutive PAIRS on the non-preferred path: the
    // first window re-warms it (idle TCP connections restart from
    // slow-start, pool threads sleep) and its sample is discarded; only
    // the second, warm window is folded into the EWMA. Set when the
    // warm-up window is dispatched; consumed by FoldWarmSample (rule 3).
    bool discard_probe = false;
    bool via_tcp = false;
    // One-shot warm calibration: once BOTH paths hold clean warm
    // estimates (collection complete), the class is parked on the
    // measured-faster path outright — hysteresis governs only LATER
    // flips. Without it a cold start whose slower path was the default
    // sat inside the hysteresis band forever.
    bool calibrated = false;
  };
  RouteClass bulk_route_ DDS_GUARDED_BY(route_mu_){
      "bulk", "DDSTORE_CMA_BULK", 1.25, 0};
  RouteClass scatter_route_ DDS_GUARDED_BY(route_mu_){
      "scattered", "DDSTORE_CMA_SCATTER", 1.10, 1};
  unsigned hw_cores_ = 1;  // CMA striping is CPU-bound; never deal more
  //                          part-lists than cores (a 1-core box pays
  //                          pure dispatch overhead for each extra part)

  // Adaptive lane autotuning, in the style of the router above: more
  // lanes only pay while the extra streams land on idle cores/serving
  // threads — past that knee each lane just slices the same aggregate
  // thinner and adds dispatch/syscall overhead. The tuner measures
  // striped-read throughput at geometrically increasing lane counts
  // (1, 2, 4, ... pool size), discarding each level's first (warm-up)
  // window and any dial-tainted window exactly like RecordRouteSample,
  // and PARKS on the best-measured level the first time a level fails
  // to beat its predecessor by kLaneGrowth — per-lane throughput has
  // stopped scaling. Parking is one-shot (an UpdatePeer recovery resets
  // it with the route estimates: the replacement peer re-measures).
  // One tuner PER TRAFFIC CLASS, like the router: bulk stripes are
  // byte-bound (lanes add parallel streams/serving cores) while
  // scatter deals whole small ops (lanes shrink every frame and
  // multiply per-frame cost) — measured on the 2-core bench kernel the
  // classes' optima differ by >3x, so one shared verdict would park
  // one class on the other's width.
  // DDSTORE_TCP_LANES_AUTOTUNE=0 pins striping at the full pool size.
  struct LaneTuner {
    const char* name = "bulk";  // log/observability label
    int cls = 0;                // 0 = bulk, 1 = scatter (pin index)
    bool autotune = true;
    bool parked = false;
    int active = 1;            // lanes striped reads use once parked
    int level = 0;             // index into levels while measuring
    std::vector<int> levels;   // 1, 2, 4, ..., max_lanes
    // Per-level warm-window cells (shared substrate, measure.h): EWMA
    // bytes/s, clean samples, warm-up state per lane count.
    std::vector<WarmStat> stats;
    int cold_skips = 0;        // dial-tainted windows discarded (bounded
    //                            like the router's: a peer that redials
    //                            every window must not pin the ramp —
    //                            measure.h rule 1, per-tuner budget)
    int64_t samples = 0;       // clean samples folded (observability)
  };
  std::mutex lane_mu_ DDS_NO_BLOCKING;
  LaneTuner bulk_lanes_ DDS_GUARDED_BY(lane_mu_);
  LaneTuner scatter_lanes_ DDS_GUARDED_BY(lane_mu_);
  // Per-tenant QoS lane budgets (SetTenantLaneBudget). The atomic flag
  // keeps the unconfigured read path at a single relaxed load. `rotor`
  // rotates the tenant's lane window one pool slot per batch so a
  // narrow budget time-shares the pool instead of camping on lane 0
  // (which every other tenant's full-width stripes include).
  struct TenantLanes {
    int lanes = 0;
    uint64_t rotor = 0;
  };
  std::map<std::string, TenantLanes> tenant_lane_budget_
      DDS_GUARDED_BY(lane_mu_);
  std::atomic<bool> tenant_budgets_set_{false};
  // Budget lookup for one request's READING tenant — `as_tenant`, or
  // derived from the variable name when "" (0 = unbudgeted); on a hit,
  // also ticks and returns the tenant's window rotation.
  int TenantLaneBudget(const std::string& name, uint64_t* rot,
                       const std::string& as_tenant);
  // Lanes the NEXT striped read of the class should engage (the parked
  // count, or the level currently being measured).
  int StripeLanes(LaneTuner& t);
  // Fold one all-TCP batch's (bytes, seconds) at `lanes` into the
  // class's tuner. `cold` marks a window that included a dial
  // (discarded while the level is unseeded, same rule as the router).
  void RecordLaneSample(LaneTuner& t, int lanes, int64_t bytes,
                        double secs, bool cold);

  // Decide the path for one request of the class (advances the probe
  // counter).
  bool RouteViaTcp(RouteClass& rc);
  bool RouteBulkViaTcp() { return RouteViaTcp(bulk_route_); }
  bool RouteScatterViaTcp() { return RouteViaTcp(scatter_route_); }
  // Fold a measured (bytes, seconds) sample into one path's EWMA and
  // re-evaluate the preference, logging any crossover. ``cold`` marks a
  // window that included connection setup: such a sample measures the
  // dial, not the transport, and must not SEED a path's estimate (a
  // routing verdict parked on it would take many probe windows to
  // overturn).
  void RecordRouteSample(RouteClass& rc, bool via_tcp, int64_t bytes,
                         double secs, bool cold = false);

  // Planner pins, one per traffic class (see PinRoute/PinLanes above).
  // route: -1 = adaptive, 0 = CMA, 1 = TCP. lanes: -1 = tuner, >= 1 =
  // pinned stripe width (clamped to the pool size at use).
  std::atomic<int> route_pin_[2]{-1, -1};
  std::atomic<int> lane_pin_[2]{-1, -1};

  // Connections dialed so far (EnsureConnected establishing a fresh
  // socket). The TCP read leg snapshots it around its timed window to
  // detect connect-tainted routing samples.
  std::atomic<int64_t> dials_{0};

  // Leaf-retry accounting (ReadVOnRetry).
  RetryStats retry_;
  // Deadline override for leaf retries (nanos; 0 = none).
  std::atomic<int64_t> retry_deadline_ns_{0};

  // Control-plane round-trip knobs (DDSTORE_CONTROL_TIMEOUT_MS /
  // DDSTORE_CONTROL_RETRY_MAX), resolved once at construction —
  // control ops run under PingConn::mu and must not getenv per call.
  long control_timeout_ms_ = 1000;
  int control_retry_max_ = 2;

  // Barrier bookkeeping. Caller tags come from independent subsystems
  // (epoch fences, the Python-layer barrier) and are NOT globally ordered,
  // so matching uses barrier_seq_ — the transport's own strictly-
  // increasing collective sequence number, identical on every rank
  // because barriers are collective and called in one program order.
  // Arrivals are keyed by (seq, dissemination round); retired_seq_ is the
  // high-water mark of completed/timed-out seqs, and late notifies at or
  // below it are dropped so a straggler can't repopulate an erased entry
  // and leak it (seqs are never reused).
  std::mutex barrier_mu_ DDS_NO_BLOCKING;
  std::condition_variable barrier_cv_;
  std::map<std::pair<int64_t, int>, int> barrier_arrived_
      DDS_GUARDED_BY(barrier_mu_);
  int64_t barrier_seq_ DDS_GUARDED_BY(barrier_mu_) = 0;
  int64_t retired_seq_ DDS_GUARDED_BY(barrier_mu_) = 0;
};

}  // namespace dds

#endif  // DDSTORE_TPU_TCP_TRANSPORT_H_
