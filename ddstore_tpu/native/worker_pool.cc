#include "worker_pool.h"

#include "trace.h"

namespace dds {

WorkerPool::WorkerPool(int max_threads)
    : max_threads_(max_threads < 1 ? 1 : max_threads) {}

WorkerPool::~WorkerPool() {
  {
    std::lock_guard<std::mutex> lock(mu_);
    stopping_ = true;
  }
  cv_.notify_all();
  for (auto& t : threads_)
    if (t.joinable()) t.join();
}

void WorkerPool::Submit(std::function<void()> fn) {
  {
    std::lock_guard<std::mutex> lock(mu_);
    queue_.push_back(std::move(fn));
    // Grow on queue depth, not zero-idle: a woken worker only decrements
    // idle_ after re-acquiring the mutex, so a burst of submits would see
    // a stale idle count and under-provision a network-bound fan-out.
    if (static_cast<int64_t>(queue_.size()) > idle_ &&
        static_cast<int>(threads_.size()) < max_threads_)
      threads_.emplace_back([this] { WorkerLoop(); });
  }
  cv_.notify_one();
}

void WorkerPool::SubmitMany(std::vector<std::function<void()>> fns) {
  if (fns.empty()) return;
  {
    std::lock_guard<std::mutex> lock(mu_);
    for (auto& fn : fns) queue_.push_back(std::move(fn));
    // Same growth rule as Submit, applied for the whole burst under one
    // lock: a lane-striped fan-out (peers × lanes leaves) provisions
    // its width in one pass instead of one lock+notify round-trip per
    // leaf.
    int64_t avail = idle_;  // idle workers + threads spawned this burst
    while (static_cast<int64_t>(queue_.size()) > avail &&
           static_cast<int>(threads_.size()) < max_threads_) {
      threads_.emplace_back([this] { WorkerLoop(); });
      ++avail;
    }
  }
  cv_.notify_all();
}

void WorkerPool::WorkerLoop() {
  std::unique_lock<std::mutex> lock(mu_);
  for (;;) {
    while (queue_.empty() && !stopping_) {
      ++idle_;
      cv_.wait(lock);
      --idle_;
    }
    if (queue_.empty() && stopping_) return;
    auto fn = std::move(queue_.front());
    queue_.pop_front();
    lock.unlock();
    fn();
    lock.lock();
  }
}

TaskGroup::TaskGroup(WorkerPool* pool)
    : pool_(pool), state_(std::make_shared<State>()) {}

void TaskGroup::Launch(std::function<void()> fn) {
  {
    std::lock_guard<std::mutex> lock(state_->mu);
    ++state_->pending;
  }
  // Trace-span propagation: a leaf runs under the SUBMITTER's span so
  // lane dials, retries and serve legs attribute to the op that caused
  // them. Identity (one relaxed load) when tracing is off.
  fn = trace::TraceTask(std::move(fn));
  pool_->Submit([st = state_, fn = std::move(fn)]() {
    fn();
    // notify under the lock: the waiter can destroy the TaskGroup the
    // moment Wait() returns, but `st` keeps the State alive here.
    std::lock_guard<std::mutex> lock(st->mu);
    if (--st->pending == 0) st->cv.notify_all();
  });
}

void TaskGroup::LaunchMany(std::vector<std::function<void()>> fns) {
  if (fns.empty()) return;
  {
    std::lock_guard<std::mutex> lock(state_->mu);
    state_->pending += static_cast<int64_t>(fns.size());
  }
  std::vector<std::function<void()>> wrapped;
  wrapped.reserve(fns.size());
  for (auto& fn : fns)
    wrapped.emplace_back([st = state_,
                          fn = trace::TraceTask(std::move(fn))]() {
      fn();
      std::lock_guard<std::mutex> lock(st->mu);
      if (--st->pending == 0) st->cv.notify_all();
    });
  pool_->SubmitMany(std::move(wrapped));
}

void TaskGroup::Wait() {
  std::unique_lock<std::mutex> lock(state_->mu);
  state_->cv.wait(lock, [this] { return state_->pending == 0; });
}

}  // namespace dds
