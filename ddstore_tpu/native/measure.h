// Warm-window measurement substrate — THE single implementation of the
// sample-hygiene rules every adaptive tuner in this codebase follows.
//
// Three independent tuners grew the same hygiene by copy-paste (the
// CMA/TCP router's RecordRouteSample, the lane autotuner's
// RecordLaneSample, and the Python-side planner's window accounting),
// and each could drift from the others silently. The rules live here
// once; the router and lane tuner hold WarmStat cells and call
// FoldWarmSample; the Python mirror (ddstore_tpu/sched/measure.py)
// implements the identical contract for host-side sample sources and is
// parity-tested against this file's semantics (tests/test_sched.py).
//
// The contract, in fold order:
//   1. DIAL-TAINT DISCARD: a window that included a connection dial
//      timed the handshake, not the transport. While the cell has no
//      clean sample yet it is discarded — bounded by a caller-scoped
//      skip budget (kWarmMaxColdSkips): a peer set that redials every
//      window must not pin collection forever; past the budget the
//      tainted number beats having none.
//   2. FIRST-WINDOW (WARM-UP) DISCARD: each cell's first surviving
//      window timed the path WAKING (TCP slow-start restart, sleeping
//      pool threads), not running; it is consumed to warm the cell and
//      its value dropped.
//   3. PAIRED-PROBE DISCARD: steady-state probes of a non-preferred
//      path come as consecutive pairs; the first only re-warms the idle
//      path. The caller arms a discard flag for it; the fold consumes
//      the flag and drops that one sample.
//   4. EWMA FOLD: surviving samples fold at kWarmEwmaAlpha (first
//      sample seeds the estimate outright).

#ifndef DDSTORE_TPU_MEASURE_H_
#define DDSTORE_TPU_MEASURE_H_

namespace dds {

// Clean samples a cell needs before a verdict may be read off it (one
// sample is a wake-up measurement, not a comparison). Shared by the
// router's collection phase, the lane tuner's per-level ramp, and the
// planner's confidence gate.
constexpr int kWarmMinSamples = 2;
// Dial-tainted discards allowed per tuner before tainted numbers are
// accepted anyway (see rule 1).
constexpr int kWarmMaxColdSkips = 4;
// EWMA smoothing: new estimate = alpha * old + (1 - alpha) * sample.
constexpr double kWarmEwmaAlpha = 0.5;

// One warm-window estimator cell: a (traffic class, knob value) pair's
// throughput estimate plus its hygiene state.
struct WarmStat {
  double ewma = 0.0;  // bytes/s estimate; 0 = no clean sample yet
  int n = 0;          // clean samples folded
  bool warmed = false;  // warm-up window consumed (rule 2)

  void Reset() {
    ewma = 0.0;
    n = 0;
    warmed = false;
  }
};

// Fold outcome, for observability/tests (callers mostly ignore it).
enum class WarmFold : int {
  kFolded = 0,      // sample entered the EWMA
  kDropCold = 1,    // rule 1: dial-tainted, skip budget charged
  kDropWarmup = 2,  // rule 2: consumed as the cell's warm-up
  kDropProbe = 3,   // rule 3: consumed the armed probe-pair discard
};

// Fold one measured window into `s` under the hygiene contract above.
// `cold` marks a window that included a dial; `cold_skips` is the
// CALLER-scoped discard budget rule 1 charges (shared across a tuner's
// cells — per-tuner, not per-cell, so a flapping peer can't spend the
// budget once per level); nullptr opts out of rule 1. `discard_flag`,
// when non-null and set, is rule 3's armed one-shot discard; nullptr
// (or unset) opts out.
inline WarmFold FoldWarmSample(WarmStat& s, double value, bool cold,
                               int* cold_skips, bool* discard_flag) {
  if (cold && s.n == 0 && cold_skips &&
      *cold_skips < kWarmMaxColdSkips) {
    ++*cold_skips;
    return WarmFold::kDropCold;
  }
  if (!s.warmed) {
    s.warmed = true;
    return WarmFold::kDropWarmup;
  }
  if (discard_flag && *discard_flag) {
    *discard_flag = false;
    return WarmFold::kDropProbe;
  }
  s.ewma = s.ewma == 0.0
               ? value
               : kWarmEwmaAlpha * s.ewma + (1.0 - kWarmEwmaAlpha) * value;
  ++s.n;
  return WarmFold::kFolded;
}

}  // namespace dds

#endif  // DDSTORE_TPU_MEASURE_H_
