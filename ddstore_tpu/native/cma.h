// Cross-memory-attach (CMA) fast path: same-host one-sided reads via
// process_vm_readv.
//
// TPU-VM hosts often run several store processes (one per chip/worker).
// Reads between them do not need sockets at all: Linux lets a same-uid
// process read another's address space directly with process_vm_readv —
// a TRUE one-sided read (single kernel copy, no serving thread, no wire).
// This is the closest TPU-host analogue of the reference's libfabric
// FI_MR_BASIC design, which likewise exchanges raw base virtual addresses
// and reads `remote_address[src] + offset`
// (/root/reference/src/common.cxx:299-306,340) — except the reference
// needs RDMA hardware for it, and this needs only the kernel.
//
// Safety: the owner publishes {base, len} per variable in a small shared-
// memory control segment guarded by a per-slot SEQLOCK. Rebind (RAM->mmap
// spill), Update, and FreeVar bump the generation around the mutation, so
// a concurrent CMA reader either sees a stable generation (data valid) or
// retries/falls back to TCP, where the store's shared_mutex serializes it
// against the mutation. A reader can never return bytes from a freed or
// half-updated backing with an even, unchanged generation.
//
// Discovery is authoritative-by-probe: peers exchange
// {pid, boot_id + pid-namespace token, segment name} over the TCP control
// channel; a token match merely permits an attempt — the first
// process_vm_readv failing with EPERM/ESRCH/EFAULT demotes the peer to
// TCP permanently. DDSTORE_CMA=0 disables the whole path.

#ifndef DDSTORE_TPU_CMA_H_
#define DDSTORE_TPU_CMA_H_

#include <atomic>
#include <cstdint>
#include <mutex>
#include <string>

#include "store.h"

namespace dds {

constexpr uint64_t kCmaMagic = 0xDD5C3A10C0DE0003ull;
constexpr int kCmaSlots = 256;
// Unpublish leaves a tombstone, not an empty: readers must probe PAST a
// freed slot or a hash-colliding variable behind it silently loses its
// fast path forever.
constexpr uint64_t kCmaTombstone = ~0ull;

struct CmaSlot {
  // Seqlock: even = stable, odd = mutation in progress. hash==0 = empty.
  std::atomic<uint64_t> gen;
  std::atomic<uint64_t> hash;
  std::atomic<uint64_t> base;
  std::atomic<uint64_t> len;
};

struct CmaSegment {
  uint64_t magic;
  int64_t pid;
  // Creator's /proc/<pid>/stat starttime (clock ticks since boot). pid
  // alone is recyclable: a crashed peer's segment can outlive it in
  // /dev/shm, and the OS may hand the pid to an unrelated same-uid
  // process whose address space process_vm_readv would then happily (and
  // wrongly) read. pid + starttime is unique for the boot.
  uint64_t start_time;
  CmaSlot slots[kCmaSlots];
};

// FNV-1a; 0 is reserved for "empty slot".
uint64_t CmaHash(const std::string& name);

// Host identity token: boot_id + pid-namespace inode. Equal tokens mean a
// CMA attempt is worth making (different pid namespaces on one host share
// a boot_id but cannot process_vm_readv each other — the probe settles it).
std::string CmaHostToken();

// starttime (field 22 of /proc/<pid>/stat) for `pid`; 0 if unreadable.
// Parsing skips past the last ')' — comm may contain spaces and parens.
uint64_t ProcStartTime(int64_t pid);

// Publisher side: owns a /dev/shm segment advertising this process's
// variable mappings.
class CmaRegistry {
 public:
  CmaRegistry();   // creates the segment; ok() false on failure
  ~CmaRegistry();  // unlinks it

  bool ok() const { return seg_ != nullptr; }
  const std::string& shm_name() const { return shm_name_; }

  // Relax Yama ptrace protection so same-uid peers can process_vm_readv
  // this process. Deferred until a peer actually asks for our CMA info
  // (the kOpCmaInfo handler) instead of done unconditionally at startup:
  // a store whose peers are all cross-host never needs the relaxation.
  void EnableReads();

  // Seqlock-publish {base, len} for `name` (new slot or in-place rebind).
  void Publish(const std::string& name, const void* base, int64_t len);
  // Seqlock-clear the slot; concurrent readers bounce to TCP.
  void Unpublish(const std::string& name);

 private:
  CmaSlot* FindSlot(uint64_t h, bool take_empty);

  std::mutex mu_;  // one writer process, many writer threads
  CmaSegment* seg_ = nullptr;
  std::string shm_name_;
  int fd_ = -1;
  std::once_flag reads_enabled_;
};

// Reader side: a peer's mapped segment + pid.
class CmaPeer {
 public:
  ~CmaPeer();

  // Maps `shm_name` and validates magic, pid AND the creator's starttime
  // against both the segment header and the live /proc entry, so a
  // recycled pid (crashed peer, stale segment) is rejected instead of
  // read. nullptr on any failure.
  static CmaPeer* Open(const std::string& shm_name, int64_t pid,
                       uint64_t start_time);

  // Try to serve `ops` via process_vm_readv. Returns:
  //   kOk          — all bytes read under a stable generation
  //   kCmaFallback — mapping absent/changing/denied; caller uses TCP
  // Never returns partial data as success.
  static constexpr int kCmaFallback = 1;
  int TryReadV(const std::string& name, const ReadOp* ops, int64_t n);

  // After EPERM/ESRCH the kernel will never allow this pair; the caller
  // should drop the peer to TCP permanently.
  bool denied() const { return denied_.load(std::memory_order_relaxed); }

 private:
  CmaPeer(CmaSegment* seg, size_t map_len, int64_t pid, uint64_t start)
      : seg_(seg), map_len_(map_len), pid_(pid), start_time_(start) {}

  // Re-check that pid_ still belongs to the process that created the
  // segment (periodically and on any read failure): if the peer died and
  // the pid was recycled mid-session, reads must demote to TCP, not
  // return another process's memory.
  bool PeerStillAlive();

  CmaSegment* seg_;
  size_t map_len_;
  int64_t pid_;
  uint64_t start_time_;
  std::atomic<int64_t> reads_since_check_{0};
  std::atomic<bool> denied_{false};
};

}  // namespace dds

#endif  // DDSTORE_TPU_CMA_H_
