// Cross-memory-attach (CMA) fast path: same-host one-sided reads via
// shared-memory mapping (preferred) or process_vm_readv (fallback).
//
// TPU-VM hosts often run several store processes (one per chip/worker).
// Reads between them do not need sockets at all: Linux lets a same-uid
// process read another's address space directly with process_vm_readv —
// a TRUE one-sided read (single kernel copy, no serving thread, no wire).
// This is the closest TPU-host analogue of the reference's libfabric
// FI_MR_BASIC design, which likewise exchanges raw base virtual addresses
// and reads `remote_address[src] + offset`
// (/root/reference/src/common.cxx:299-306,340) — except the reference
// needs RDMA hardware for it, and this needs only the kernel.
//
// process_vm_readv's cost is per SEGMENT, and on sandboxed kernels
// (gVisor emulates the syscall in the sentry) that cost is brutal for the
// training hot path's scatter shape — hundreds of small rows per peer
// (measured on a gVisor box: 8.9 GB/s for one 32 MiB segment vs 2.3 GB/s
// for the same bytes as 1024 x 512 B segments; plain memcpy of the same
// scatter from a shared mapping runs >20 GB/s). So owned shards are
// allocated in per-variable /dev/shm files (Transport::AllocShard →
// CmaRegistry::AllocData) and the slot advertises the file id instead of
// a raw address: a reader mmaps the peer's data file ONCE and then
// gathers with plain memcpy under the same seqlock — zero per-segment
// kernel cost, which is what closes the bulk-vs-scatter bandwidth gap.
// Borrowed shards (registered with copy=False, or rebound to an mmap
// after a disk spill) cannot move into shm, so they keep the
// process_vm_readv path: the slot carries either {shm_id} or {base}.
//
// Safety: the owner publishes {base, len} per variable in a small shared-
// memory control segment guarded by a per-slot SEQLOCK. Rebind (RAM->mmap
// spill), Update, and FreeVar bump the generation around the mutation, so
// a concurrent CMA reader either sees a stable generation (data valid) or
// retries/falls back to TCP, where the store's shared_mutex serializes it
// against the mutation. A reader can never return bytes from a freed or
// half-updated backing with an even, unchanged generation.
//
// Discovery is authoritative-by-probe: peers exchange
// {pid, boot_id + pid-namespace token, segment name} over the TCP control
// channel; a token match merely permits an attempt — the first
// process_vm_readv failing with EPERM/ESRCH/EFAULT demotes the peer to
// TCP permanently. DDSTORE_CMA=0 disables the whole path.

#ifndef DDSTORE_TPU_CMA_H_
#define DDSTORE_TPU_CMA_H_

#include <atomic>
#include <cstdint>
#include <map>
#include <mutex>
#include <string>

#include "store.h"
#include "thread_annotations.h"

namespace dds {

// Bumped (0003 -> 0004) when the slot layout grew `shm_id`: a stale
// segment from an older build must be rejected by magic, not misread.
constexpr uint64_t kCmaMagic = 0xDD5C3A10C0DE0004ull;
constexpr int kCmaSlots = 256;
// Unpublish leaves a tombstone, not an empty: readers must probe PAST a
// freed slot or a hash-colliding variable behind it silently loses its
// fast path forever.
constexpr uint64_t kCmaTombstone = ~0ull;

struct CmaSlot {
  // Seqlock: even = stable, odd = mutation in progress. hash==0 = empty.
  std::atomic<uint64_t> gen;
  std::atomic<uint64_t> hash;
  // shm_id != 0: the shard lives in the owner's data file
  // "<segment-name>.d<shm_id>" and `base` is the byte offset within it
  // (currently always 0). shm_id == 0: `base` is a raw address in the
  // owner's address space, readable only via process_vm_readv.
  std::atomic<uint64_t> shm_id;
  std::atomic<uint64_t> base;
  std::atomic<uint64_t> len;
};

struct CmaSegment {
  uint64_t magic;
  int64_t pid;
  // Creator's /proc/<pid>/stat starttime (clock ticks since boot). pid
  // alone is recyclable: a crashed peer's segment can outlive it in
  // /dev/shm, and the OS may hand the pid to an unrelated same-uid
  // process whose address space process_vm_readv would then happily (and
  // wrongly) read. pid + starttime is unique for the boot.
  uint64_t start_time;
  // CmaHash of CmaHostToken() (boot-id + pid-namespace): the stale-file
  // sweeper may only judge a creator pid dead via /proc when the
  // segment was made in ITS pid namespace — containers can share a
  // /dev/shm mount without sharing a pid namespace, and an other-ns
  // owner's pid is invisible to our /proc, not dead.
  uint64_t ns_hash;
  CmaSlot slots[kCmaSlots];
};

// FNV-1a; 0 is reserved for "empty slot".
uint64_t CmaHash(const std::string& name);

// Host identity token: boot_id + pid-namespace inode. Equal tokens mean a
// CMA attempt is worth making (different pid namespaces on one host share
// a boot_id but cannot process_vm_readv each other — the probe settles it).
std::string CmaHostToken();

// starttime (field 22 of /proc/<pid>/stat) for `pid`; 0 if unreadable.
// Parsing skips past the last ')' — comm may contain spaces and parens.
uint64_t ProcStartTime(int64_t pid);

// Publisher side: owns a /dev/shm segment advertising this process's
// variable mappings.
class CmaRegistry {
 public:
  CmaRegistry();   // creates the segment; ok() false on failure
  ~CmaRegistry();  // unlinks it

  bool ok() const { return seg_ != nullptr; }
  const std::string& shm_name() const { return shm_name_; }

  // Relax Yama ptrace protection so same-uid peers can process_vm_readv
  // this process. Deferred until a peer actually asks for our CMA info
  // (the kOpCmaInfo handler) instead of done unconditionally at startup:
  // a store whose peers are all cross-host never needs the relaxation.
  void EnableReads();

  // Seqlock-publish `name`'s mapping (new slot or in-place rebind). If
  // `base` was handed out by AllocData the slot advertises the data-file
  // id (peers mmap + memcpy); otherwise the raw address (process_vm_readv).
  void Publish(const std::string& name, const void* base, int64_t len);
  // Seqlock-clear the slot; concurrent readers bounce to TCP.
  void Unpublish(const std::string& name);

  // Shard backing in shareable memory: creates "<shm_name>.d<id>" in
  // /dev/shm sized `nbytes`, maps it RW, and returns the mapping (nullptr
  // on any failure — the caller falls back to malloc and the pvm path).
  // FreeData unmaps + unlinks a mapping AllocData returned; false if the
  // pointer is not one of ours (caller should ::free it instead).
  void* AllocData(int64_t nbytes, uint64_t* id);
  bool FreeData(void* base);

 private:
  CmaSlot* FindSlot(uint64_t h, bool take_empty) DDS_REQUIRES(mu_);

  struct DataFile {
    uint64_t id;
    int64_t len;
  };

  // One writer process, many writer threads. Registration/teardown
  // path: shm file creation under it is accepted (not a hot-path
  // mutex). Ordered after the store's registry lock (PublishVar runs
  // under Store::mu_).
  std::mutex mu_;
  CmaSegment* seg_ = nullptr;
  std::string shm_name_;
  int fd_ = -1;
  std::once_flag reads_enabled_;
  // AllocData'd shard backings
  std::map<void*, DataFile> data_ DDS_GUARDED_BY(mu_);
  uint64_t next_data_id_ DDS_GUARDED_BY(mu_) = 0;
};

// Reader side: a peer's mapped segment + pid.
class CmaPeer {
 public:
  ~CmaPeer();

  // Maps `shm_name` and validates magic, pid AND the creator's starttime
  // against both the segment header and the live /proc entry, so a
  // recycled pid (crashed peer, stale segment) is rejected instead of
  // read. nullptr on any failure.
  static CmaPeer* Open(const std::string& shm_name, int64_t pid,
                       uint64_t start_time);

  // Try to serve `ops` one-sidedly: plain memcpy from the peer's mapped
  // /dev/shm data file when the slot advertises one (the scatter-read
  // fast path — zero per-segment kernel cost), process_vm_readv on the
  // raw address otherwise. Returns:
  //   kOk          — all bytes read under a stable generation
  //   kCmaFallback — mapping absent/changing/denied; caller uses TCP
  // Never returns partial data as success.
  static constexpr int kCmaFallback = 1;
  int TryReadV(const std::string& name, const ReadOp* ops, int64_t n);

  // After EPERM/ESRCH the kernel will never allow this pair; the caller
  // should drop the peer to TCP permanently.
  bool denied() const { return denied_.load(std::memory_order_relaxed); }

 private:
  CmaPeer(CmaSegment* seg, size_t map_len, int64_t pid, uint64_t start,
          std::string shm_name)
      : seg_(seg), map_len_(map_len), pid_(pid), start_time_(start),
        shm_name_(std::move(shm_name)) {}

  // Re-check that pid_ still belongs to the process that created the
  // segment (periodically and on any read failure): if the peer died and
  // the pid was recycled mid-session, reads must demote to TCP, not
  // return another process's memory.
  bool PeerStillAlive();

  // Time-throttled PeerStillAlive (at most one /proc read per ~200 ms).
  // The shm gather path needs an explicit gate: our mmap pins the data
  // file's pages, so reads from a DEAD peer would keep succeeding
  // silently forever — but the store's failure-detection contract says
  // dead peers surface as DDStoreError within bounded time. The pvm
  // path gets the same gate for free (ESRCH from the kernel).
  bool LiveRecently();

  // The peer's data file "<shm_name_>.d<id>", mapped read-only on first
  // use and cached. A cached MAP_SHARED mapping pins the file's tmpfs
  // pages (host RAM) even after the owner unlinks it (spill, FreeVar,
  // republish), so mappings are refcounted: Ensure pins, Release unpins,
  // and Ensure opportunistically munmaps unpinned mappings whose backing
  // file is gone — ids are never reused, so a deleted file can have no
  // future reader, and a gather mid-memcpy holds a pin. nullptr =
  // unmappable (negative result cached for deterministic failures only).
  struct DataMap {
    char* base;
    int64_t len;
    int pins;
  };
  const DataMap* EnsureDataMap(uint64_t id);
  void ReleaseDataMap(uint64_t id);

  CmaSegment* seg_;
  size_t map_len_;
  int64_t pid_;
  uint64_t start_time_;
  const std::string shm_name_;
  std::mutex maps_mu_;
  std::map<uint64_t, DataMap> maps_ DDS_GUARDED_BY(maps_mu_);
  std::atomic<int64_t> reads_since_check_{0};
  std::atomic<int64_t> last_live_ns_{0};
  std::atomic<bool> denied_{false};
};

}  // namespace dds

#endif  // DDSTORE_TPU_CMA_H_
