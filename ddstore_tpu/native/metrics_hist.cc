#include "metrics_hist.h"

#include <time.h>

#include <cstdlib>
#include <cstring>

namespace dds {
namespace metrics {

namespace {
thread_local OpTimer* tls_op = nullptr;
}  // namespace

uint64_t OpTimer::NowNs() {
  timespec ts;
  ::clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
         static_cast<uint64_t>(ts.tv_nsec);
}

Registry::Registry() : cells_(new Cell[kMaxCells]) {
  std::memset(tenant_slots_, 0, sizeof(tenant_slots_));
  if (const char* e = std::getenv("DDSTORE_METRICS")) {
    // Only a PARSED zero disables: garbage ("on", "true") must keep
    // the always-on default, not silently kill the latency surface.
    char* end = nullptr;
    const long v = std::strtol(e, &end, 10);
    if (end != e && v == 0)
      enabled_.store(0, std::memory_order_relaxed);
  }
}

int Registry::Configure(int enabled) {
  if (enabled >= 0)
    enabled_.store(enabled ? 1 : 0, std::memory_order_relaxed);
  return 0;
}

void Registry::Reset() {
  for (int i = 0; i < kMaxCells; ++i) {
    Cell& c = cells_[i];
    if (c.key.load(std::memory_order_acquire) == 0) continue;
    c.count.store(0, std::memory_order_relaxed);
    c.lat_sum_ns.store(0, std::memory_order_relaxed);
    c.bytes_sum.store(0, std::memory_order_relaxed);
    for (auto& b : c.lat) b.store(0, std::memory_order_relaxed);
    for (auto& b : c.bytes) b.store(0, std::memory_order_relaxed);
  }
}

namespace {
// Slots store at most kTenantNameCap-1 bytes, so lookups must compare
// the TRUNCATED label — a full-string compare of a 48+-byte label
// against its truncated slot would never match and intern a duplicate
// slot per lookup until the table was exhausted.
bool SlotMatches(const char* slot, const std::string& tenant) {
  const size_t len =
      tenant.size() < kTenantNameCap - 1 ? tenant.size()
                                         : kTenantNameCap - 1;
  return std::strncmp(slot, tenant.data(), len) == 0 &&
         slot[len] == '\0';
}
}  // namespace

int Registry::TenantId(const std::string& tenant) {
  if (tenant.empty()) return 0;
  // Labels with control characters or the CSV separator cannot come
  // through any validated entry point (the Python boundary and the
  // native spec parsers all reject them) — fold anything reaching the
  // raw capi hook into slot 0 so TenantNamesCsv's format can never be
  // corrupted.
  for (const char c : tenant)
    if (static_cast<unsigned char>(c) < 0x20 || c == ',') {
      tenant_overflow_.fetch_add(1, std::memory_order_relaxed);
      return 0;
    }
  // Lock-free scan of the published prefix: slots are immutable once
  // the count's release-store made them visible.
  const int n = tenant_count_.load(std::memory_order_acquire);
  for (int i = 1; i < n; ++i)
    if (SlotMatches(tenant_slots_[i].name, tenant)) return i;
  std::lock_guard<std::mutex> lock(mu_);
  const int n2 = tenant_count_.load(std::memory_order_relaxed);
  for (int i = 1; i < n2; ++i)
    if (SlotMatches(tenant_slots_[i].name, tenant)) return i;
  if (n2 >= kMaxTenants) {
    tenant_overflow_.fetch_add(1, std::memory_order_relaxed);
    return 0;  // fold into the default slot; counted, never blocks
  }
  std::strncpy(tenant_slots_[n2].name, tenant.c_str(),
               kTenantNameCap - 1);
  tenant_slots_[n2].name[kTenantNameCap - 1] = '\0';
  tenant_count_.store(n2 + 1, std::memory_order_release);
  return n2;
}

int Registry::TenantNamesCsv(char* out, int cap) const {
  if (!out || cap <= 0) return 0;
  const int n = tenant_count_.load(std::memory_order_acquire);
  int pos = 0;
  for (int i = 0; i < n; ++i) {
    const char* name = i == 0 ? "" : tenant_slots_[i].name;
    const int len = static_cast<int>(std::strlen(name));
    if (pos + len + 2 > cap) break;
    if (i > 0) out[pos++] = ',';
    std::memcpy(out + pos, name, static_cast<size_t>(len));
    pos += len;
  }
  out[pos < cap ? pos : cap - 1] = '\0';
  return pos;
}

uint64_t Registry::PackKey(int cls, int route, int peer, int tenant_id) {
  // peer + 1 so peer -1 (multi) packs as 0; the claim bit keeps a key
  // of all-zero fields distinct from a free slot.
  return (1ull << 63) |
         (static_cast<uint64_t>(cls & 0xff) << 48) |
         (static_cast<uint64_t>(route & 0xff) << 40) |
         (static_cast<uint64_t>(tenant_id & 0xffff) << 24) |
         (static_cast<uint64_t>(peer + 1) & 0xffffff);
}

Registry::Cell* Registry::FindCell(uint64_t key) {
  // splitmix-style scramble so adjacent peers don't cluster.
  uint64_t h = key;
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdull;
  h ^= h >> 33;
  for (int probe = 0; probe < kMaxCells; ++probe) {
    Cell& c = cells_[(h + probe) % kMaxCells];
    uint64_t k = c.key.load(std::memory_order_acquire);
    if (k == key) return &c;
    if (k == 0) {
      uint64_t expected = 0;
      // Release on success: a snapshot reader that sees the key sees a
      // fully constructed (zeroed) cell.
      if (c.key.compare_exchange_strong(expected, key,
                                        std::memory_order_acq_rel))
        return &c;
      if (expected == key) return &c;  // lost the race to ourselves
    }
  }
  return nullptr;  // table full
}

void Registry::Record(int cls, int route, int peer, int tenant_id,
                      uint64_t lat_ns, uint64_t bytes) {
  Cell* c = FindCell(PackKey(cls, route, peer, tenant_id));
  if (!c) {
    dropped_.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  c->count.fetch_add(1, std::memory_order_relaxed);
  c->lat_sum_ns.fetch_add(lat_ns, std::memory_order_relaxed);
  c->lat[BucketOf(lat_ns)].fetch_add(1, std::memory_order_relaxed);
  c->bytes_sum.fetch_add(bytes, std::memory_order_relaxed);
  c->bytes[BucketOf(bytes)].fetch_add(1, std::memory_order_relaxed);
  recorded_.fetch_add(1, std::memory_order_relaxed);
}

int64_t Registry::Snapshot(void* out, int64_t cap_bytes) const {
  constexpr int64_t kRec = static_cast<int64_t>(sizeof(CellRecord));
  if (!out) return kMaxCells * kRec;
  char* p = static_cast<char*>(out);
  int64_t written = 0;
  for (int i = 0; i < kMaxCells; ++i) {
    const Cell& c = cells_[i];
    const uint64_t key = c.key.load(std::memory_order_acquire);
    if (key == 0) continue;
    const uint64_t count = c.count.load(std::memory_order_relaxed);
    if (count == 0) continue;  // claimed but not yet (or reset) counted
    if (written + kRec > cap_bytes) break;
    CellRecord r;
    std::memset(&r, 0, sizeof(r));
    r.cls = static_cast<int32_t>((key >> 48) & 0xff);
    r.route = static_cast<int32_t>((key >> 40) & 0xff);
    r.peer = static_cast<int32_t>(key & 0xffffff) - 1;
    const int tid = static_cast<int>((key >> 24) & 0xffff);
    if (tid > 0 && tid < tenant_count_.load(std::memory_order_acquire))
      std::strncpy(r.tenant, tenant_slots_[tid].name,
                   kTenantNameCap - 1);
    r.count = count;
    r.lat_sum_ns = c.lat_sum_ns.load(std::memory_order_relaxed);
    r.bytes_sum = c.bytes_sum.load(std::memory_order_relaxed);
    for (int b = 0; b < kBuckets; ++b) {
      r.lat[b] = c.lat[b].load(std::memory_order_relaxed);
      r.bytes[b] = c.bytes[b].load(std::memory_order_relaxed);
    }
    std::memcpy(p + written, &r, sizeof(r));
    written += kRec;
  }
  return written;
}

void Registry::TenantLatHist(int tenant_id, uint64_t hist[kBuckets],
                             uint64_t* count) const {
  for (int b = 0; b < kBuckets; ++b) hist[b] = 0;
  uint64_t n = 0;
  for (int i = 0; i < kMaxCells; ++i) {
    const Cell& c = cells_[i];
    const uint64_t key = c.key.load(std::memory_order_acquire);
    if (key == 0) continue;
    if (static_cast<int>((key >> 24) & 0xffff) != tenant_id) continue;
    for (int b = 0; b < kBuckets; ++b)
      hist[b] += c.lat[b].load(std::memory_order_relaxed);
    n += c.count.load(std::memory_order_relaxed);
  }
  if (count) *count = n;
}

void Registry::Stats(int64_t out[kNumStats]) const {
  for (int i = 0; i < kNumStats; ++i) out[i] = 0;
  int64_t used = 0;
  for (int i = 0; i < kMaxCells; ++i)
    if (cells_[i].key.load(std::memory_order_acquire) != 0) ++used;
  out[0] = enabled() ? 1 : 0;
  out[1] = used;
  out[2] = kMaxCells;
  out[3] = dropped_.load(std::memory_order_relaxed);
  out[4] = tenant_count_.load(std::memory_order_acquire);
  out[5] = tenant_overflow_.load(std::memory_order_relaxed);
  out[6] = recorded_.load(std::memory_order_relaxed);
}

OpTimer::OpTimer(Registry* reg, int cls, int peer, int tenant_id,
                 uint64_t bytes, uint64_t t0_ns)
    : reg_(reg && reg->enabled() ? reg : nullptr) {
  if (!reg_) return;
  if (tls_op) {
    // Nested op (the async issue->completion bracket already timing
    // this thread's inner GetBatch/ReadRuns execution leg): ONE op =
    // ONE sample — recording both would double-count the tenant's
    // traffic and dilute the SLO quantile with the faster execution
    // legs, masking a queueing-driven breach. Route marks land on the
    // enclosing (sole) active token; at most one token is ever live
    // per thread.
    reg_ = nullptr;
    return;
  }
  t0_ns_ = t0_ns ? t0_ns : NowNs();
  cls_ = cls;
  peer_ = peer;
  tenant_ = tenant_id;
  bytes_ = bytes;
  tls_op = this;
}

OpTimer::~OpTimer() {
  if (!reg_) return;
  tls_op = nullptr;
  const uint64_t now = NowNs();
  reg_->Record(cls_, route_, peer_, tenant_,
               now > t0_ns_ ? now - t0_ns_ : 0, bytes_);
}

void OpTimer::MarkRoute(int route) {
  if (tls_op && route > tls_op->route_) tls_op->route_ = route;
}

}  // namespace metrics
}  // namespace dds
