// Machine-readable lock specifications for the native layer.
//
// The concurrency invariants of this codebase ("never hold a data-lane
// mutex during Ping", "no getenv under async_mu_ on the hot path",
// "health thread declared last = joined first") used to live only in
// CHANGES.md prose. These macros turn them into annotations that
// (a) the repo-native static analyzer (ddstore_tpu/analysis — lexer +
// per-function lock-state tracker, runs as a tier-1 test) consumes as
// ground truth, and (b) map onto clang's Thread Safety Analysis
// attributes when a clang build opts in. Under this container's gcc 10
// (and by default everywhere) they expand to nothing — zero code-gen
// or ABI effect.
//
// Vocabulary (annotation arguments name mutexes; the analyzer also
// accepts qualified inner-struct names like `Conn::mu` that are not
// valid C++ expressions, which is why the clang mapping is opt-in via
// -DDDS_USE_CLANG_THREAD_SAFETY rather than automatic):
//
//   DDS_GUARDED_BY(m)        field: reads/writes require m held.
//   DDS_REQUIRES(...)        function: caller must hold these mutexes
//                            (the analyzer checks call sites AND treats
//                            them as held inside the body).
//   DDS_EXCLUDES(...)        function: must not acquire (or hold) these
//                            — e.g. Ping vs the data-lane mutexes.
//   DDS_ACQUIRED_BEFORE(...) mutex decl: declared lock-order edges,
//                            seeding the analyzer's global
//                            acquisition-order graph (observed lexical
//                            nesting adds the rest; cycles fail lint).
//   DDS_NO_BLOCKING          mutex decl: no blocking call (connect,
//                            poll, read/recv, sleep_for, Wait, getenv,
//                            ...) may run while this mutex is held —
//                            the "hot-path mutex" marker.
//   DDS_DESTROYED_BEFORE(m)  member decl: this member's destructor must
//                            run before m's, i.e. it must be DECLARED
//                            AFTER m (reverse destruction order). Pins
//                            "health thread declared last = joined
//                            first"-style teardown contracts.
//
// Adding a new mutex? Annotate its guarded fields and lock-taking
// methods here-style, then run `make lint` — see README "Static
// analysis".

#ifndef DDSTORE_TPU_THREAD_ANNOTATIONS_H_
#define DDSTORE_TPU_THREAD_ANNOTATIONS_H_

#if defined(DDS_USE_CLANG_THREAD_SAFETY) && defined(__clang__)
// Clang Thread Safety Analysis mapping. Opt-in: some annotation
// arguments in this tree (qualified inner-struct mutex names, parameter
// members) are analyzer-vocabulary, not valid capability expressions,
// so the default build must not feed them to the compiler.
#define DDS_GUARDED_BY(x) __attribute__((guarded_by(x)))
#define DDS_REQUIRES(...) __attribute__((exclusive_locks_required(__VA_ARGS__)))
#define DDS_EXCLUDES(...) __attribute__((locks_excluded(__VA_ARGS__)))
#define DDS_ACQUIRED_BEFORE(...) __attribute__((acquired_before(__VA_ARGS__)))
#else
#define DDS_GUARDED_BY(x)
#define DDS_REQUIRES(...)
#define DDS_EXCLUDES(...)
#define DDS_ACQUIRED_BEFORE(...)
#endif

// Analyzer-only markers (no clang TSA equivalent).
#define DDS_NO_BLOCKING
#define DDS_DESTROYED_BEFORE(x)

#endif  // DDSTORE_TPU_THREAD_ANNOTATIONS_H_
