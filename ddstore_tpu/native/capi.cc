// C ABI over the store core, consumed by the Python ctypes binding
// (ddstore_tpu/binding.py). Fills the role of the reference's Cython layer
// (/root/reference/src/pyddstore.pyx:33-131) but is dtype-agnostic: rows are
// byte spans here; dtype dispatch lives in Python where numpy already knows
// it (the reference instantiates six C++ templates instead,
// pyddstore.pyx:69-82).

#include <cstdint>
#include <cstring>
#include <memory>
#include <string>
#include <vector>

#include "fault.h"
#include "local_transport.h"
#include "store.h"
#include "tcp_transport.h"
#include "trace.h"
#include "uring_transport.h"

using dds::Store;

extern "C" {

struct dds_handle {
  std::unique_ptr<Store> store;
  dds::TcpTransport* tcp = nullptr;      // borrowed, owned by store
  dds::UringTransport* uring = nullptr;  // borrowed; also set as tcp (subclass)
  dds::LocalTransport* local = nullptr;  // borrowed, owned by store
  std::string local_gid;
};

dds_handle* dds_create_local(const char* group_id, int rank, int world) {
  auto group = dds::LocalGroup::GetOrCreate(group_id, world);
  if (!group) return nullptr;
  auto transport = std::make_unique<dds::LocalTransport>(std::move(group), rank);
  dds::LocalTransport* raw = transport.get();
  auto* h = new dds_handle();
  h->store = std::make_unique<Store>(std::move(transport));
  h->local = raw;
  h->local_gid = group_id;
  raw->Attach(h->store.get());
  return h;
}

dds_handle* dds_create_tcp(int rank, int world, int port) {
  auto transport = std::make_unique<dds::TcpTransport>(rank, world, port);
  if (transport->server_port() < 0) return nullptr;
  dds::TcpTransport* raw = transport.get();
  auto* h = new dds_handle();
  h->store = std::make_unique<Store>(std::move(transport));
  h->tcp = raw;
  raw->Attach(h->store.get());
  return h;
}

// DDSTORE_TRANSPORT=uring. A UringTransport IS a TcpTransport (the
// wire loop is the only override), so every tcp entry point here —
// dds_set_peers, dds_server_port, faults, failover, gateway — serves
// uring handles through h->tcp unchanged. When the capability probe
// refuses (gVisor-class kernels), the handle still constructs and
// serves through the inherited TCP path; dds_uring_state/_reason
// export that verdict as a first-class fact.
dds_handle* dds_create_uring(int rank, int world, int port) {
  auto transport = std::make_unique<dds::UringTransport>(rank, world, port);
  if (transport->server_port() < 0) return nullptr;
  dds::UringTransport* raw = transport.get();
  auto* h = new dds_handle();
  h->store = std::make_unique<Store>(std::move(transport));
  h->tcp = raw;
  h->uring = raw;
  raw->Attach(h->store.get());
  return h;
}

int dds_server_port(dds_handle* h) {
  return h && h->tcp ? h->tcp->server_port() : -1;
}

int dds_set_peers(dds_handle* h, const char** hosts, const int* ports, int n) {
  if (!h || !h->tcp) return dds::kErrInvalidArg;
  std::vector<std::string> hs(hosts, hosts + n);
  std::vector<int> ps(ports, ports + n);
  return h->tcp->SetPeers(hs, ps);
}

int dds_update_peer(dds_handle* h, int target, const char* host_csv,
                    int port) {
  if (!h || !h->tcp || !host_csv) return dds::kErrInvalidArg;
  int rc = h->tcp->UpdatePeer(target, host_csv, port);
  // The replacement process gets a clean liveness slate: suspicion
  // belonged to the dead process at the old endpoint.
  if (rc == dds::kOk) h->store->ClearPeerSuspected(target);
  return rc;
}

// -- replication / failover / heartbeat --------------------------------------

// The replication factor in force (DDSTORE_REPLICATION clamped to
// [1, world]; 1 = replication off, exactly the pre-replication tree).
int dds_replication(dds_handle* h) {
  return h ? h->store->replication() : dds::kErrInvalidArg;
}

// Pull/refresh this rank's mirrors of `name` (the shards of the next
// R-1 ranks). The Python add() calls it after the registration barrier
// (every owner's shard must exist before any holder pulls).
int dds_replicate(dds_handle* h, const char* name) {
  if (!h || !name) return dds::kErrInvalidArg;
  return h->store->Replicate(name);
}

// Re-pull EVERY mirror this rank hosts, creating missing ones — the
// elastic-recovery rebuild (survivors re-mirror the replacement's
// restored shard; the replacement builds its chain from scratch).
// Suspected/unreachable owners are skipped, never fatal.
int dds_refresh_mirrors(dds_handle* h) {
  if (!h) return dds::kErrInvalidArg;
  h->store->RefreshMirrors();
  return dds::kOk;
}

// Replica set of `owner`'s shard, primary first (chain placement).
// Returns the count written into `out` (bounded by cap).
int dds_replica_set(dds_handle* h, int owner, int* out, int cap) {
  if (!h || !out) return dds::kErrInvalidArg;
  return h->store->ReplicaSet(owner, out, cap);
}

// Per-peer liveness view (union of heartbeat verdicts and data-path
// ladder give-ups): writes min(world, cap) 0/1 suspicion flags,
// returns the count written.
int dds_health_state(dds_handle* h, int64_t* out, int cap) {
  if (!h || !out) return dds::kErrInvalidArg;
  return h->store->HealthState(out, cap);
}

// Runtime heartbeat control: interval_ms > 0 (re)starts the detector
// with that ping period (suspect_n <= 0 keeps the env/default
// threshold); interval_ms <= 0 stops it. The suspect registry itself
// survives a stop.
int dds_heartbeat_configure(dds_handle* h, long interval_ms,
                            int suspect_n) {
  if (!h) return dds::kErrInvalidArg;
  h->store->ConfigureHeartbeat(interval_ms, suspect_n);
  return dds::kOk;
}

// Test/ops hook: force one peer into (or out of) the suspect set —
// deterministic failover routing without killing anything.
int dds_mark_suspect(dds_handle* h, int target, int suspected) {
  if (!h) return dds::kErrInvalidArg;
  if (suspected)
    h->store->MarkPeerSuspected(target);
  else
    h->store->ClearPeerSuspected(target);
  return dds::kOk;
}

// Failover/heartbeat observability snapshot. Layout (keep in sync with
// binding.py FAILOVER_STAT_KEYS): [replication, failover_reads,
// failover_runs, failover_bytes, suspect_skips, replica_giveups,
// mirror_fills, mirror_refresh_skipped, mirror_bytes, hb_pings,
// hb_failures, hb_suspects_raised, hb_active, suspected_now, 0, 0].
int dds_failover_stats(dds_handle* h, int64_t out[16]) {
  if (!h || !out) return dds::kErrInvalidArg;
  h->store->FailoverCounters(out);
  return dds::kOk;
}

// -- end-to-end data integrity ------------------------------------------------

// Runtime integrity toggles: verify -1 keeps / 0 off / 1 on (reader-
// side verification; also enables sum computation); scrub_ms -1 keeps /
// 0 stops the background scrubber / >0 (re)starts it at that
// per-mirror tick. Load-time equivalents: DDSTORE_VERIFY /
// DDSTORE_SCRUB_MS.
int dds_integrity_configure(dds_handle* h, int verify, long scrub_ms) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->ConfigureIntegrity(verify, scrub_ms);
}

// Integrity observability snapshot. Layout (keep in sync with
// binding.py INTEGRITY_STAT_KEYS): [verify_mode, sums_tables,
// sums_computed, sums_rows, sums_served, verified_reads,
// verified_bytes, verify_mismatches, verify_seq_retries,
// verify_primary_retries, verify_failovers, corrupt_errors,
// scrub_rows, scrub_divergent, scrub_repaired, last_corrupt_peer].
int dds_integrity_stats(dds_handle* h, int64_t out[16]) {
  if (!h || !out) return dds::kErrInvalidArg;
  h->store->IntegrityStats(out);
  return dds::kOk;
}

// Owner-side sum read (test/debug hook): `count` per-row checksums of
// the LOCAL shard of `name` starting at local row `row0`, plus the
// content version they were computed at. Builds the table lazily;
// kErrNotFound while integrity is disabled.
int dds_integrity_sums(dds_handle* h, const char* name, int64_t row0,
                       int64_t count, uint64_t* out, int64_t* seq) {
  if (!h || !name || !out) return dds::kErrInvalidArg;
  return h->store->RowSums(name, row0, count, out, seq);
}

// One synchronous scrub pass over every resident mirror (the
// deterministic test/bench hook; the DDSTORE_SCRUB_MS thread does the
// same one mirror per tick). Returns the number of divergent mirrors
// found, or a negative ErrorCode.
int dds_integrity_scrub(dds_handle* h) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->ScrubOnce();
}

// -- tiered storage: hot-row cache + cold placement ---------------------------

// Runtime hot-row cache budget (bytes; 0 disables and evicts
// everything, < 0 keeps). Load-time equivalent:
// DDSTORE_TIER_CACHE_BYTES.
int dds_tier_configure(dds_handle* h, int64_t cache_bytes) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->ConfigureTierCache(cache_bytes);
}

// Record a registered variable's storage tier (0 = hot RAM/shm, 1 =
// cold file-backed) — drives the cold_vars/cold_bytes gauges; the
// serving legs are tier-agnostic.
int dds_set_var_tier(dds_handle* h, const char* name, int tier) {
  if (!h || !name) return dds::kErrInvalidArg;
  return h->store->SetVarTier(name, tier);
}

// The recorded tier of `name`, or a negative ErrorCode.
int dds_var_tier(dds_handle* h, const char* name) {
  if (!h || !name) return dds::kErrInvalidArg;
  return h->store->VarTier(name);
}

// Per-tenant placement policy for mirror fills and snapshot kept
// copies: cold != 0 lands them file-backed under DDSTORE_TIER_COLD_DIR.
int dds_set_tier_placement(dds_handle* h, const char* tenant, int cold) {
  if (!h || !tenant) return dds::kErrInvalidArg;
  return h->store->SetTierPlacement(tenant, cold);
}

// Warm the hot-row cache with `n` sorted-unique global rows of `name`
// as window `window` (the eviction key); the fill runs detached on the
// async pool. Advisory: disabled-cache / duplicate / over-budget calls
// are counted no-ops. `as_tenant` (nullable) names the READING tenant
// for the quota charge and QoS admission.
int64_t dds_cache_prefetch(dds_handle* h, const char* name,
                           const int64_t* rows, int64_t n,
                           int64_t window, const char* as_tenant) {
  if (!h || !name) return dds::kErrInvalidArg;
  return h->store->CachePrefetch(name, rows, n, window,
                                 as_tenant ? as_tenant : "");
}

// Evict window `window`'s cache entries (< 0: every entry), releasing
// their tenant-quota charges. Returns the count evicted.
int dds_cache_evict(dds_handle* h, int64_t window) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->CacheEvict(window);
}

// Tiering observability snapshot. Layout (keep in sync with binding.py
// TIERING_STAT_KEYS): [cache_max_bytes, cache_bytes, cache_entries,
// cold_vars, cold_bytes, cache_hits, cache_hit_bytes, cache_misses,
// cache_miss_bytes, cache_fills, cache_fill_bytes, cache_fill_failures,
// cache_evictions, cache_evicted_bytes, cache_over_budget,
// cache_prefetches].
int dds_tiering_stats(dds_handle* h, int64_t out[16]) {
  if (!h || !out) return dds::kErrInvalidArg;
  h->store->TieringStats(out);
  return dds::kOk;
}

// -- io_uring data plane ------------------------------------------------------

// Process-wide capability probe, independent of any store (the diag
// module reports it before deciding a transport). Layout: [supported,
// features, op_send, op_recv, op_sendmsg, op_recvmsg, op_read,
// op_read_fixed, ext_arg, reserved].
int dds_uring_probe(int64_t out[10]) {
  if (!out) return dds::kErrInvalidArg;
  const dds::UringCaps& c = dds::ProbeUring();
  out[0] = c.supported ? 1 : 0;
  out[1] = static_cast<int64_t>(c.features);
  out[2] = c.op_send ? 1 : 0;
  out[3] = c.op_recv ? 1 : 0;
  out[4] = c.op_sendmsg ? 1 : 0;
  out[5] = c.op_recvmsg ? 1 : 0;
  out[6] = c.op_read ? 1 : 0;
  out[7] = c.op_read_fixed ? 1 : 0;
  out[8] = c.ext_arg ? 1 : 0;
  out[9] = 0;
  return dds::kOk;
}

// The probe's human-readable verdict ("ok" or why not). Returns the
// full reason length; the copy is NUL-terminated and truncated to cap.
int dds_uring_probe_reason(char* buf, int cap) {
  const std::string& r = dds::ProbeUring().reason;
  if (buf && cap > 0) {
    const int n = static_cast<int>(r.size()) < cap - 1
                      ? static_cast<int>(r.size())
                      : cap - 1;
    std::memcpy(buf, r.data(), n);
    buf[n] = '\0';
  }
  return static_cast<int>(r.size());
}

// 1 = uring handle with the ring engaged, 0 = uring handle serving
// through the TCP fallback (probe refused), -1 = not a uring handle.
int dds_uring_state(dds_handle* h) {
  if (!h || !h->uring) return -1;
  return h->uring->engaged() ? 1 : 0;
}

// This handle's engagement/fallback reason ("ok" when engaged).
// Same copy contract as dds_uring_probe_reason; -1 for non-uring.
int dds_uring_reason(dds_handle* h, char* buf, int cap) {
  if (!h || !h->uring) return -1;
  const std::string& r = h->uring->reason();
  if (buf && cap > 0) {
    const int n = static_cast<int>(r.size()) < cap - 1
                      ? static_cast<int>(r.size())
                      : cap - 1;
    std::memcpy(buf, r.data(), n);
    buf[n] = '\0';
  }
  return static_cast<int>(r.size());
}

// Wire-loop counters: [engaged, bursts, enters, sqes, frames,
// fallbacks, ring_errors]. A healthy engaged run shows enters far
// below frames (the point); fallbacks counts reads served by the
// inherited TCP loop after a per-lane ring refusal.
int dds_uring_stats(dds_handle* h, int64_t out[7]) {
  if (!h || !out) return dds::kErrInvalidArg;
  if (!h->uring) return dds::kErrInvalidArg;
  h->uring->UringCounters(out);
  return dds::kOk;
}

// Cold-tier O_DIRECT reader counters, any handle: [files, reads,
// bytes, fallbacks, regbuf, ring_ok].
int dds_cold_direct_stats(dds_handle* h, int64_t out[6]) {
  if (!h || !out) return dds::kErrInvalidArg;
  h->store->ColdDirectStats(out);
  return dds::kOk;
}

// Register a READONLY cold var's backing file for O_DIRECT serving
// (Store::SetVarFile contract: tier-1 vars only; kErrTransport when
// io_uring/O_DIRECT is unavailable — the var stays on the mmap path).
int dds_set_var_file(dds_handle* h, const char* name, const char* path) {
  if (!h || !name || !path) return dds::kErrInvalidArg;
  return h->store->SetVarFile(name, path);
}

// Requester-side send gather counters for the TCP pipeline:
// [req_frames, req_sends]. frames/sends is the writev gather factor
// the half-window refill buys (1.0 = the old one-sendmsg-per-frame
// steady state). Works on tcp AND uring handles (the uring wire loop
// does not count here — its burst gather is visible in
// dds_uring_stats instead).
int dds_req_send_stats(dds_handle* h, int64_t out[2]) {
  if (!h || !out || !h->tcp) return dds::kErrInvalidArg;
  h->tcp->ReqSendCounters(out);
  return dds::kOk;
}

// -- ddmetrics: live latency histograms + SLO monitor -------------------------

// Runtime switch for THIS store's histograms (-1 keeps; load-time knob
// DDSTORE_METRICS, default on). Per-store, unlike the process-global
// trace rings: a ThreadGroup's in-process ranks keep separate surfaces.
int dds_metrics_configure(dds_handle* h, int enabled) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->ConfigureMetrics(enabled);
}

int dds_metrics_enabled(dds_handle* h) {
  return h && h->store->MetricsEnabled() ? 1 : 0;
}

// Zero every cell's counters (claimed keys/tenants stay interned).
int dds_metrics_reset(dds_handle* h) {
  if (!h) return dds::kErrInvalidArg;
  h->store->MetricsReset();
  return dds::kOk;
}

// Serialize this store's cells as packed metrics::CellRecords
// (binding.py METRICS_CELL_DTYPE). out == NULL returns the worst-case
// byte size; else the bytes written.
int64_t dds_metrics_snapshot(dds_handle* h, void* out, int64_t cap) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->MetricsSnapshot(out, cap);
}

// Pull `target`'s snapshot over the control plane (kOpMetrics on the
// dedicated PingConn; LocalTransport reads the peer registry
// directly). Returns bytes written, or a negative ErrorCode —
// kErrPeerLost for a detector-suspected/dead peer (zero budget burned,
// never a giveup).
int64_t dds_metrics_pull(dds_handle* h, int target, void* out,
                         int64_t cap) {
  if (!h || !out) return dds::kErrInvalidArg;
  return h->store->MetricsPull(target, out, cap);
}

// Counter snapshot: [enabled, cells, cells_cap, dropped_cells,
// tenants, tenant_overflow, ops_recorded, 0] — keep in sync with
// binding.py METRICS_STAT_KEYS.
int dds_metrics_stats(dds_handle* h, int64_t out[8]) {
  if (!h || !out) return dds::kErrInvalidArg;
  h->store->MetricsStats(out);
  return dds::kOk;
}

// CSV of interned reading-tenant labels in slot order (the default
// tenant is the leading empty field). Returns the length written.
int dds_metrics_tenants(dds_handle* h, char* out, int cap) {
  if (!h || !out || cap <= 0) return dds::kErrInvalidArg;
  return h->store->metrics_registry().TenantNamesCsv(out, cap);
}

// Test / Python-side injection hook: fold one synthetic op sample into
// the histograms (bucket-math units, exporter fixtures, Python-layer
// ops that never cross the native read path). kErrInvalidArg on an
// out-of-range class/route/peer, like every sibling entry.
int dds_metrics_record(dds_handle* h, int cls, int route, int peer,
                       const char* tenant, int64_t lat_ns,
                       int64_t bytes) {
  if (!h || lat_ns < 0 || bytes < 0) return dds::kErrInvalidArg;
  return h->store->MetricsRecord(cls, route, peer,
                                 tenant ? tenant : "",
                                 static_cast<uint64_t>(lat_ns),
                                 static_cast<uint64_t>(bytes));
}

// Replace the tenant latency objectives ("t=p99:5ms,..."; empty
// clears; load-time knob DDSTORE_TENANT_SLOS). Baselines reset to the
// current histograms.
int dds_slo_configure(dds_handle* h, const char* spec) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->SetTenantSlos(spec ? spec : "");
}

// Evaluate every objective over the delta window since the last
// evaluation (rate-limited by DDSTORE_SLO_WINDOW_MS). Breach rows of 6
// int64s [tenant_slot, pct, threshold_ns, measured_low_ns,
// window_count, 0] land in `out` (<= cap_rows); returns the breach
// count. Each breach emits a kSloBreach trace event and one flight
// dump (kReasonSloBreach).
int64_t dds_slo_evaluate(dds_handle* h, int64_t* out, int cap_rows) {
  if (!h || !out) return dds::kErrInvalidArg;
  return h->store->EvaluateSlos(out, cap_rows);
}

// [rules, evaluations, breaches, window_ms, last_breach_tenant_slot,
// 0, 0, 0] — keep in sync with binding.py SLO_STAT_KEYS.
int dds_slo_stats(dds_handle* h, int64_t out[8]) {
  if (!h || !out) return dds::kErrInvalidArg;
  h->store->SloStats(out);
  return dds::kOk;
}

// -- tenant namespaces / quotas / snapshot epochs -----------------------------

// Byte/var budget for one tenant (< 0 = unlimited). Checked-and-
// reserved atomically at add/init registration; kErrQuota (-11) on
// exhaustion — classified distinctly from kErrPeerLost.
int dds_tenant_set_quota(dds_handle* h, const char* tenant,
                         int64_t max_bytes, int64_t max_vars) {
  if (!h || !tenant) return dds::kErrInvalidArg;
  return h->store->SetTenantQuota(tenant, max_bytes, max_vars);
}

// Async-admission weight (>= 1): with any share configured, tenant t
// runs at most max(1, width * share_t / total) concurrent async reads.
int dds_tenant_set_share(dds_handle* h, const char* tenant, int share) {
  if (!h || !tenant) return dds::kErrInvalidArg;
  return h->store->SetTenantShare(tenant, share);
}

// QoS lane budget for one tenant's striped reads (<= 0 clears). No-op
// kOk on non-TCP backends (no lanes to budget).
int dds_tenant_set_lane_budget(dds_handle* h, const char* tenant,
                               int lanes) {
  if (!h || !tenant) return dds::kErrInvalidArg;
  if (!h->tcp) return dds::kOk;
  return h->tcp->SetTenantLaneBudget(tenant, lanes);
}

// CSV of every tenant the store has seen; returns the length written.
int dds_tenant_names(dds_handle* h, char* out, int cap) {
  if (!h || !out || cap <= 0) return dds::kErrInvalidArg;
  return h->store->TenantNames(out, cap);
}

// Per-tenant ledger snapshot. Layout (keep in sync with binding.py
// TENANT_STAT_KEYS): [quota_bytes, quota_vars, bytes, vars,
// quota_rejections, read_bytes, reads, served_bytes, served_reads,
// async_admitted, async_deferred, snapshot_pins, share, 0, 0, 0].
int dds_tenant_stats(dds_handle* h, const char* tenant,
                     int64_t out[16]) {
  if (!h || !tenant || !out) return dds::kErrInvalidArg;
  return h->store->TenantCounters(tenant, out);
}

// Pin the store-wide current shard versions for a read-only snapshot
// reader (local pin + a control op to every peer; all-or-nothing).
// Returns a positive snapshot id, or a negative ErrorCode.
int64_t dds_snapshot_acquire(dds_handle* h, const char* tenant) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->SnapshotAcquire(tenant ? tenant : "");
}

// Release a snapshot everywhere; kept versions whose last pin this was
// are freed (dead peers best-effort).
int dds_snapshot_release(dds_handle* h, int64_t snap_id) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->SnapshotRelease(snap_id);
}

// [active_snapshots, kept_versions, kept_bytes, reclaimed_pins] on
// THIS rank.
int dds_snapshot_stats(dds_handle* h, int64_t out[4]) {
  if (!h || !out) return dds::kErrInvalidArg;
  h->store->SnapshotCounters(out);
  return dds::kOk;
}

// -- serving gateway ---------------------------------------------------------

// Runtime gateway (re)configuration; -1 keeps each numeric field.
// enabled >= 1 also clears a previous drain and (re)arms the lease
// reaper; pin_ttl_ms arms stranded-pin reclaim even with the gateway
// off.
int dds_gateway_configure(dds_handle* h, int enabled, long lease_ms,
                          long defer_ms, int queue_cap,
                          int admit_margin_pct, int lane_share,
                          long pin_ttl_ms) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->ConfigureGateway(enabled, lease_ms, defer_ms,
                                    queue_cap, admit_margin_pct,
                                    lane_share, pin_ttl_ms);
}

// Attach an ephemeral reader session on `target`'s gateway (target ==
// this rank or < 0 attaches locally). Returns a positive session
// token, or a negative ErrorCode.
int64_t dds_gateway_attach(dds_handle* h, int target, const char* tenant,
                           int with_snapshot, int64_t quota_bytes) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->GatewayAttachTo(target, tenant ? tenant : "",
                                   with_snapshot, quota_bytes);
}

// Lease heartbeat: kOk, or kErrNotFound after expiry (re-attach).
int dds_gateway_renew(dds_handle* h, int target, int64_t token) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->GatewayRenewTo(target, token);
}

// Graceful goodbye: releases the lease's pins/quota/lane share.
int dds_gateway_detach(dds_handle* h, int target, int64_t token) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->GatewayDetachTo(target, token);
}

// Stop admitting, wait up to deadline_ms for in-flight reads, shed
// the rest with kErrAdmission. kOk when the gateway went quiet.
int dds_gateway_drain(dds_handle* h, long deadline_ms) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->GatewayDrain(deadline_ms);
}

// One synchronous lease/pin reap pass (the deterministic test hook for
// the background reaper). Returns the number of stale pins reclaimed.
int dds_gateway_reap(dds_handle* h) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->GatewayReap();
}

// Layout (keep in sync with binding.py GATEWAY_STAT_KEYS):
// [enabled, sessions, attaches, detaches, expired, renewals, admitted,
//  deferred, rejected, drain_sheds, draining, inflight, deferred_now,
//  last_retry_after_ms, 0, 0].
int dds_gateway_stats(dds_handle* h, int64_t out[16]) {
  if (!h || !out) return dds::kErrInvalidArg;
  h->store->GatewayStats(out);
  return dds::kOk;
}

int dds_routing_state(dds_handle* h, int cls, double* cma_bw,
                      double* tcp_bw, int64_t* decisions,
                      int64_t* crossovers, int* via_tcp, int* calibrated) {
  if (!h || !h->tcp) return dds::kErrInvalidArg;
  h->tcp->RoutingState(cls, cma_bw, tcp_bw, decisions, crossovers,
                       via_tcp, calibrated);
  return dds::kOk;
}

// Lane (striped-connection) observability. `out` receives
// [max_lanes, active_lanes, parked, autotune, samples,
//  best_bw_bytes_per_s, scatter_active_lanes, scatter_parked] —
// slots 1-5 are the bulk-stripe tuner, 6-7 the scatter-class tuner
// (keep in sync with TcpTransport::LaneState and binding.py
// LANE_STATE_KEYS).
int dds_lane_state(dds_handle* h, int64_t out[8]) {
  if (!h || !out) return dds::kErrInvalidArg;
  for (int i = 0; i < 8; ++i) out[i] = 0;
  if (!h->tcp) return dds::kErrInvalidArg;  // lanes are a TCP concept
  h->tcp->LaneState(out);
  return dds::kOk;
}

// Per-lane response bytes (target >= 0: that peer's lanes; -1: summed
// across peers, lane-aligned). Returns the lane count written into
// `out` (bounded by cap), or a negative error.
int dds_lane_bytes(dds_handle* h, int target, int64_t* out, int cap) {
  if (!h || !out || cap <= 0) return dds::kErrInvalidArg;
  if (!h->tcp) return dds::kErrInvalidArg;
  return h->tcp->LaneBytes(target, out, cap);
}

// Warm-window substrate snapshot for the cost-model scheduler: writes
// up to `cap` rows of 5 doubles [source (0=route, 1=lanes), cls
// (0=bulk, 1=scatter), knob (route: 0=cma/1=tcp; lanes: lane count),
// ewma_bytes_per_s, clean_samples] and returns the row count (keep in
// sync with binding.py SCHED_CELL_COLS). 0 rows for non-TCP backends
// (they have no router/lane tuners to snapshot).
int dds_sched_cells(dds_handle* h, double* out, int cap) {
  if (!h || !out || cap < 0) return dds::kErrInvalidArg;
  if (!h->tcp) return 0;
  return h->tcp->SchedCells(out, cap);
}

// Planner route pin for one traffic class (0 = bulk, 1 = scatter):
// mode 0 = CMA, 1 = TCP, -1 = release to the adaptive router. Ranks
// BELOW the user's env pin (DDSTORE_CMA_BULK/SCATTER) and is released
// by UpdatePeer (the plan was against the old peer set).
int dds_sched_pin_route(dds_handle* h, int cls, int mode) {
  if (!h || !h->tcp) return dds::kErrInvalidArg;
  return h->tcp->PinRoute(cls, mode);
}

// Planner lane-width pin for one traffic class: lanes >= 1 pins the
// stripe width (clamped to the pool size), -1 releases to the lane
// autotuner. Same env-pin/UpdatePeer ranking as the route pin.
int dds_sched_pin_lanes(dds_handle* h, int cls, int lanes) {
  if (!h || !h->tcp) return dds::kErrInvalidArg;
  return h->tcp->PinLanes(cls, lanes);
}

// Async admission width (how many async batched reads run at once):
// n >= 1 overrides, n <= 0 restores the DDSTORE_ASYNC_THREADS /
// core-ladder default. Valid for every backend (the async engine is
// store-level).
int dds_set_async_width(dds_handle* h, int n) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->SetAsyncWidth(n);
}

int dds_async_width(dds_handle* h) {
  return h ? h->store->AsyncWidth() : dds::kErrInvalidArg;
}

// Per-store retry-deadline override (seconds; <= 0 clears). The
// degraded readahead path shares one OP_DEADLINE budget across a
// window give-up and its per-batch refetch through this; other stores
// in the process keep their full budgets.
int dds_set_retry_deadline(dds_handle* h, double seconds) {
  if (!h) return dds::kErrInvalidArg;
  h->store->SetRetryDeadline(seconds);
  return dds::kOk;
}

int64_t dds_barrier_seq(dds_handle* h) {
  return h && h->tcp ? h->tcp->barrier_seq() : -1;
}

int dds_set_barrier_seq(dds_handle* h, int64_t seq) {
  if (!h || !h->tcp) return dds::kErrInvalidArg;
  h->tcp->SetBarrierSeq(seq);
  return dds::kOk;
}

int dds_add(dds_handle* h, const char* name, const void* buf, int64_t nrows,
            int64_t disp, int64_t itemsize, const int64_t* all_nrows,
            int copy) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->Add(name, buf, nrows, disp, itemsize, all_nrows,
                       copy != 0);
}

int dds_init(dds_handle* h, const char* name, int64_t nrows, int64_t disp,
             int64_t itemsize, const int64_t* all_nrows) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->Init(name, nrows, disp, itemsize, all_nrows);
}

int dds_update(dds_handle* h, const char* name, const void* buf, int64_t nrows,
               int64_t row_offset) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->Update(name, buf, nrows, row_offset);
}

// `as_tenant` (nullable) names the READING handle for the per-tenant
// read ledger; NULL/"" derives the tenant from the variable name.
int dds_get(dds_handle* h, const char* name, void* dst, int64_t start,
            int64_t count, const char* as_tenant) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->Get(name, dst, start, count,
                       as_tenant ? as_tenant : "");
}

// `as_tenant` (nullable) names the READING handle for the per-tenant
// read ledger and QoS lane budget; NULL/"" derives the tenant from the
// variable name (the pre-tenancy behavior).
int dds_get_batch(dds_handle* h, const char* name, void* dst,
                  const int64_t* starts, int64_t n,
                  const char* as_tenant) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->GetBatch(name, dst, starts, n,
                            as_tenant ? as_tenant : "");
}

// Async batched reads (the epoch-readahead engine's native leg): issue a
// GetBatch on the store's background pool, poll/wait, release. See
// Store::GetBatchAsync for the contract (dst stays alive until the
// ticket completes; Release blocks until the read finishes).
// `as_tenant` (nullable) names the READING handle for QoS admission
// and the per-tenant admitted/deferred ledger; NULL/"" derives the
// tenant from the variable name (the pre-tenancy behavior).
int64_t dds_get_batch_async(dds_handle* h, const char* name, void* dst,
                            const int64_t* starts, int64_t n,
                            const char* as_tenant) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->GetBatchAsync(name, dst, starts, n,
                                 as_tenant ? as_tenant : "");
}

// Async vectored run read (the readahead window fast path): executes
// the caller's pre-coalesced per-peer runs without re-deriving the
// plan — O(runs), not O(rows). See Store::ReadRunsAsync.
int64_t dds_read_runs_async(dds_handle* h, const char* name, void* dst,
                            const int64_t* targets,
                            const int64_t* src_off,
                            const int64_t* dst_off, const int64_t* nbytes,
                            int64_t nruns, const char* as_tenant) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->ReadRunsAsync(name, dst, targets, src_off, dst_off,
                                 nbytes, nruns,
                                 as_tenant ? as_tenant : "");
}

// 1 = done ok; 0 = still in flight after timeout_ms (0 polls, negative
// waits forever); <0 = error. `done_mono_s` (nullable) receives the
// CLOCK_MONOTONIC completion time, comparable to time.monotonic().
int dds_async_wait(dds_handle* h, int64_t ticket, int64_t timeout_ms,
                   double* done_mono_s) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->AsyncWait(ticket, timeout_ms, done_mono_s);
}

int dds_async_release(dds_handle* h, int64_t ticket) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->AsyncRelease(ticket);
}

int64_t dds_async_pending(dds_handle* h) {
  return h ? h->store->AsyncPending() : 0;
}

int dds_query(dds_handle* h, const char* name, int64_t* total_rows,
              int64_t* disp, int64_t* itemsize, int64_t* local_rows) {
  if (!h) return dds::kErrInvalidArg;
  return h->store->Query(name, total_rows, disp, itemsize, local_rows);
}

int dds_epoch_begin(dds_handle* h) {
  return h ? h->store->EpochBegin() : dds::kErrInvalidArg;
}

int dds_epoch_end(dds_handle* h) {
  return h ? h->store->EpochEnd() : dds::kErrInvalidArg;
}

int dds_set_epoch_collective(dds_handle* h, int collective) {
  if (!h) return dds::kErrInvalidArg;
  h->store->set_epoch_collective(collective != 0);
  return dds::kOk;
}

// Elastic-recovery fence realignment: force the fence state machine
// closed (local, idempotent) — a non-unanimous fence abort can leave
// fence_active_ divergent across survivors; recover() heals it here.
int dds_fence_reset(dds_handle* h) {
  if (!h) return dds::kErrInvalidArg;
  h->store->FenceReset();
  return dds::kOk;
}

int dds_set_ifaces(dds_handle* h, const char* csv) {
  if (!h || !h->tcp || !csv) return dds::kErrInvalidArg;
  h->tcp->SetLocalIfaces(dds::SplitCsv(csv));
  return dds::kOk;
}

int dds_rebind(dds_handle* h, const char* name, void* base) {
  return h ? h->store->Rebind(name, base) : dds::kErrInvalidArg;
}

int dds_free_var(dds_handle* h, const char* name) {
  return h ? h->store->FreeVar(name) : dds::kErrInvalidArg;
}

int dds_barrier(dds_handle* h, int64_t tag) {
  return h ? h->store->Barrier(tag) : dds::kErrInvalidArg;
}

int64_t dds_cma_ops(dds_handle* h) {
  return h && h->tcp ? h->tcp->cma_ops() : 0;
}

int64_t dds_uds_conns(dds_handle* h) {
  return h && h->tcp ? h->tcp->uds_conns() : 0;
}

// Scatter-read planner statistics (cumulative; see dds::PlanStats). `out`
// receives [batches, rows, runs, local_runs, peer_lists, dedup_hits,
// scratch_runs, scratch_bytes] — a flat array so the ctypes binding stays
// struct-layout-agnostic.
int dds_plan_stats(dds_handle* h, int64_t out[8]) {
  if (!h || !out) return dds::kErrInvalidArg;
  dds::PlanStats s = h->store->plan_stats();
  out[0] = s.batches;
  out[1] = s.rows;
  out[2] = s.runs;
  out[3] = s.local_runs;
  out[4] = s.peer_lists;
  out[5] = s.dedup_hits;
  out[6] = s.scratch_runs;
  out[7] = s.scratch_bytes;
  return dds::kOk;
}

// Reconfigure the process-global deterministic fault injector (tests
// script per-run schedules without env plumbing; resets every injector
// counter including the draw counter, so the same seed replays the same
// schedule). Empty/NULL spec disables injection.
int dds_fault_configure(const char* spec, uint64_t seed,
                        const char* ranks_csv) {
  return dds::FaultInjector::Get().Configure(spec ? spec : "", seed,
                                             ranks_csv ? ranks_csv : "");
}

// Fault/retry observability snapshot. `out` receives:
//   [0..5]  process-global injector counters: checks, reset, trunc,
//           delay, stall, injected_delay_ms
//   [6..11] retry counters for THIS handle (store-level layer + TCP
//           leaf layer summed): transient, retries, reconnects,
//           backoff_ms, giveups, fatal
//   [12]    last_error_peer (most recent failed target; -1 = none —
//           the TCP layer's wins when both are set)
//   [13]    injected_corrupt (payloads served with flipped bytes)
//   [14]    ctrl_checks (control-plane injector draws — own counter
//           domain; see fault.h)
//   [15]    ctrl_injected (control-plane faults fired)
int dds_fault_stats(dds_handle* h, int64_t out[16]) {
  if (!h || !out) return dds::kErrInvalidArg;
  for (int i = 0; i < 16; ++i) out[i] = 0;
  dds::FaultInjector::Stats fi = dds::FaultInjector::Get().stats();
  out[0] = fi.checks;
  out[1] = fi.reset;
  out[2] = fi.trunc;
  out[3] = fi.delay;
  out[4] = fi.stall;
  out[5] = fi.delay_ms;
  out[13] = fi.corrupt;
  out[14] = fi.ctrl_checks;
  out[15] = fi.ctrl_injected;
  int64_t st[7], tc[7] = {0, 0, 0, 0, 0, 0, -1};
  h->store->RetryCounters(st);
  if (h->tcp) h->tcp->RetryCounters(tc);
  for (int i = 0; i < 6; ++i) out[6 + i] = st[i] + tc[i];
  out[12] = tc[6] >= 0 ? tc[6] : st[6];
  return dds::kOk;
}

// -- ddtrace: event-ring tracing + flight recorder ----------------------------
//
// Process-global (like the fault injector): the rings belong to
// threads, not stores, and a ThreadGroup test's N in-process "ranks"
// share one trace — every event carries its emitting rank.

// Runtime switch: enabled >= 0 sets (0/1; -1 keeps), ring_events >= 1
// sets the per-thread ring capacity for rings allocated from now on.
int dds_trace_configure(int enabled, long ring_events) {
  return dds::trace::Configure(enabled, ring_events);
}

int dds_trace_enabled(void) { return dds::trace::Enabled() ? 1 : 0; }

// Drop recorded events (rings trimmed, flight buffer cleared). The
// monotone totals in dds_trace_stats keep counting.
int dds_trace_reset(void) {
  dds::trace::Reset();
  return 0;
}

// Python-side event injection (readahead window issue/ready/stall,
// scheduler replan/applied ride this). span 0 = outside any span.
int dds_trace_emit(uint32_t type, uint64_t span, int rank, int64_t a,
                   int64_t b, int64_t c) {
  dds::trace::Emit(static_cast<uint16_t>(type), span, rank, a, b, c);
  return 0;
}

// Mint a span id for a Python-side logical op (a readahead window).
uint64_t dds_trace_new_span(int rank) {
  return dds::trace::NewSpan(rank);
}

// Manual flight-recorder trigger (the Python readahead layer's window
// give-up; reason codes in trace.h FlightReason / binding.py
// TRACE_FLIGHT_REASONS).
int dds_trace_flight(int reason, int rank) {
  dds::trace::Flight(reason, rank);
  return 0;
}

// Serialize ring events (packed 48-byte records, binding.py
// TRACE_EVENT_DTYPE). out == NULL returns the worst-case byte size;
// else returns the bytes written.
int64_t dds_trace_dump(void* out, int64_t cap_bytes) {
  return dds::trace::DumpEvents(out, cap_bytes);
}

// Serialize the LAST flight-recorder snapshot (same record format).
int64_t dds_trace_flight_dump(void* out, int64_t cap_bytes) {
  return dds::trace::DumpFlight(out, cap_bytes);
}

// Counter snapshot: [enabled, ring_events, threads, capacity, live,
// captured, dropped, flight_events, flight_dumps, spans, 0, 0] — keep
// in sync with binding.py TRACE_STAT_KEYS.
int dds_trace_stats(int64_t out[12]) {
  if (!out) return dds::kErrInvalidArg;
  dds::trace::Stats(out);
  return 0;
}

int dds_rank(dds_handle* h) { return h ? h->store->rank() : -1; }
int dds_world(dds_handle* h) { return h ? h->store->world() : -1; }

void dds_destroy(dds_handle* h) { delete h; }

void dds_release_local_group(const char* gid) {
  dds::LocalGroup::Release(gid);
}

const char* dds_error_string(int code) { return dds::ErrorString(code); }

// Exposed for unit tests of the owner-lookup function.
int dds_owner_of(const int64_t* cum, int n, int64_t row) {
  std::vector<int64_t> v(cum, cum + n);
  return Store::OwnerOf(v, row);
}

}  // extern "C"
