#include "local_transport.h"

#include <chrono>
#include <cstring>

#include "trace.h"

namespace dds {

namespace {
std::mutex g_groups_mu;
std::map<std::string, std::shared_ptr<LocalGroup>>* g_groups = nullptr;
}  // namespace

std::shared_ptr<LocalGroup> LocalGroup::GetOrCreate(const std::string& gid,
                                                    int world) {
  std::lock_guard<std::mutex> lock(g_groups_mu);
  if (!g_groups) g_groups = new std::map<std::string, std::shared_ptr<LocalGroup>>();
  auto it = g_groups->find(gid);
  if (it != g_groups->end()) {
    if (it->second->world() != world) return nullptr;
    return it->second;
  }
  auto g = std::make_shared<LocalGroup>(world);
  (*g_groups)[gid] = g;
  return g;
}

void LocalGroup::Release(const std::string& gid) {
  std::lock_guard<std::mutex> lock(g_groups_mu);
  if (g_groups) g_groups->erase(gid);
}

void LocalGroup::Register(int rank, Store* store) {
  std::lock_guard<std::mutex> lock(mu_);
  if (rank >= 0 && rank < world_) {
    members_[rank] = store;
    ever_registered_[rank] = true;
  }
  cv_.notify_all();
}

bool LocalGroup::AliveOrPending(int rank) {
  std::lock_guard<std::mutex> lock(mu_);
  if (rank < 0 || rank >= world_) return false;
  return members_[rank] != nullptr || !ever_registered_[rank];
}

void LocalGroup::Unregister(int rank) {
  std::lock_guard<std::mutex> lock(mu_);
  if (rank >= 0 && rank < world_) members_[rank] = nullptr;
  // A member death is a barrier wake-up event: waiters must notice the
  // closed store NOW, not after sleeping out their 120 s timeout.
  cv_.notify_all();
}

Store* LocalGroup::member(int rank) {
  std::unique_lock<std::mutex> lock(mu_);
  if (rank < 0 || rank >= world_) return nullptr;
  // A peer may not have constructed its store yet (threads race at
  // startup); wait briefly for registration — but ONLY for bootstrap.
  // A member that registered and then closed is dead NOW: a 30 s
  // grace for a corpse would serialize every control op and retry
  // ladder behind it.
  cv_.wait_for(lock, std::chrono::seconds(30),
               [&] { return members_[rank] != nullptr ||
                            ever_registered_[rank]; });
  return members_[rank];
}

int LocalGroup::Barrier(int64_t tag, int rank, int* lost_rank,
                        const std::function<bool(int)>& suspect) {
  std::unique_lock<std::mutex> lock(mu_);
  BarrierState& b = barriers_[tag];
  b.arrived.insert(rank);
  cv_.notify_all();
  const auto deadline =
      std::chrono::steady_clock::now() + std::chrono::seconds(120);
  int lost = -1;
  bool done = false;
  for (;;) {
    auto it = barriers_.find(tag);
    // Completion wins over abort: once everyone has arrived, the
    // barrier's information is complete and the collective succeeds —
    // including a member that arrived and THEN died or was suspected
    // (its contribution was delivered; the benign staggered-teardown
    // case must not read as a dead fence).
    if (it != barriers_.end() &&
        static_cast<int>(it->second.arrived.size()) >= world_) {
      done = true;
      break;
    }
    // Death poll, NOT-YET-ARRIVED members only: one whose store closed
    // mid-wait (registered then unregistered — bootstrap is not death)
    // can never arrive, and neither can one the caller's detector
    // declared dead.
    const std::set<int>& arr = barriers_[tag].arrived;
    for (int r = 0; r < world_ && lost < 0; ++r) {
      if (arr.count(r)) continue;
      if (ever_registered_[r] && members_[r] == nullptr) lost = r;
      if (lost < 0 && suspect && suspect(r)) lost = r;
    }
    if (lost >= 0) break;
    const auto now = std::chrono::steady_clock::now();
    if (now >= deadline) break;
    const auto slice = std::chrono::milliseconds(50);
    const auto left = deadline - now;
    cv_.wait_for(lock, left < slice ? left : slice);
  }
  if (!done) {
    // Withdraw our arrival — and every DEAD member's: a rolled-back
    // fence re-enters at the SAME tag, and neither a stale live count
    // nor a corpse's arrival from the aborted attempt may satisfy the
    // re-entered barrier (the corpse cannot participate again; its
    // replacement arrives fresh after recovery).
    BarrierState& bw = barriers_[tag];
    bw.arrived.erase(rank);
    for (int r = 0; r < world_; ++r)
      if (ever_registered_[r] && members_[r] == nullptr)
        bw.arrived.erase(r);
    if (bw.left >= static_cast<int>(bw.arrived.size()))
      barriers_.erase(tag);
    if (lost >= 0) {
      if (lost_rank) *lost_rank = lost;
      return kErrPeerLost;
    }
    return kErrTransport;
  }
  // Erase when every CURRENT arrival has left (left == arrived == world
  // in the clean case; with withdrawals, the last leaver of a
  // divergent barrier — some members completed, others aborted — still
  // reclaims the entry instead of leaking it).
  BarrierState& b2 = barriers_[tag];
  ++b2.left;
  if (b2.left >= static_cast<int>(b2.arrived.size()))
    barriers_.erase(tag);
  return kOk;
}

void LocalTransport::Attach(Store* store) { group_->Register(rank_, store); }

LocalTransport::~LocalTransport() { group_->Unregister(rank_); }

int LocalTransport::Barrier(int64_t tag) {
  std::function<bool(int)> oracle;
  {
    std::lock_guard<std::mutex> lock(oracle_mu_);
    oracle = suspect_oracle_;
  }
  std::function<bool(int)> suspect;
  if (oracle)
    // Never self-suspect: our own rank answering its own barrier is
    // definitionally alive.
    suspect = [o = std::move(oracle), me = rank_](int r) {
      return r != me && o(r);
    };
  int lost = -1;
  const int rc = group_->Barrier(tag, rank_, &lost, suspect);
  if (rc == kErrPeerLost) {
    last_lost_peer_.store(lost, std::memory_order_relaxed);
    trace::Ev(trace::kBarrierAbort, rank_, tag, -1, lost);
    trace::Flight(trace::kReasonBarrierAbort, rank_);
  }
  return rc;
}

int LocalTransport::DrawCtrlFault(int target) {
  FaultInjector& fi = FaultInjector::Get();
  if (!fi.enabled()) return kOk;
  const FaultDecision d = fi.DrawCtrl(target);
  switch (d.kind) {
    case FaultKind::kReset:
    case FaultKind::kStall:
    case FaultKind::kConnDrop:
      // No wire to reset (or hard-close) here: all degrade to "this
      // control op transiently failed" — the caller's bounded control
      // retry absorbs it (stall fails WITHOUT sleeping, matching the
      // local data-path convention: there is no client timeout to
      // trip).
      return kErrTransport;
    case FaultKind::kDelay:
      FaultSleepMs(d.param_ms, nullptr);
      return kOk;
    default:
      return kOk;
  }
}

namespace {
// Fault injection for the in-process backend (DDSTORE_FAULT_SPEC): there
// is no wire to reset here, so reset/trunc/stall all degrade to "this
// read transiently failed" (kErrTransport — absorbed by the Store's
// retry layer, since this transport has no internal retry; stall fails
// WITHOUT sleeping — there is no client timeout to trip on the local
// path, and an uninterruptible 2 s sleep would only serialize the
// consumer); delay serves late; corrupt is returned to the CALLER,
// which performs the read and then flips the landed bytes — the local
// analogue of a mangled wire payload (no error fires; only checksum
// verification can notice). One draw per transport call, same
// determinism contract as the TCP serve loop.
int DrawLocalFault(int rank, FaultDecision* corrupt) {
  FaultInjector& fi = FaultInjector::Get();
  if (!fi.enabled()) return kOk;
  const FaultDecision d = fi.Draw(rank);
  switch (d.kind) {
    case FaultKind::kReset:
    case FaultKind::kTrunc:
    case FaultKind::kStall:
      return kErrTransport;
    case FaultKind::kDelay:
      FaultSleepMs(d.param_ms, nullptr);
      break;
    case FaultKind::kCorrupt:
      if (corrupt) *corrupt = d;
      break;
    case FaultKind::kNone:
      break;
  }
  return kOk;
}
}  // namespace

int LocalTransport::Read(int target, const std::string& name, int64_t offset,
                         int64_t nbytes, void* dst) {
  Store* peer = group_->member(target);
  if (!peer) return kErrTransport;
  // Drawn as the TARGET rank: the injected fault models the PEER's serve
  // path failing, matching the TCP side (and the DDSTORE_FAULT_RANKS
  // filter's "inject when these ranks serve" semantics).
  FaultDecision corrupt;
  if (int rc = DrawLocalFault(target, &corrupt)) return rc;
  // ReadLocal holds the peer's read lock across the copy, so a concurrent
  // FreeVar on the peer cannot free the shard mid-read.
  const int rc = peer->ReadLocal(name, offset, nbytes, dst);
  if (rc == kOk && corrupt.kind == FaultKind::kCorrupt)
    CorruptBytes(dst, nbytes, corrupt.h | 1, corrupt.param_ms);
  return rc;
}

int64_t LocalTransport::ReadVarSeq(int target, const std::string& name) {
  // Bounded control retry around the ctrl-domain injector draw (the
  // in-process mirror of the TCP side's ControlRoundTrip contract);
  // -1 ("pull unconditionally") is the safe terminal state.
  for (int att = 0;; ++att) {
    if (DrawCtrlFault(target) == kOk) break;
    if (att >= ctrl_retry_max_) return -1;
  }
  Store* peer = group_->member(target);
  return peer ? peer->UpdateSeqOf(name) : -1;
}

int LocalTransport::ReadRowSums(int target, const std::string& name,
                                int64_t row0, int64_t count,
                                int64_t* seq, uint64_t* sums) {
  for (int att = 0;; ++att) {
    if (DrawCtrlFault(target) == kOk) break;
    if (att >= ctrl_retry_max_) return kErrTransport;
  }
  Store* peer = group_->member(target);
  if (!peer) return kErrTransport;
  return peer->RowSums(name, row0, count, sums, seq);
}

int LocalTransport::SnapshotControl(int target, int64_t snap_id,
                                    bool pin, const std::string& tenant) {
  for (int att = 0;; ++att) {
    if (DrawCtrlFault(target) == kOk) break;
    if (att >= ctrl_retry_max_) return kErrTransport;
  }
  Store* peer = group_->member(target);
  // Registered-then-closed is the bounded "peer is gone" signal (the
  // in-process kill vehicle): classify like the TCP side so a mid-
  // placement death engages SnapshotAcquire's partial-pin unwind with
  // kErrPeerLost, not a generic transport error.
  if (!peer)
    return group_->AliveOrPending(target) ? kErrTransport : kErrPeerLost;
  return pin ? peer->PinSnapshot(snap_id, tenant)
             : peer->UnpinSnapshot(snap_id);
}

int LocalTransport::GatewayControl(int target, int verb,
                                   const std::string& tenant,
                                   int64_t arg, int64_t arg2,
                                   int64_t* token_out) {
  if (verb < 0 || verb > 2) return kErrInvalidArg;
  for (int att = 0;; ++att) {
    if (DrawCtrlFault(target) == kOk) break;
    if (att >= ctrl_retry_max_) return kErrTransport;
  }
  Store* peer = group_->member(target);
  // Same death classification as SnapshotControl: a reaped member is
  // kErrPeerLost, a not-yet-registered one a transient failure.
  if (!peer)
    return group_->AliveOrPending(target) ? kErrTransport : kErrPeerLost;
  if (verb == 1) return peer->GatewayRenew(arg);
  if (verb == 2) return peer->GatewayDetach(arg);
  const int64_t token = peer->GatewayAttach(tenant, arg != 0, arg2);
  if (token < 0) return static_cast<int>(token);
  if (token_out) *token_out = token;
  return kOk;
}

int64_t LocalTransport::ReadMetrics(int target, void* out, int64_t cap) {
  for (int att = 0;; ++att) {
    if (DrawCtrlFault(target) == kOk) break;
    if (att >= ctrl_retry_max_) return kErrTransport;
  }
  Store* peer = group_->member(target);
  // Registered-then-closed is the bounded "peer is gone" signal (the
  // in-process kill vehicle) — classified like the TCP suspect
  // short-circuit so a cluster pull skips the corpse cleanly.
  if (!peer)
    return group_->AliveOrPending(target) ? kErrTransport : kErrPeerLost;
  return peer->MetricsSnapshot(out, cap);
}

int LocalTransport::ReadV(int target, const std::string& name,
                          const ReadOp* ops, int64_t n) {
  // Peer resolution and the registry lookup happen once for the batch
  // (the base-class default would pay both per op).
  Store* peer = group_->member(target);
  if (!peer) return kErrTransport;
  FaultDecision corrupt;
  if (int rc = DrawLocalFault(target, &corrupt)) return rc;
  const int rc = peer->ReadLocalV(name, ops, n);
  if (rc == kOk && corrupt.kind == FaultKind::kCorrupt && n > 0) {
    // One op of the batch gets its landed bytes flipped (deterministic
    // pick): the local-memcpy analogue of a corrupted wire frame.
    const ReadOp& op = ops[corrupt.h % static_cast<uint64_t>(n)];
    CorruptBytes(op.dst, op.nbytes, (corrupt.h >> 8) | 1,
                 corrupt.param_ms);
  }
  return rc;
}

}  // namespace dds
