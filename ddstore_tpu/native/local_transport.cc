#include "local_transport.h"

#include <chrono>
#include <cstring>

namespace dds {

namespace {
std::mutex g_groups_mu;
std::map<std::string, std::shared_ptr<LocalGroup>>* g_groups = nullptr;
}  // namespace

std::shared_ptr<LocalGroup> LocalGroup::GetOrCreate(const std::string& gid,
                                                    int world) {
  std::lock_guard<std::mutex> lock(g_groups_mu);
  if (!g_groups) g_groups = new std::map<std::string, std::shared_ptr<LocalGroup>>();
  auto it = g_groups->find(gid);
  if (it != g_groups->end()) {
    if (it->second->world() != world) return nullptr;
    return it->second;
  }
  auto g = std::make_shared<LocalGroup>(world);
  (*g_groups)[gid] = g;
  return g;
}

void LocalGroup::Release(const std::string& gid) {
  std::lock_guard<std::mutex> lock(g_groups_mu);
  if (g_groups) g_groups->erase(gid);
}

void LocalGroup::Register(int rank, Store* store) {
  std::lock_guard<std::mutex> lock(mu_);
  if (rank >= 0 && rank < world_) {
    members_[rank] = store;
    ever_registered_[rank] = true;
  }
  cv_.notify_all();
}

bool LocalGroup::AliveOrPending(int rank) {
  std::lock_guard<std::mutex> lock(mu_);
  if (rank < 0 || rank >= world_) return false;
  return members_[rank] != nullptr || !ever_registered_[rank];
}

void LocalGroup::Unregister(int rank) {
  std::lock_guard<std::mutex> lock(mu_);
  if (rank >= 0 && rank < world_) members_[rank] = nullptr;
}

Store* LocalGroup::member(int rank) {
  std::unique_lock<std::mutex> lock(mu_);
  if (rank < 0 || rank >= world_) return nullptr;
  // A peer may not have constructed its store yet (threads race at startup);
  // wait briefly for registration.
  cv_.wait_for(lock, std::chrono::seconds(30),
               [&] { return members_[rank] != nullptr; });
  return members_[rank];
}

int LocalGroup::Barrier(int64_t tag) {
  std::unique_lock<std::mutex> lock(mu_);
  BarrierState& b = barriers_[tag];
  ++b.arrived;
  cv_.notify_all();
  bool ok = cv_.wait_for(lock, std::chrono::seconds(120), [&] {
    auto it = barriers_.find(tag);
    return it != barriers_.end() && it->second.arrived >= world_;
  });
  if (!ok) return kErrTransport;
  BarrierState& b2 = barriers_[tag];
  if (++b2.left == world_) barriers_.erase(tag);
  return kOk;
}

void LocalTransport::Attach(Store* store) { group_->Register(rank_, store); }

LocalTransport::~LocalTransport() { group_->Unregister(rank_); }

namespace {
// Fault injection for the in-process backend (DDSTORE_FAULT_SPEC): there
// is no wire to reset here, so reset/trunc/stall all degrade to "this
// read transiently failed" (kErrTransport — absorbed by the Store's
// retry layer, since this transport has no internal retry; stall fails
// WITHOUT sleeping — there is no client timeout to trip on the local
// path, and an uninterruptible 2 s sleep would only serialize the
// consumer); delay serves late; corrupt is returned to the CALLER,
// which performs the read and then flips the landed bytes — the local
// analogue of a mangled wire payload (no error fires; only checksum
// verification can notice). One draw per transport call, same
// determinism contract as the TCP serve loop.
int DrawLocalFault(int rank, FaultDecision* corrupt) {
  FaultInjector& fi = FaultInjector::Get();
  if (!fi.enabled()) return kOk;
  const FaultDecision d = fi.Draw(rank);
  switch (d.kind) {
    case FaultKind::kReset:
    case FaultKind::kTrunc:
    case FaultKind::kStall:
      return kErrTransport;
    case FaultKind::kDelay:
      FaultSleepMs(d.param_ms, nullptr);
      break;
    case FaultKind::kCorrupt:
      if (corrupt) *corrupt = d;
      break;
    case FaultKind::kNone:
      break;
  }
  return kOk;
}
}  // namespace

int LocalTransport::Read(int target, const std::string& name, int64_t offset,
                         int64_t nbytes, void* dst) {
  Store* peer = group_->member(target);
  if (!peer) return kErrTransport;
  // Drawn as the TARGET rank: the injected fault models the PEER's serve
  // path failing, matching the TCP side (and the DDSTORE_FAULT_RANKS
  // filter's "inject when these ranks serve" semantics).
  FaultDecision corrupt;
  if (int rc = DrawLocalFault(target, &corrupt)) return rc;
  // ReadLocal holds the peer's read lock across the copy, so a concurrent
  // FreeVar on the peer cannot free the shard mid-read.
  const int rc = peer->ReadLocal(name, offset, nbytes, dst);
  if (rc == kOk && corrupt.kind == FaultKind::kCorrupt)
    CorruptBytes(dst, nbytes, corrupt.h | 1, corrupt.param_ms);
  return rc;
}

int64_t LocalTransport::ReadVarSeq(int target, const std::string& name) {
  Store* peer = group_->member(target);
  return peer ? peer->UpdateSeqOf(name) : -1;
}

int LocalTransport::ReadRowSums(int target, const std::string& name,
                                int64_t row0, int64_t count,
                                int64_t* seq, uint64_t* sums) {
  Store* peer = group_->member(target);
  if (!peer) return kErrTransport;
  return peer->RowSums(name, row0, count, sums, seq);
}

int LocalTransport::SnapshotControl(int target, int64_t snap_id,
                                    bool pin, const std::string& tenant) {
  Store* peer = group_->member(target);
  if (!peer) return kErrTransport;
  return pin ? peer->PinSnapshot(snap_id, tenant)
             : peer->UnpinSnapshot(snap_id);
}

int LocalTransport::ReadV(int target, const std::string& name,
                          const ReadOp* ops, int64_t n) {
  // Peer resolution and the registry lookup happen once for the batch
  // (the base-class default would pay both per op).
  Store* peer = group_->member(target);
  if (!peer) return kErrTransport;
  FaultDecision corrupt;
  if (int rc = DrawLocalFault(target, &corrupt)) return rc;
  const int rc = peer->ReadLocalV(name, ops, n);
  if (rc == kOk && corrupt.kind == FaultKind::kCorrupt && n > 0) {
    // One op of the batch gets its landed bytes flipped (deterministic
    // pick): the local-memcpy analogue of a corrupted wire frame.
    const ReadOp& op = ops[corrupt.h % static_cast<uint64_t>(n)];
    CorruptBytes(op.dst, op.nbytes, (corrupt.h >> 8) | 1,
                 corrupt.param_ms);
  }
  return rc;
}

}  // namespace dds
