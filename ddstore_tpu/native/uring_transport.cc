// io_uring data plane (see uring_transport.h for the design brief).
//
// Raw syscalls throughout: liburing is NOT a dependency (the container
// ships only kernel headers), so ring setup/mmap layout, SQE filling
// and the enter/reap protocol are done by hand against
// <linux/io_uring.h>. Memory-ordering contract with the kernel: the
// SQ tail and CQ head are published with release stores, the SQ head
// and CQ tail read with acquire loads — single-owner rings need
// nothing stronger.

#include "uring_transport.h"

#include <errno.h>
#include <fcntl.h>
#include <linux/io_uring.h>
#include <linux/time_types.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <sys/uio.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "metrics_hist.h"
#include "trace.h"
#include "wire.h"

namespace dds {
namespace {

using namespace wire;  // NOLINT — shared framing contract (see wire.h)

int uring_setup(unsigned entries, struct io_uring_params* p) {
  return static_cast<int>(::syscall(__NR_io_uring_setup, entries, p));
}
int uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                unsigned flags, const void* arg, size_t argsz) {
  return static_cast<int>(::syscall(__NR_io_uring_enter, fd, to_submit,
                                    min_complete, flags, arg, argsz));
}
int uring_register(int fd, unsigned opcode, const void* arg,
                   unsigned nr_args) {
  return static_cast<int>(
      ::syscall(__NR_io_uring_register, fd, opcode, arg, nr_args));
}

std::string ErrnoStr(int err) {
  char buf[128];
  std::snprintf(buf, sizeof(buf), "%s (errno %d)", ::strerror(err), err);
  return buf;
}

long EnvLongU(const char* name, long dflt) {
  const char* v = ::getenv(name);
  if (!v || !*v) return dflt;
  char* end = nullptr;
  long out = std::strtol(v, &end, 10);
  return (end && *end == '\0') ? out : dflt;
}

int64_t NowMs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

// user_data encoding for transport bursts: kind in the top byte, index
// below. Cold-tier reads use the slice index directly.
constexpr uint64_t kUdSend = 1ULL << 56;
constexpr uint64_t kUdHdr = 2ULL << 56;
constexpr uint64_t kUdPay = 3ULL << 56;
constexpr uint64_t kUdCancel = 4ULL << 56;
constexpr uint64_t kUdKindMask = 0xffULL << 56;
constexpr uint64_t kUdIdxMask = ~kUdKindMask;

// O_DIRECT alignment: 4096 covers every logical block size in the
// field (512 and 4k) AND keeps bounce-slice addresses page-aligned.
constexpr int64_t kDirectAlign = 4096;
constexpr int64_t kBounceBytes = int64_t{4} << 20;

}  // namespace

// ---------------------------------------------------------------------
// Probe

static UringCaps RunProbe() {
  UringCaps caps;
  io_uring_params p;
  std::memset(&p, 0, sizeof(p));
  int fd = uring_setup(8, &p);
  if (fd < 0) {
    caps.reason = "io_uring_setup: " + ErrnoStr(errno);
    return caps;
  }
  caps.features = p.features;
  caps.ext_arg = (p.features & IORING_FEAT_EXT_ARG) != 0;
  // Opcode support table. 256 slots is far past the last opcode any
  // kernel defines; the kernel fills what it knows and sets last_op.
  constexpr unsigned kProbeOps = 256;
  const size_t psz =
      sizeof(io_uring_probe) + kProbeOps * sizeof(io_uring_probe_op);
  std::vector<char> buf(psz, 0);
  auto* probe = reinterpret_cast<io_uring_probe*>(buf.data());
  if (uring_register(fd, IORING_REGISTER_PROBE, probe, kProbeOps) < 0) {
    caps.reason = "IORING_REGISTER_PROBE: " + ErrnoStr(errno);
    ::close(fd);
    return caps;
  }
  ::close(fd);
  auto has = [&](unsigned op) {
    return op <= probe->last_op &&
           (probe->ops[op].flags & IO_URING_OP_SUPPORTED) != 0;
  };
  caps.op_send = has(IORING_OP_SEND);
  caps.op_recv = has(IORING_OP_RECV);
  caps.op_sendmsg = has(IORING_OP_SENDMSG);
  caps.op_recvmsg = has(IORING_OP_RECVMSG);
  caps.op_read = has(IORING_OP_READ);
  caps.op_read_fixed = has(IORING_OP_READ_FIXED);
  std::string missing;
  if (!caps.op_sendmsg) missing += " SENDMSG";
  if (!caps.op_recvmsg) missing += " RECVMSG";
  if (!caps.op_recv) missing += " RECV";
  if (!caps.ext_arg) missing += " FEAT_EXT_ARG";
  if (!missing.empty()) {
    caps.reason = "missing:" + missing;
    return caps;
  }
  caps.supported = true;
  caps.reason = "ok";
  return caps;
}

const UringCaps& ProbeUring() {
  static const UringCaps caps = RunProbe();
  return caps;
}

// ---------------------------------------------------------------------
// SubmissionRing

SubmissionRing::~SubmissionRing() { Destroy(); }

bool SubmissionRing::Init(unsigned depth) {
  io_uring_params p;
  std::memset(&p, 0, sizeof(p));
  int fd = uring_setup(depth, &p);
  if (fd < 0) {
    reason_ = "io_uring_setup: " + ErrnoStr(errno);
    return false;
  }
  sq_entries_ = p.sq_entries;
  cq_entries_ = p.cq_entries;
  ext_arg_ = (p.features & IORING_FEAT_EXT_ARG) != 0;
  sq_ring_sz_ = p.sq_off.array + p.sq_entries * sizeof(unsigned);
  cq_ring_sz_ = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
  const bool single = (p.features & IORING_FEAT_SINGLE_MMAP) != 0;
  if (single) sq_ring_sz_ = cq_ring_sz_ = std::max(sq_ring_sz_, cq_ring_sz_);
  sq_ring_ = ::mmap(nullptr, sq_ring_sz_, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING);
  if (sq_ring_ == MAP_FAILED) {
    reason_ = "mmap sq ring: " + ErrnoStr(errno);
    sq_ring_ = nullptr;
    ::close(fd);
    return false;
  }
  if (single) {
    cq_ring_ = sq_ring_;
  } else {
    cq_ring_ = ::mmap(nullptr, cq_ring_sz_, PROT_READ | PROT_WRITE,
                      MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_CQ_RING);
    if (cq_ring_ == MAP_FAILED) {
      reason_ = "mmap cq ring: " + ErrnoStr(errno);
      ::munmap(sq_ring_, sq_ring_sz_);
      sq_ring_ = cq_ring_ = nullptr;
      ::close(fd);
      return false;
    }
  }
  sqes_sz_ = p.sq_entries * sizeof(io_uring_sqe);
  sqes_ = ::mmap(nullptr, sqes_sz_, PROT_READ | PROT_WRITE,
                 MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQES);
  if (sqes_ == MAP_FAILED) {
    reason_ = "mmap sqes: " + ErrnoStr(errno);
    ::munmap(sq_ring_, sq_ring_sz_);
    if (!single) ::munmap(cq_ring_, cq_ring_sz_);
    sq_ring_ = cq_ring_ = sqes_ = nullptr;
    ::close(fd);
    return false;
  }
  char* sqr = static_cast<char*>(sq_ring_);
  sq_head_ = reinterpret_cast<unsigned*>(sqr + p.sq_off.head);
  sq_tail_ = reinterpret_cast<unsigned*>(sqr + p.sq_off.tail);
  sq_mask_ = reinterpret_cast<unsigned*>(sqr + p.sq_off.ring_mask);
  sq_array_ = reinterpret_cast<unsigned*>(sqr + p.sq_off.array);
  char* cqr = static_cast<char*>(cq_ring_);
  cq_head_ = reinterpret_cast<unsigned*>(cqr + p.cq_off.head);
  cq_tail_ = reinterpret_cast<unsigned*>(cqr + p.cq_off.tail);
  cq_mask_ = reinterpret_cast<unsigned*>(cqr + p.cq_off.ring_mask);
  cqes_ = cqr + p.cq_off.cqes;
  ring_fd_ = fd;
  reason_ = "ok";
  return true;
}

void SubmissionRing::Destroy() {
  if (ring_fd_ < 0) return;
  // Closing the ring fd releases the instance; any still-inflight op is
  // torn down by the kernel's ring teardown (owners drain before
  // destroying precisely so no op can still reference their arenas).
  ::close(ring_fd_);
  ring_fd_ = -1;
  if (sqes_) ::munmap(sqes_, sqes_sz_);
  const bool single = cq_ring_ == sq_ring_;
  if (sq_ring_) ::munmap(sq_ring_, sq_ring_sz_);
  if (!single && cq_ring_) ::munmap(cq_ring_, cq_ring_sz_);
  sq_ring_ = cq_ring_ = sqes_ = nullptr;
  sq_head_ = sq_tail_ = sq_mask_ = sq_array_ = nullptr;
  cq_head_ = cq_tail_ = cq_mask_ = nullptr;
  cqes_ = nullptr;
  prepared_ = 0;
  inflight_ = 0;
}

void* SubmissionRing::sqe_at(unsigned idx) {
  return static_cast<io_uring_sqe*>(sqes_) + idx;
}

bool SubmissionRing::PrepCommon(uint8_t opcode, int fd, const void* addr,
                                uint32_t len, uint64_t off,
                                uint64_t user_data, bool link,
                                uint32_t op_flags, unsigned buf_index) {
  if (ring_fd_ < 0) return false;
  const unsigned tail = *sq_tail_;  // single-owner: plain read is ours
  const unsigned head = __atomic_load_n(sq_head_, __ATOMIC_ACQUIRE);
  if (tail - head >= sq_entries_) return false;  // SQ full
  const unsigned idx = tail & *sq_mask_;
  auto* sqe = static_cast<io_uring_sqe*>(sqe_at(idx));
  std::memset(sqe, 0, sizeof(*sqe));
  sqe->opcode = opcode;
  sqe->fd = fd;
  sqe->addr = reinterpret_cast<uint64_t>(addr);
  sqe->len = len;
  sqe->off = off;
  sqe->user_data = user_data;
  sqe->flags = link ? IOSQE_IO_LINK : 0;
  sqe->msg_flags = op_flags;
  sqe->buf_index = static_cast<uint16_t>(buf_index);
  sq_array_[idx] = idx;
  __atomic_store_n(sq_tail_, tail + 1, __ATOMIC_RELEASE);
  ++prepared_;
  return true;
}

bool SubmissionRing::PrepSendMsg(int fd, const void* msg,
                                 uint64_t user_data, bool link) {
  return PrepCommon(IORING_OP_SENDMSG, fd, msg, 1, 0, user_data, link,
                    MSG_NOSIGNAL, 0);
}

bool SubmissionRing::PrepRecv(int fd, void* buf, size_t len, int flags,
                              uint64_t user_data, bool link) {
  return PrepCommon(IORING_OP_RECV, fd, buf, static_cast<uint32_t>(len),
                    0, user_data, link, static_cast<uint32_t>(flags), 0);
}

bool SubmissionRing::PrepRecvMsg(int fd, void* msg, unsigned msg_flags,
                                 uint64_t user_data, bool link) {
  return PrepCommon(IORING_OP_RECVMSG, fd, msg, 1, 0, user_data, link,
                    msg_flags, 0);
}

bool SubmissionRing::PrepRead(int fd, void* buf, size_t len, uint64_t off,
                              uint64_t user_data, bool link) {
  return PrepCommon(IORING_OP_READ, fd, buf, static_cast<uint32_t>(len),
                    off, user_data, link, 0, 0);
}

bool SubmissionRing::PrepReadFixed(int fd, void* buf, size_t len,
                                   uint64_t off, unsigned buf_index,
                                   uint64_t user_data, bool link) {
  return PrepCommon(IORING_OP_READ_FIXED, fd, buf,
                    static_cast<uint32_t>(len), off, user_data, link, 0,
                    buf_index);
}

bool SubmissionRing::PrepCancel(uint64_t target_user_data,
                                uint64_t user_data) {
  return PrepCommon(IORING_OP_ASYNC_CANCEL, -1,
                    reinterpret_cast<const void*>(target_user_data), 0, 0,
                    user_data, false, 0, 0);
}

void SubmissionRing::AbandonPrepared() {
  if (ring_fd_ < 0 || prepared_ == 0) return;
  __atomic_store_n(sq_tail_, *sq_tail_ - prepared_, __ATOMIC_RELEASE);
  prepared_ = 0;
}

bool SubmissionRing::RegisterBuffers(const void* const* bases,
                                     const size_t* lens, unsigned n) {
  if (ring_fd_ < 0) return false;
  std::vector<iovec> iovs(n);
  for (unsigned i = 0; i < n; ++i)
    iovs[i] = iovec{const_cast<void*>(bases[i]), lens[i]};
  return uring_register(ring_fd_, IORING_REGISTER_BUFFERS, iovs.data(),
                        n) == 0;
}

int SubmissionRing::SubmitAndWait(unsigned wait_nr, int timeout_ms) {
  if (ring_fd_ < 0) return -EBADF;
  const unsigned to_submit = prepared_;
  unsigned flags = 0;
  const void* argp = nullptr;
  size_t argsz = 0;
  struct __kernel_timespec ts;
  io_uring_getevents_arg arg;
  if (wait_nr > 0) {
    flags |= IORING_ENTER_GETEVENTS;
    if (timeout_ms >= 0 && ext_arg_) {
      ts.tv_sec = timeout_ms / 1000;
      ts.tv_nsec = static_cast<long long>(timeout_ms % 1000) * 1000000;
      std::memset(&arg, 0, sizeof(arg));
      arg.ts = reinterpret_cast<uint64_t>(&ts);
      flags |= IORING_ENTER_EXT_ARG;
      argp = &arg;
      argsz = sizeof(arg);
    }
  }
  int rc = uring_enter(ring_fd_, to_submit, wait_nr, flags, argp, argsz);
  if (rc < 0) return -errno;  // -ETIME = wait timed out, nothing new
  prepared_ -= static_cast<unsigned>(rc);
  inflight_ += rc;
  return rc;
}

int SubmissionRing::ReapCompletions(std::vector<Completion>* out) {
  if (ring_fd_ < 0) return 0;
  unsigned head = *cq_head_;
  const unsigned tail = __atomic_load_n(cq_tail_, __ATOMIC_ACQUIRE);
  int nr = 0;
  while (head != tail) {
    const auto* cqe =
        static_cast<const io_uring_cqe*>(cqes_) + (head & *cq_mask_);
    out->push_back(Completion{cqe->user_data, cqe->res});
    ++head;
    ++nr;
  }
  __atomic_store_n(cq_head_, head, __ATOMIC_RELEASE);
  inflight_ -= nr;
  return nr;
}

// ---------------------------------------------------------------------
// ColdDirectReader

ColdDirectReader::ColdDirectReader() = default;

ColdDirectReader::~ColdDirectReader() {
  std::lock_guard<std::mutex> lock(mu_);
  for (auto& kv : fds_) ::close(kv.second);
  fds_.clear();
  ring_.reset();
  if (bounce_) ::free(bounce_);
  bounce_ = nullptr;
}

bool ColdDirectReader::AddFile(const std::string& name,
                               const std::string& path) {
  int fd = ::open(path.c_str(), O_RDONLY | O_DIRECT | O_CLOEXEC);
  if (fd < 0) return false;  // fs refuses O_DIRECT: var stays on mmap
  std::lock_guard<std::mutex> lock(mu_);
  auto it = fds_.find(name);
  if (it != fds_.end()) ::close(it->second);
  fds_[name] = fd;
  return true;
}

void ColdDirectReader::DropFile(const std::string& name) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = fds_.find(name);
  if (it == fds_.end()) return;
  ::close(it->second);
  fds_.erase(it);
}

bool ColdDirectReader::HasFile(const std::string& name) const {
  std::lock_guard<std::mutex> lock(mu_);
  return fds_.count(name) != 0;
}

bool ColdDirectReader::EnsureRing() {
  if (ring_ && ring_->ok()) return true;
  if (ring_failed_) return false;
  if (!ProbeUring().supported || !ProbeUring().op_read) {
    ring_failed_ = true;
    return false;
  }
  void* mem = nullptr;
  if (::posix_memalign(&mem, kDirectAlign, kBounceBytes) != 0) {
    ring_failed_ = true;
    return false;
  }
  bounce_ = static_cast<char*>(mem);
  ring_.reset(new SubmissionRing());
  if (!ring_->Init(64)) {
    ring_.reset();
    ::free(bounce_);
    bounce_ = nullptr;
    ring_failed_ = true;
    return false;
  }
  // Registered bounce buffer -> READ_FIXED skips the per-op pin/unpin
  // (DDSTORE_URING_REGBUF=0 opts out; refusal — e.g. RLIMIT_MEMLOCK —
  // silently keeps plain READ).
  if (EnvLongU("DDSTORE_URING_REGBUF", 1) != 0 &&
      ProbeUring().op_read_fixed) {
    const void* base = bounce_;
    const size_t len = static_cast<size_t>(kBounceBytes);
    regbuf_ = ring_->RegisterBuffers(&base, &len, 1);
  }
  return true;
}

bool ColdDirectReader::Read(const std::string& name, int64_t offset,
                            int64_t nbytes, void* dst) {
  CdOp op{offset, nbytes, dst};
  return ReadBatch(name, &op, 1);
}

bool ColdDirectReader::ReadBatch(const std::string& name, const CdOp* ops,
                                 int n) {
  if (n <= 0) return true;
  std::lock_guard<std::mutex> lock(mu_);
  auto it = fds_.find(name);
  if (it == fds_.end()) return false;
  if (!EnsureRing()) {
    fallbacks_.fetch_add(1, std::memory_order_relaxed);
    return false;
  }
  const int fd = it->second;
  const int timeout_ms =
      static_cast<int>(EnvLongU("DDSTORE_READ_TIMEOUT_S", 300)) * 1000;
  struct Slice {
    int64_t a_off;   // aligned file offset
    int64_t span;    // aligned read length
    int64_t need;    // bytes from a_off that must land (EOF-aware)
    char* buf;
    const CdOp* op;
  };
  std::vector<Slice> slices;
  std::vector<SubmissionRing::Completion> cqes;
  int64_t total = 0;
  int i = 0;
  while (i < n) {
    // Pack as many ops as fit the bounce buffer (and the ring) into ONE
    // submission of independent (unlinked) READs.
    slices.clear();
    int64_t used = 0;
    int j = i;
    while (j < n &&
           slices.size() + 1 < static_cast<size_t>(ring_->depth())) {
      const CdOp& op = ops[j];
      if (op.nbytes < 0 || op.offset < 0) {
        fallbacks_.fetch_add(1, std::memory_order_relaxed);
        return false;
      }
      if (op.nbytes == 0) {  // nothing to read; no slice
        ++j;
        continue;
      }
      const int64_t a_off = op.offset & ~(kDirectAlign - 1);
      const int64_t a_end =
          (op.offset + op.nbytes + kDirectAlign - 1) & ~(kDirectAlign - 1);
      const int64_t span = a_end - a_off;
      if (span > kBounceBytes) {
        // One op bigger than the bounce window: serve the whole batch
        // from the mmap (no partial application).
        fallbacks_.fetch_add(1, std::memory_order_relaxed);
        return false;
      }
      if (used + span > kBounceBytes) break;
      slices.push_back(Slice{a_off, span,
                             op.offset + op.nbytes - a_off,
                             bounce_ + used, &ops[j]});
      used += span;
      ++j;
    }
    if (slices.empty()) {
      i = j;  // trailing zero-byte ops
      continue;
    }
    for (size_t s = 0; s < slices.size(); ++s) {
      const Slice& sl = slices[s];
      const bool ok =
          regbuf_
              ? ring_->PrepReadFixed(fd, sl.buf,
                                     static_cast<size_t>(sl.span),
                                     static_cast<uint64_t>(sl.a_off), 0,
                                     s, false)
              : ring_->PrepRead(fd, sl.buf, static_cast<size_t>(sl.span),
                                static_cast<uint64_t>(sl.a_off), s,
                                false);
      if (!ok) {
        fallbacks_.fetch_add(1, std::memory_order_relaxed);
        return false;
      }
    }
    // One io_uring_enter serves the whole slice group.
    unsigned pending = static_cast<unsigned>(slices.size());
    const int64_t deadline = NowMs() + timeout_ms;
    while (pending > 0) {
      int rc = ring_->SubmitAndWait(pending, timeout_ms);
      if (rc < 0 && rc != -EINTR) break;
      cqes.clear();
      ring_->ReapCompletions(&cqes);
      for (const auto& cqe : cqes) {
        --pending;
        const Slice& sl = slices[static_cast<size_t>(cqe.user_data)];
        // Short read past EOF is fine as long as the needed span
        // landed; anything else poisons the group.
        if (cqe.res < 0 || cqe.res < sl.need) {
          fallbacks_.fetch_add(1, std::memory_order_relaxed);
          // Drain stragglers before the arenas can go away.
          while (pending > 0) {
            if (ring_->SubmitAndWait(pending, 2000) < 0) break;
            cqes.clear();
            pending -= static_cast<unsigned>(
                std::min<int64_t>(pending,
                                  ring_->ReapCompletions(&cqes)));
            if (NowMs() > deadline) break;
          }
          if (pending > 0) {
            // Undrainable inflight read: never let it scribble a freed
            // bounce buffer — retire the ring (teardown cancels it).
            ring_.reset();
            ring_failed_ = true;
          }
          return false;
        }
      }
      if (pending > 0 && NowMs() > deadline) {
        ring_.reset();
        ring_failed_ = true;
        fallbacks_.fetch_add(1, std::memory_order_relaxed);
        return false;
      }
    }
    for (const Slice& sl : slices) {
      std::memcpy(sl.op->dst, sl.buf + (sl.op->offset - sl.a_off),
                  static_cast<size_t>(sl.op->nbytes));
      total += sl.op->nbytes;
    }
    i = j;
  }
  reads_.fetch_add(n, std::memory_order_relaxed);
  bytes_.fetch_add(total, std::memory_order_relaxed);
  return true;
}

void ColdDirectReader::Stats(int64_t out[6]) const {
  std::lock_guard<std::mutex> lock(mu_);
  out[0] = static_cast<int64_t>(fds_.size());
  out[1] = reads_.load(std::memory_order_relaxed);
  out[2] = bytes_.load(std::memory_order_relaxed);
  out[3] = fallbacks_.load(std::memory_order_relaxed);
  out[4] = regbuf_ ? 1 : 0;
  out[5] = (ring_ && ring_->ok()) ? 1 : 0;
}

// ---------------------------------------------------------------------
// UringTransport

UringTransport::UringTransport(int rank, int world, int port)
    : TcpTransport(rank, world, port) {
  const UringCaps& caps = ProbeUring();
  engaged_ = caps.supported;
  reason_ = caps.reason;
  // Floor 64: the worst single frame costs 1 send + 1 hdr +
  // ceil(kVecMaxOps/kIovMax)=8 payload SQEs, and the burst budget
  // below reserves slack on top.
  depth_ = static_cast<unsigned>(std::min<long>(
      std::max<long>(EnvLongU("DDSTORE_URING_DEPTH", 256), 64), 4096));
  enter_timeout_ms_ =
      static_cast<int>(EnvLongU("DDSTORE_READ_TIMEOUT_S", 300)) * 1000;
  if (!engaged_) {
    // The LOUD fallback the probe contract demands: the transport keeps
    // working (inherited TCP path), but nobody should discover that
    // from a bench number — the verdict is printed once and exported
    // through dds_uring_state/dds_uring_reason.
    std::fprintf(stderr,
                 "[ddstore] DDSTORE_TRANSPORT=uring requested but "
                 "io_uring is unavailable on this kernel (%s); rank %d "
                 "serving every read via the TCP wire path\n",
                 reason_.c_str(), rank);
  }
}

UringTransport::~UringTransport() {
  // Base ~TcpTransport joins the serving threads and closes every lane
  // BEFORE members of this subclass are destroyed — but lane rings hold
  // no reference to arenas by now (every ReadVOn drains its burst
  // before returning), so destruction order is safe either way.
}

void UringTransport::UringCounters(int64_t out[7]) const {
  out[0] = engaged_ ? 1 : 0;
  out[1] = bursts_.load(std::memory_order_relaxed);
  out[2] = enters_.load(std::memory_order_relaxed);
  out[3] = sqes_.load(std::memory_order_relaxed);
  out[4] = frames_.load(std::memory_order_relaxed);
  out[5] = fallbacks_.load(std::memory_order_relaxed);
  out[6] = ring_errors_.load(std::memory_order_relaxed);
}

int UringTransport::WireRouteLabel() const {
  return engaged_ ? metrics::kRouteUring : metrics::kRouteTcp;
}

SubmissionRing* UringTransport::LaneRing(Conn* c) {
  std::lock_guard<std::mutex> lock(rings_mu_);
  auto it = rings_.find(c);
  if (it != rings_.end()) return it->second->ok() ? it->second.get()
                                                  : nullptr;
  auto ring = std::unique_ptr<SubmissionRing>(new SubmissionRing());
  if (!ring->Init(depth_)) {
    ring_errors_.fetch_add(1, std::memory_order_relaxed);
    rings_.emplace(c, std::move(ring));  // cache the refusal
    return nullptr;
  }
  SubmissionRing* out = ring.get();
  rings_.emplace(c, std::move(ring));
  return out;
}

void UringTransport::DropLaneRing(Conn* c) {
  std::lock_guard<std::mutex> lock(rings_mu_);
  rings_.erase(c);
}

int UringTransport::ReadVOn(Peer& p, Conn& c, const std::string& name,
                            const ReadOp* ops, int64_t n) {
  if (!engaged_) return TcpTransport::ReadVOn(p, c, name, ops, n);
  SubmissionRing* ring = LaneRing(&c);
  if (ring == nullptr) {
    fallbacks_.fetch_add(1, std::memory_order_relaxed);
    return TcpTransport::ReadVOn(p, c, name, ops, n);
  }
  std::lock_guard<std::mutex> lock(c.mu);
  int rc = EnsureConnected(p, c);
  if (rc != kOk) return rc;
  return UringReadVLocked(p, c, *ring, name, ops, n);
}

int UringTransport::UringReadVLocked(Peer& p, Conn& c,
                                     SubmissionRing& ring,
                                     const std::string& name,
                                     const ReadOp* ops, int64_t n) {
  (void)p;
  // -- Framing: the EXACT plan TcpTransport::ReadVOn computes (wire.h
  // contract). Identical frames mean an identical byte stream on the
  // wire — which is what keeps the server-side seeded fault-draw
  // schedule, the trace tag plumbing and mixed-fleet interop unchanged.
  const int64_t tspan = static_cast<int64_t>(trace::CurrentSpan());
  struct Frame {
    int64_t begin, end, bytes, req_bytes;
  };
  std::vector<Frame> frames;
  for (int64_t i = 0; i < n;) {
    int64_t j = i, bytes = 0;
    while (j < n && j - i < kVecMaxOps &&
           bytes + ops[j].nbytes <= (ops[j].nbytes < kPackBytes
                                         ? kScatterFrameBytes
                                         : kVecMaxBytes)) {
      bytes += ops[j].nbytes;
      ++j;
    }
    if (j == i) {  // single op over the byte cap
      bytes = ops[i].nbytes;
      j = i + 1;
    }
    const int64_t req_bytes = static_cast<int64_t>(sizeof(WireReq)) +
                              static_cast<int64_t>(name.size()) +
                              (j - i > 1 ? (j - i) * 16 : 0);
    frames.push_back(Frame{i, j, bytes, req_bytes});
    i = j;
  }
  const int64_t nframes = static_cast<int64_t>(frames.size());
  std::vector<WireReq> hdrs(static_cast<size_t>(nframes));
  std::vector<int64_t> all_ops(static_cast<size_t>(n) * 2);
  for (int64_t k = 0; k < n; ++k) {
    all_ops[2 * k] = ops[k].offset;
    all_ops[2 * k + 1] = ops[k].nbytes;
  }
  for (int64_t f = 0; f < nframes; ++f) {
    const Frame& fr = frames[f];
    const int64_t fn = fr.end - fr.begin;
    if (fn == 1)
      hdrs[static_cast<size_t>(f)] =
          WireReq{kMagic, kOpRead,
                  rank_,  static_cast<uint32_t>(name.size()),
                  ops[fr.begin].offset, ops[fr.begin].nbytes,
                  tspan};
    else
      hdrs[static_cast<size_t>(f)] =
          WireReq{kMagic, kOpReadVec,
                  rank_,  static_cast<uint32_t>(name.size()),
                  fn,     fr.bytes,
                  tspan};
  }
  std::vector<WireResp> resps(static_cast<size_t>(nframes));

  // Per-burst arenas. Sized exactly before any SQE is prepped and
  // never grown afterwards: the kernel snapshots msghdr/iovec arrays
  // at submission, but the pack staging and response headers are live
  // until the CQE lands — a reallocation mid-flight would be a
  // use-after-free. Declared outside the burst loop purely for reuse.
  std::vector<iovec> req_iovs;
  msghdr req_msg;
  std::vector<char> pack;
  std::vector<iovec> pay_iovs;
  std::vector<msghdr> pay_msgs;
  struct Chunk {  // one RECVMSG SQE worth of payload
    int64_t bytes;
  };
  std::vector<Chunk> chunks;
  struct Fixup {
    char* src;
    void* dst;
    int64_t nbytes;
  };
  std::vector<Fixup> fixups;
  std::vector<size_t> frame_fix_begin, frame_fix_end;
  std::vector<SubmissionRing::Completion> cqes;

  int64_t done = 0;
  while (done < nframes) {
    // ---- Plan the burst [done, burst_end): every frame costs one
    // header-recv SQE plus ceil(scatter iovecs / kIovMax) payload
    // recvs; the whole burst's requests ride ONE sendmsg SQE. Budget
    // against the ring (slack for short-send continuations + cancels).
    const unsigned budget = ring.depth() - 8;
    // Request-side cap: the burst's gather list rides one sendmsg (≤ 3
    // iovecs per frame), which the kernel bounds at UIO_MAXIOV entries.
    const int64_t max_burst_frames =
        static_cast<int64_t>(kIovMax / 3) - 1;
    int64_t burst_end = done;
    size_t est_sqes = 1;       // the request sendmsg
    size_t est_iovs = 0, est_pack = 0, est_chunks = 0, est_req_iovs = 0;
    while (burst_end < nframes && burst_end - done < max_burst_frames) {
      const Frame& fr = frames[burst_end];
      // Count scatter iovecs after pack-merging (consecutive small ops
      // share one staging iovec) — the same walk the fill pass does.
      size_t iovn = 0, packb = 0;
      bool prev_packed = false;
      for (int64_t k = fr.begin; k < fr.end; ++k) {
        if (ops[k].nbytes <= 0) continue;
        if (ops[k].nbytes < kPackBytes) {
          if (!prev_packed) ++iovn;
          packb += static_cast<size_t>(ops[k].nbytes);
          prev_packed = true;
        } else {
          ++iovn;
          prev_packed = false;
        }
      }
      const size_t nchunks =
          fr.bytes > 0 ? (iovn + kIovMax - 1) / kIovMax : 0;
      const size_t cost = 1 + nchunks;
      if (burst_end > done && est_sqes + cost > budget) break;
      est_sqes += cost;
      est_iovs += iovn;
      est_pack += packb;
      est_chunks += nchunks;
      est_req_iovs += 3;
      ++burst_end;
      if (est_sqes >= budget) break;
    }
    const int64_t bn = burst_end - done;

    // ---- Fill arenas (exact reservations; no growth past this point).
    req_iovs.clear();
    req_iovs.reserve(est_req_iovs);
    if (pack.size() < est_pack) pack.resize(est_pack);
    pay_iovs.clear();
    pay_iovs.reserve(est_iovs);
    pay_msgs.clear();
    pay_msgs.reserve(est_chunks);
    chunks.clear();
    chunks.reserve(est_chunks);
    fixups.clear();
    frame_fix_begin.assign(static_cast<size_t>(bn), 0);
    frame_fix_end.assign(static_cast<size_t>(bn), 0);
    struct FrameChunks {
      size_t first_chunk = 0, nchunks = 0;
      bool hdr_done = false;
    };
    std::vector<FrameChunks> fcs(static_cast<size_t>(bn));
    int64_t req_total = 0;
    char* sp = pack.data();
    for (int64_t bf = 0; bf < bn; ++bf) {
      const int64_t f = done + bf;
      const Frame& fr = frames[f];
      req_iovs.push_back(iovec{&hdrs[static_cast<size_t>(f)],
                               sizeof(WireReq)});
      req_iovs.push_back(
          iovec{const_cast<char*>(name.data()), name.size()});
      if (fr.end - fr.begin > 1)
        req_iovs.push_back(
            iovec{&all_ops[static_cast<size_t>(2 * fr.begin)],
                  static_cast<size_t>(fr.end - fr.begin) * 16});
      req_total += fr.req_bytes;
      // Scatter plan (pack/fixup scheme identical to the TCP path).
      fcs[static_cast<size_t>(bf)].first_chunk = chunks.size();
      frame_fix_begin[static_cast<size_t>(bf)] = fixups.size();
      const size_t iov_start = pay_iovs.size();
      bool prev_packed = false;
      for (int64_t k = fr.begin; k < fr.end; ++k) {
        const ReadOp& op = ops[k];
        if (op.nbytes <= 0) continue;
        if (op.nbytes < kPackBytes) {
          fixups.push_back(Fixup{sp, op.dst, op.nbytes});
          if (prev_packed)
            pay_iovs.back().iov_len += static_cast<size_t>(op.nbytes);
          else
            pay_iovs.push_back(iovec{sp, static_cast<size_t>(op.nbytes)});
          sp += op.nbytes;
          prev_packed = true;
        } else {
          pay_iovs.push_back(
              iovec{op.dst, static_cast<size_t>(op.nbytes)});
          prev_packed = false;
        }
      }
      frame_fix_end[static_cast<size_t>(bf)] = fixups.size();
      // Chunk the frame's iovecs at kIovMax per RECVMSG.
      size_t off = iov_start;
      while (off < pay_iovs.size()) {
        const size_t cnt = std::min(kIovMax, pay_iovs.size() - off);
        msghdr mh;
        std::memset(&mh, 0, sizeof(mh));
        mh.msg_iov = pay_iovs.data() + off;
        mh.msg_iovlen = cnt;
        pay_msgs.push_back(mh);
        int64_t cb = 0;
        for (size_t q = off; q < off + cnt; ++q)
          cb += static_cast<int64_t>(pay_iovs[q].iov_len);
        chunks.push_back(Chunk{cb});
        ++fcs[static_cast<size_t>(bf)].nchunks;
        off += cnt;
      }
    }
    std::memset(&req_msg, 0, sizeof(req_msg));
    req_msg.msg_iov = req_iovs.data();
    req_msg.msg_iovlen = req_iovs.size();

    // ---- Prep: one unlinked sendmsg (its own chain), then the recv
    // chain hdr0 -> pay0... -> hdrN -> payN. Two independent chains —
    // linking recvs behind the send would serialize the whole exchange
    // and deadlock once both sides block in send; linking ALL recvs
    // serializes them on the fd so async workers cannot interleave the
    // stream.
    bool prep_ok = ring.PrepSendMsg(c.fd, &req_msg, kUdSend, false);
    for (int64_t bf = 0; prep_ok && bf < bn; ++bf) {
      const int64_t f = done + bf;
      const FrameChunks& fc = fcs[static_cast<size_t>(bf)];
      const bool last_sqe = (bf == bn - 1) && fc.nchunks == 0;
      prep_ok = ring.PrepRecv(c.fd, &resps[static_cast<size_t>(f)],
                              sizeof(WireResp), MSG_WAITALL,
                              kUdHdr | static_cast<uint64_t>(bf),
                              !last_sqe);
      for (size_t q = 0; prep_ok && q < fc.nchunks; ++q) {
        const size_t ci = fc.first_chunk + q;
        const bool last =
            (bf == bn - 1) && (q == fc.nchunks - 1);
        prep_ok = ring.PrepRecvMsg(c.fd, &pay_msgs[ci], MSG_WAITALL,
                                   kUdPay | static_cast<uint64_t>(ci),
                                   !last);
      }
    }
    // ---- Submit + reap. Happy path: ONE io_uring_enter submits the
    // whole burst and waits for every completion (the short re-poll
    // below only triggers on bursts that outlive the poll quantum).
    sqes_.fetch_add(static_cast<int64_t>(est_sqes),
                    std::memory_order_relaxed);
    unsigned pending = prep_ok ? 1 : 0;  // the request sendmsg
    if (prep_ok)
      for (int64_t bf = 0; bf < bn; ++bf)
        pending += 1 + static_cast<unsigned>(
                           fcs[static_cast<size_t>(bf)].nchunks);
    int64_t send_done_bytes = 0;
    size_t send_iov_off = 0;  // first request iovec not fully sent
    bool err = !prep_ok;      // SQ unexpectedly full = budget bug
    if (err) ring_errors_.fetch_add(1, std::memory_order_relaxed);
    const int64_t deadline = NowMs() + enter_timeout_ms_;
    // Poll quantum: waiting for ALL completions in one enter is the
    // fast path, but a server-reported error frame starves the recv
    // chain (the server sends no payload for it, so the chain waits on
    // bytes that never come) — re-examine completed headers every
    // quantum so a fatal status surfaces in ~50 ms, not at the read
    // deadline, mirroring the TCP loop's immediate error return.
    constexpr int kPollMs = 50;
    while (!err && pending > 0) {
      const int64_t left = deadline - NowMs();
      if (left <= 0) {
        err = true;
        break;
      }
      const int rc = ring.SubmitAndWait(
          pending,
          static_cast<int>(std::min<int64_t>(left, kPollMs)));
      enters_.fetch_add(1, std::memory_order_relaxed);
      if (rc < 0 && rc != -EINTR && rc != -ETIME) {
        err = true;
        break;
      }
      cqes.clear();
      ring.ReapCompletions(&cqes);
      for (const auto& cqe : cqes) {
        --pending;
        const uint64_t kind = cqe.user_data & kUdKindMask;
        const uint64_t idx = cqe.user_data & kUdIdxMask;
        if (kind == kUdSend) {
          if (cqe.res <= 0) {
            err = true;
            continue;
          }
          send_done_bytes += cqe.res;
          if (send_done_bytes < req_total) {
            // Short send (socket buffer full at the nonblocking
            // attempt): advance the gather list past the sent bytes
            // and submit a continuation. Only ever ONE send is
            // outstanding, so request bytes stay in order.
            int64_t adv = cqe.res;
            while (adv > 0 && send_iov_off < req_iovs.size()) {
              iovec& v = req_iovs[send_iov_off];
              if (static_cast<int64_t>(v.iov_len) <= adv) {
                adv -= static_cast<int64_t>(v.iov_len);
                ++send_iov_off;
              } else {
                v.iov_base = static_cast<char*>(v.iov_base) + adv;
                v.iov_len -= static_cast<size_t>(adv);
                adv = 0;
              }
            }
            req_msg.msg_iov = req_iovs.data() + send_iov_off;
            req_msg.msg_iovlen = req_iovs.size() - send_iov_off;
            if (!ring.PrepSendMsg(c.fd, &req_msg, kUdSend, false)) {
              err = true;
              continue;
            }
            ++pending;
          }
        } else if (kind == kUdHdr) {
          if (cqe.res != static_cast<int32_t>(sizeof(WireResp)))
            err = true;
          else
            fcs[idx].hdr_done = true;
        } else if (kind == kUdPay) {
          if (cqe.res < 0 ||
              static_cast<int64_t>(cqe.res) != chunks[idx].bytes)
            err = true;
        }
      }
      // A completed header carrying a server error means the rest of
      // the chain may never be fed — bail out NOW with that status.
      for (int64_t bf = 0; !err && bf < bn; ++bf)
        if (fcs[static_cast<size_t>(bf)].hdr_done &&
            resps[static_cast<size_t>(done + bf)].status != kOk)
          err = true;
    }

    if (err || pending > 0) {
      // Failure path, ticket hygiene first: discard anything staged
      // but never submitted (a mid-prep failure's SQEs reference
      // arenas about to die), wake every blocked socket op (shutdown
      // completes them fast), cancel + drain until no submitted SQE
      // can still reference this stack's arenas, then reset the
      // connection exactly like the TCP fail() contract.
      if (!prep_ok) ring.AbandonPrepared();
      ::shutdown(c.fd, SHUT_RDWR);
      const int64_t drain_deadline = NowMs() + 10000;
      bool cancels_sent = false;
      while (pending > 0 && NowMs() < drain_deadline) {
        if (!cancels_sent) {
          // Best-effort cancels (a poll-armed op does not wake on
          // shutdown alone on every kernel). Cancel CQEs are extra
          // completions on top of `pending`, accounted below by kind.
          for (int64_t bf = 0; bf < bn; ++bf)
            if (ring.PrepCancel(kUdHdr | static_cast<uint64_t>(bf),
                                kUdCancel))
              cancels_sent = true;
        }
        const int rc = ring.SubmitAndWait(1, 500);
        if (rc < 0 && rc != -EINTR && rc != -ETIME) break;
        cqes.clear();
        ring.ReapCompletions(&cqes);
        for (const auto& cqe : cqes) {
          const uint64_t kind = cqe.user_data & kUdKindMask;
          if (kind == kUdCancel) continue;
          if (pending > 0) --pending;
          if (kind == kUdHdr &&
              cqe.res == static_cast<int32_t>(sizeof(WireResp)))
            fcs[cqe.user_data & kUdIdxMask].hdr_done = true;
        }
      }
      if (pending > 0) {
        // Could not prove quiescence: retire the whole ring — teardown
        // cancels stragglers in the kernel — so no completion can
        // touch the arenas after this frame returns.
        DropLaneRing(&c);
      }
      // First server-reported bad status (in frame order) outranks the
      // transport verdict — mirrors the TCP loop, which returns the
      // status of the first error frame it reads.
      int status = kErrTransport;
      for (int64_t bf = 0; bf < bn; ++bf) {
        const auto& fc = fcs[static_cast<size_t>(bf)];
        const WireResp& r = resps[static_cast<size_t>(done + bf)];
        if (fc.hdr_done && r.status != kOk) {
          status = r.status;
          break;
        }
      }
      trace::Ev(trace::kLaneClose, rank_, c.idx, kErrTransport, 0);
      ::close(c.fd);
      c.fd = -1;
      return status;
    }

    // ---- Validate + land the burst, strictly in frame order (the
    // first bad status wins, like the TCP loop).
    for (int64_t bf = 0; bf < bn; ++bf) {
      const int64_t f = done + bf;
      const Frame& fr = frames[f];
      const WireResp& r = resps[static_cast<size_t>(f)];
      if (r.status != kOk || r.nbytes != fr.bytes) {
        const int status = r.status != kOk ? r.status : kErrTransport;
        trace::Ev(trace::kLaneClose, rank_, c.idx, kErrTransport, 0);
        ::close(c.fd);
        c.fd = -1;
        return status;
      }
      for (size_t x = frame_fix_begin[static_cast<size_t>(bf)];
           x < frame_fix_end[static_cast<size_t>(bf)]; ++x)
        std::memcpy(fixups[x].dst, fixups[x].src,
                    static_cast<size_t>(fixups[x].nbytes));
      if (fr.bytes > 0)
        c.bytes.fetch_add(fr.bytes, std::memory_order_relaxed);
    }
    frames_.fetch_add(bn, std::memory_order_relaxed);
    bursts_.fetch_add(1, std::memory_order_relaxed);
    done = burst_end;
  }
  return kOk;
}

}  // namespace dds
