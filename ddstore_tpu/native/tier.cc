#include "tier.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstring>

namespace dds {
namespace tier {

void HotRowCache::Configure(int64_t max_bytes) {
  if (max_bytes < 0) return;
  max_bytes_.store(max_bytes, std::memory_order_relaxed);
}

std::shared_ptr<Entry> HotRowCache::Begin(const std::string& name,
                                          const int64_t* rows, int64_t n,
                                          int64_t row_bytes,
                                          int64_t window,
                                          const std::string& tenant,
                                          int64_t quota_charged) {
  const int64_t cap = max_bytes_.load(std::memory_order_relaxed);
  if (cap <= 0 || !rows || n <= 0 || row_bytes <= 0) return nullptr;
  // The serve-side density check binary-searches the row list: an
  // unsorted (or duplicated) list would let it certify a run whose
  // middle rows are NOT present — wrong bytes served. Refuse instead
  // (the window planner always hands sorted-unique rows).
  for (int64_t i = 1; i < n; ++i)
    if (rows[i] <= rows[i - 1]) return nullptr;
  const int64_t bytes = n * row_bytes;
  // Build (and allocate) OUTSIDE the lock: a multi-MB window buffer's
  // first-touch must not serialize concurrent serves. A refusal below
  // just drops the entry (and its buffer) on the floor.
  auto e = std::make_shared<Entry>();
  e->name = name;
  e->window = window;
  e->row_bytes = row_bytes;
  e->rows.assign(rows, rows + n);
  // Quota fields armed BEFORE publication: an evict racing the
  // prefetch releases the charge through the entry it removed.
  e->tenant = tenant;
  e->quota_charged = quota_charged;
  if (quota_charged > 0)
    e->quota_live.store(true, std::memory_order_release);
  e->buf.reset(new (std::nothrow) char[static_cast<size_t>(bytes)]);
  if (!e->buf) {
    cnt_.over_budget.fetch_add(1, std::memory_order_relaxed);
    return nullptr;
  }
  const auto key = std::make_pair(name, window);
  {
    std::lock_guard<std::mutex> lock(mu_);
    if (entries_.count(key)) return nullptr;  // already warmed: no-op
    if (charged_ + bytes > cap) {
      cnt_.over_budget.fetch_add(1, std::memory_order_relaxed);
      return nullptr;
    }
    charged_ += bytes;
    entries_.emplace(key, e);
  }
  return e;
}

void HotRowCache::RemoveLocked(
    std::map<std::pair<std::string, int64_t>,
             std::shared_ptr<Entry>>::iterator it) {
  Entry& e = *it->second;
  if (e.charged) {
    e.charged = false;
    charged_ -= e.bytes();
    if (charged_ < 0) charged_ = 0;
  }
  entries_.erase(it);
}

void HotRowCache::Commit(const std::shared_ptr<Entry>& e, bool ok) {
  if (!e) return;
  // State published BEFORE any serve can see the entry as ready; the
  // release store pairs with ServeRun's acquire load so the fill's
  // writes into buf are visible to the serving memcpy.
  e->state.store(ok ? Entry::kReady : Entry::kFailed,
                 std::memory_order_release);
  if (ok) {
    cnt_.fills.fetch_add(1, std::memory_order_relaxed);
    cnt_.fill_bytes.fetch_add(e->bytes(), std::memory_order_relaxed);
    return;
  }
  cnt_.fill_failures.fetch_add(1, std::memory_order_relaxed);
  // A failed fill's slot is useless: remove it (budget released
  // exactly once — an eviction that raced us already flipped
  // `charged`, and the erase below then finds a different or missing
  // entry and does nothing).
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(std::make_pair(e->name, e->window));
  if (it != entries_.end() && it->second == e) RemoveLocked(it);
}

bool HotRowCache::ServeRun(const std::string& name, int64_t row0,
                           int64_t nrows, int64_t row_bytes, char* dst) {
  if (nrows <= 0) return false;
  std::shared_ptr<Entry> hit;
  size_t pos = 0;
  {
    std::lock_guard<std::mutex> lock(mu_);
    // Entries of one name are contiguous in the (name, window) map;
    // the readahead pipeline keeps only a handful live at once.
    for (auto it = entries_.lower_bound(std::make_pair(name, INT64_MIN));
         it != entries_.end() && it->first.first == name; ++it) {
      Entry& e = *it->second;
      if (e.state.load(std::memory_order_acquire) != Entry::kReady)
        continue;
      if (e.row_bytes != row_bytes) continue;  // re-registered geometry
      auto lb = std::lower_bound(e.rows.begin(), e.rows.end(), row0);
      if (lb == e.rows.end() || *lb != row0) continue;
      const size_t p = static_cast<size_t>(lb - e.rows.begin());
      if (p + nrows > e.rows.size()) continue;
      // Sorted unique rows: the run is fully, densely present iff the
      // last row sits exactly nrows-1 slots later.
      if (e.rows[p + nrows - 1] != row0 + nrows - 1) continue;
      hit = it->second;
      pos = p;
      break;
    }
  }
  const int64_t bytes = nrows * row_bytes;
  if (!hit) {
    cnt_.misses.fetch_add(1, std::memory_order_relaxed);
    cnt_.miss_bytes.fetch_add(bytes, std::memory_order_relaxed);
    return false;
  }
  // Copy outside the lock: the shared_ptr keeps the buffer alive
  // across a concurrent eviction, which is the race the ASan stress
  // block hammers.
  std::memcpy(dst, hit->buf.get() + pos * row_bytes,
              static_cast<size_t>(bytes));
  cnt_.hits.fetch_add(1, std::memory_order_relaxed);
  cnt_.hit_bytes.fetch_add(bytes, std::memory_order_relaxed);
  return true;
}

int HotRowCache::Evict(int64_t window,
                       std::vector<std::shared_ptr<Entry>>* out) {
  int n = 0;
  std::lock_guard<std::mutex> lock(mu_);
  for (auto it = entries_.begin(); it != entries_.end();) {
    if (window >= 0 && it->first.second != window) {
      ++it;
      continue;
    }
    if (out) out->push_back(it->second);
    cnt_.evictions.fetch_add(1, std::memory_order_relaxed);
    cnt_.evicted_bytes.fetch_add(it->second->bytes(),
                                 std::memory_order_relaxed);
    auto victim = it++;
    RemoveLocked(victim);
    ++n;
  }
  return n;
}

void HotRowCache::DropVar(const std::string& name,
                          std::vector<std::shared_ptr<Entry>>* out) {
  std::lock_guard<std::mutex> lock(mu_);
  for (auto it = entries_.lower_bound(std::make_pair(name, INT64_MIN));
       it != entries_.end() && it->first.first == name;) {
    if (out) out->push_back(it->second);
    cnt_.evictions.fetch_add(1, std::memory_order_relaxed);
    cnt_.evicted_bytes.fetch_add(it->second->bytes(),
                                 std::memory_order_relaxed);
    auto victim = it++;
    RemoveLocked(victim);
  }
}

void HotRowCache::Stats(int64_t out[13]) const {
  out[0] = cnt_.hits.load(std::memory_order_relaxed);
  out[1] = cnt_.hit_bytes.load(std::memory_order_relaxed);
  out[2] = cnt_.misses.load(std::memory_order_relaxed);
  out[3] = cnt_.miss_bytes.load(std::memory_order_relaxed);
  out[4] = cnt_.fills.load(std::memory_order_relaxed);
  out[5] = cnt_.fill_bytes.load(std::memory_order_relaxed);
  out[6] = cnt_.fill_failures.load(std::memory_order_relaxed);
  out[7] = cnt_.evictions.load(std::memory_order_relaxed);
  out[8] = cnt_.evicted_bytes.load(std::memory_order_relaxed);
  out[9] = cnt_.over_budget.load(std::memory_order_relaxed);
  out[10] = cnt_.prefetches.load(std::memory_order_relaxed);
  std::lock_guard<std::mutex> lock(mu_);
  out[11] = charged_;
  out[12] = static_cast<int64_t>(entries_.size());
}

void* ColdAlloc(const std::string& dir, int64_t bytes) {
  if (dir.empty() || bytes < 0) return nullptr;
  char path[4096];
  static std::atomic<uint64_t> seq{0};
  std::snprintf(path, sizeof(path), "%s/ddstore-cold-%ld-%llu.bin",
                dir.c_str(), static_cast<long>(::getpid()),
                static_cast<unsigned long long>(
                    seq.fetch_add(1, std::memory_order_relaxed)));
  const int fd = ::open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  // Unlink immediately: the mapping keeps the inode alive, the disk
  // space is reclaimed the moment the mapping (or the process) dies —
  // no free-path or crash can leak cold files.
  ::unlink(path);
  const size_t len = bytes > 0 ? static_cast<size_t>(bytes) : 1;
  if (::ftruncate(fd, static_cast<off_t>(len)) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* base =
      ::mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);  // the mapping holds its own reference
  return base == MAP_FAILED ? nullptr : base;
}

void ColdFree(void* base, int64_t bytes) {
  if (!base) return;
  ::munmap(base, bytes > 0 ? static_cast<size_t>(bytes) : 1);
}

}  // namespace tier
}  // namespace dds
