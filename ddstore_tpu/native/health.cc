#include "health.h"

#include <chrono>
#include <cstdlib>

#include "fault.h"
#include "trace.h"

namespace dds {

HealthMonitor::~HealthMonitor() { Stop(); }

void HealthMonitor::Init(int rank, int world) {
  std::lock_guard<std::mutex> lock(mu_);
  if (fails_) return;
  rank_ = rank;
  world_ = world > 0 ? world : 0;
  if (world_ > 0) {
    fails_.reset(new std::atomic<int>[world_]);
    suspected_.reset(new std::atomic<bool>[world_]);
    verdict_hold_.reset(new std::atomic<int>[world_]);
    for (int i = 0; i < world_; ++i) {
      fails_[i].store(0, std::memory_order_relaxed);
      suspected_[i].store(false, std::memory_order_relaxed);
      verdict_hold_[i].store(0, std::memory_order_relaxed);
    }
  }
}

void HealthMonitor::Start(long interval_ms, int suspect_n,
                          std::function<bool(int)> pinger) {
  Stop();
  std::lock_guard<std::mutex> lock(mu_);
  if (interval_ms <= 0 || world_ <= 1 || !pinger) return;
  interval_ms_ = interval_ms;
  suspect_n_ = suspect_n > 0 ? suspect_n : 1;
  pinger_ = std::move(pinger);
  stop_.store(false, std::memory_order_relaxed);
  running_.store(true, std::memory_order_relaxed);
  thread_ = std::thread([this] { Loop(); });
}

void HealthMonitor::Stop() {
  std::thread t;
  {
    std::lock_guard<std::mutex> lock(mu_);
    stop_.store(true, std::memory_order_relaxed);
    if (thread_.joinable()) t = std::move(thread_);
  }
  if (t.joinable()) t.join();
  running_.store(false, std::memory_order_relaxed);
}

void HealthMonitor::Loop() {
  while (!stop_.load(std::memory_order_relaxed)) {
    for (int t = 0; t < world_; ++t) {
      if (t == rank_) continue;
      if (stop_.load(std::memory_order_relaxed)) break;
      const bool ok = pinger_(t);
      pings_.fetch_add(1, std::memory_order_relaxed);
      if (ok) {
        fails_[t].store(0, std::memory_order_relaxed);
        // Heartbeat-raised suspicion clears on the first success (a
        // restarted/healed peer is not dead) — but a DATA-PATH ladder
        // verdict is stickier: the data port can be dead while the
        // listener still answers pings, and re-trusting such a peer
        // every interval would burn a fresh ladder per read. The
        // verdict needs suspect_n consecutive successes to clear
        // (which also restores a live peer the failover's naming
        // fallback retired by mistake, in ~suspect_n intervals).
        int hold = verdict_hold_[t].load(std::memory_order_relaxed);
        if (hold > 0)
          hold = verdict_hold_[t].fetch_sub(
                     1, std::memory_order_relaxed) - 1;
        if (hold <= 0 &&
            suspected_[t].exchange(false, std::memory_order_relaxed))
          trace::Ev(trace::kSuspectClear, rank_, t, 0, 0);
      } else {
        failures_.fetch_add(1, std::memory_order_relaxed);
        // A failure re-arms any draining verdict hold.
        if (verdict_hold_[t].load(std::memory_order_relaxed) > 0)
          verdict_hold_[t].store(suspect_n_, std::memory_order_relaxed);
        const int n = fails_[t].fetch_add(1, std::memory_order_relaxed) + 1;
        if (n >= suspect_n_ &&
            !suspected_[t].exchange(true, std::memory_order_relaxed)) {
          raised_.fetch_add(1, std::memory_order_relaxed);
          // Verdict moment: record it and snapshot every thread's last
          // events — the flight recorder's "who was doing what when
          // the peer died" story (0 = heartbeat-raised).
          trace::Ev(trace::kSuspect, rank_, t, 0, 0);
          trace::Flight(trace::kReasonSuspect, rank_);
        }
      }
    }
    // Interruptible sleep (<= 50 ms slices): teardown must not wait out
    // an interval.
    FaultSleepMs(interval_ms_, &stop_);
  }
  running_.store(false, std::memory_order_relaxed);
}

bool HealthMonitor::Suspected(int target) const {
  if (!suspected_ || target < 0 || target >= world_) return false;
  return suspected_[target].load(std::memory_order_relaxed);
}

void HealthMonitor::MarkSuspected(int target) {
  if (!suspected_ || target < 0 || target >= world_) return;
  verdict_hold_[target].store(suspect_n_ > 0 ? suspect_n_ : 1,
                              std::memory_order_relaxed);
  if (!suspected_[target].exchange(true, std::memory_order_relaxed)) {
    raised_.fetch_add(1, std::memory_order_relaxed);
    // Data-path ladder verdict (1 = ladder-raised), with a flight
    // snapshot: with replication in force kErrPeerLost never SURFACES
    // (the read fails over) — this transition is the postmortem
    // moment, and it runs under the failing read's span.
    trace::Ev(trace::kSuspect, rank_, target, 1, 0);
    trace::Flight(trace::kReasonSuspect, rank_);
  }
}

void HealthMonitor::ResetPeer(int target) {
  if (!suspected_ || target < 0 || target >= world_) return;
  fails_[target].store(0, std::memory_order_relaxed);
  verdict_hold_[target].store(0, std::memory_order_relaxed);
  if (suspected_[target].exchange(false, std::memory_order_relaxed))
    trace::Ev(trace::kSuspectClear, rank_, target, 0, 0);
}

int HealthMonitor::SuspectFlags(int64_t* out, int cap) const {
  if (!out || cap <= 0 || !suspected_) return 0;
  const int n = world_ < cap ? world_ : cap;
  for (int i = 0; i < n; ++i)
    out[i] = suspected_[i].load(std::memory_order_relaxed) ? 1 : 0;
  return n;
}

int HealthMonitor::SuspectedCount() const {
  if (!suspected_) return 0;
  int n = 0;
  for (int i = 0; i < world_; ++i)
    if (suspected_[i].load(std::memory_order_relaxed)) ++n;
  return n;
}

void HealthMonitor::Counters(int64_t out[4]) const {
  out[0] = pings_.load(std::memory_order_relaxed);
  out[1] = failures_.load(std::memory_order_relaxed);
  out[2] = raised_.load(std::memory_order_relaxed);
  out[3] = running() ? 1 : 0;
}

long HeartbeatIntervalMsFromEnv(int replication) {
  if (const char* env = std::getenv("DDSTORE_HEARTBEAT_MS")) {
    char* end = nullptr;
    long v = std::strtol(env, &end, 10);
    if (end != env && v >= 0) return v;
  }
  return replication > 1 ? 250 : 0;
}

int HeartbeatSuspectNFromEnv() {
  if (const char* env = std::getenv("DDSTORE_HEARTBEAT_SUSPECT_N")) {
    char* end = nullptr;
    long v = std::strtol(env, &end, 10);
    if (end != env && v > 0) return static_cast<int>(v);
  }
  return 3;
}

}  // namespace dds
