// Persistent worker pool for the transport hot path.
//
// Round 1 spawned a fresh std::thread per remote peer per GetBatch call and
// another per connection per striped ReadV — thread creation/join on every
// batch (the TCP analogue of the reference's per-call fi_mr_reg cliff,
// /root/reference/src/common.cxx:314-323, which SURVEY §7 flags as the
// anti-pattern to not reproduce). This pool keeps a small set of persistent
// threads; callers submit leaf tasks through a TaskGroup and wait on a
// counter. Tasks never submit nested tasks that are themselves waited on
// from inside the pool (the batched-read path flattens peer×connection
// fan-out into one task list first), so the pool cannot self-deadlock; the
// submitting thread additionally runs one task inline, guaranteeing
// progress even with zero pool threads available.

#ifndef DDSTORE_TPU_WORKER_POOL_H_
#define DDSTORE_TPU_WORKER_POOL_H_

#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include "thread_annotations.h"

namespace dds {

class WorkerPool {
 public:
  // Threads are created lazily, up to `max_threads`, and persist until
  // destruction.
  explicit WorkerPool(int max_threads);
  ~WorkerPool();

  WorkerPool(const WorkerPool&) = delete;
  WorkerPool& operator=(const WorkerPool&) = delete;

  // Enqueue fn; never blocks. Spawns a new persistent thread when all
  // existing ones are busy and the cap allows.
  void Submit(std::function<void()> fn);

  // Enqueue a whole burst under ONE lock acquisition + one broadcast
  // wake, provisioning threads for the burst's width in the same pass —
  // the lane-striped fan-out dispatches peers × lanes leaves at once,
  // where per-leaf lock+notify is measurable overhead.
  void SubmitMany(std::vector<std::function<void()>> fns);

  int max_threads() const { return max_threads_; }

 private:
  void WorkerLoop();

  const int max_threads_;
  // Queue mutex: dispatch hot path (one acquisition per burst), no
  // blocking call may run under it.
  std::mutex mu_ DDS_NO_BLOCKING;
  std::condition_variable cv_;
  std::deque<std::function<void()>> queue_ DDS_GUARDED_BY(mu_);
  std::vector<std::thread> threads_ DDS_GUARDED_BY(mu_);
  int idle_ DDS_GUARDED_BY(mu_) = 0;
  bool stopping_ DDS_GUARDED_BY(mu_) = false;
};

// Tracks a batch of tasks submitted to a pool; Wait() blocks until all
// complete. Reusable after Wait() returns. The counter state is held by
// shared_ptr so an in-flight task's completion can never touch a
// destroyed TaskGroup (the waiter may destroy the group the moment
// Wait() returns).
class TaskGroup {
 public:
  explicit TaskGroup(WorkerPool* pool);

  // Submit fn to the pool as part of this group.
  void Launch(std::function<void()> fn);
  // Submit a burst as one batch (WorkerPool::SubmitMany).
  void LaunchMany(std::vector<std::function<void()>> fns);
  // Block until every launched task has finished.
  void Wait();

 private:
  struct State {
    std::mutex mu;  // no blocking under it: completion-count bumps only
    std::condition_variable cv;
    int64_t pending DDS_GUARDED_BY(State::mu) = 0;
  };
  WorkerPool* pool_;
  std::shared_ptr<State> state_;
};

}  // namespace dds

#endif  // DDSTORE_TPU_WORKER_POOL_H_
