#include "gateway.h"

#include <chrono>

#include "store.h"  // error codes

namespace dds {
namespace gw {

void Gateway::Configure(const Config& c) {
  {
    std::lock_guard<std::mutex> lock(cfg_mu_);
    cfg_ = c;
  }
  defer_ms_.store(c.defer_ms > 0 ? c.defer_ms : 1,
                  std::memory_order_relaxed);
  queue_cap_.store(c.queue_cap > 0 ? c.queue_cap : 1,
                   std::memory_order_relaxed);
  if (c.enabled) draining_.store(false, std::memory_order_relaxed);
  enabled_.store(c.enabled ? 1 : 0, std::memory_order_relaxed);
  // Deferred waiters re-check enabled/draining on wakeup.
  admit_cv_.notify_all();
}

Config Gateway::config() const {
  std::lock_guard<std::mutex> lock(cfg_mu_);
  return cfg_;
}

int64_t Gateway::Attach(int rank, const std::string& tenant,
                        int64_t snap_id, int64_t quota_bytes,
                        uint64_t now_ns, bool* first_of_tenant) {
  if (first_of_tenant) *first_of_tenant = false;
  if (draining_.load(std::memory_order_relaxed)) return 0;
  long lease_ms;
  {
    std::lock_guard<std::mutex> lock(cfg_mu_);
    lease_ms = cfg_.lease_ms;
  }
  std::lock_guard<std::mutex> lock(lease_mu_);
  const int64_t token =
      (static_cast<int64_t>(rank) << 32) | ++token_counter_;
  Session s;
  s.tenant = tenant;
  s.snap_id = snap_id;
  s.quota_bytes = quota_bytes;
  s.deadline_ns = now_ns + static_cast<uint64_t>(lease_ms) * 1000000ull;
  sessions_[token] = std::move(s);
  if (++tenant_sessions_[tenant] == 1 && first_of_tenant)
    *first_of_tenant = true;
  ++attaches_;
  return token;
}

int Gateway::Renew(int64_t token, uint64_t now_ns) {
  long lease_ms;
  {
    std::lock_guard<std::mutex> lock(cfg_mu_);
    lease_ms = cfg_.lease_ms;
  }
  std::lock_guard<std::mutex> lock(lease_mu_);
  auto it = sessions_.find(token);
  if (it == sessions_.end()) return kErrNotFound;
  it->second.deadline_ns =
      now_ns + static_cast<uint64_t>(lease_ms) * 1000000ull;
  ++renewals_;
  return kOk;
}

int Gateway::Detach(int64_t token, SessionInfo* out,
                    bool* last_of_tenant) {
  if (last_of_tenant) *last_of_tenant = false;
  std::lock_guard<std::mutex> lock(lease_mu_);
  auto it = sessions_.find(token);
  if (it == sessions_.end()) return kErrNotFound;
  if (out) {
    out->token = token;
    out->tenant = it->second.tenant;
    out->snap_id = it->second.snap_id;
    out->quota_bytes = it->second.quota_bytes;
  }
  auto tit = tenant_sessions_.find(it->second.tenant);
  if (tit != tenant_sessions_.end() && --tit->second <= 0) {
    tenant_sessions_.erase(tit);
    if (last_of_tenant) *last_of_tenant = true;
  }
  sessions_.erase(it);
  ++detaches_;
  admit_cv_.notify_all();  // a freed lease slot may clear pressure
  return kOk;
}

void Gateway::ExpireLeases(uint64_t now_ns, std::vector<SessionInfo>* out,
                           std::vector<std::string>* last_tenants) {
  std::lock_guard<std::mutex> lock(lease_mu_);
  for (auto it = sessions_.begin(); it != sessions_.end();) {
    if (it->second.deadline_ns > now_ns) {
      ++it;
      continue;
    }
    if (out) {
      SessionInfo si;
      si.token = it->first;
      si.tenant = it->second.tenant;
      si.snap_id = it->second.snap_id;
      si.quota_bytes = it->second.quota_bytes;
      out->push_back(std::move(si));
    }
    auto tit = tenant_sessions_.find(it->second.tenant);
    if (tit != tenant_sessions_.end() && --tit->second <= 0) {
      if (last_tenants) last_tenants->push_back(tit->first);
      tenant_sessions_.erase(tit);
    }
    it = sessions_.erase(it);
    ++expired_;
  }
}

bool Gateway::HoldsSnapshot(int64_t snap_id) const {
  if (snap_id == 0) return false;
  std::lock_guard<std::mutex> lock(lease_mu_);
  for (const auto& kv : sessions_)
    if (kv.second.snap_id == snap_id) return true;
  return false;
}

int64_t Gateway::SessionCount() const {
  std::lock_guard<std::mutex> lock(lease_mu_);
  return static_cast<int64_t>(sessions_.size());
}

long Gateway::RetryAfterMsLocked() const {
  // Deeper backlog ⇒ longer hint: one defer window per queued slot
  // ahead of the caller, clamped so clients never park for minutes.
  const long defer = defer_ms_.load(std::memory_order_relaxed);
  long hint = defer * (1 + waiting_);
  if (hint > 60000) hint = 60000;
  if (hint < defer) hint = defer;
  return hint;
}

int Gateway::Admit(bool is_protected,
                   const std::function<bool()>& pressure,
                   const std::atomic<bool>* stop, long* retry_after_ms) {
  if (retry_after_ms) *retry_after_ms = 0;
  if (draining_.load(std::memory_order_relaxed)) {
    std::lock_guard<std::mutex> lock(admit_mu_);
    ++rejected_;
    ++drain_sheds_;
    last_retry_after_ms_ = RetryAfterMsLocked();
    if (retry_after_ms) *retry_after_ms = last_retry_after_ms_;
    return kErrAdmission;
  }
  if (is_protected || !pressure || !pressure()) {
    std::lock_guard<std::mutex> lock(admit_mu_);
    ++admitted_;
    return kOk;
  }
  // Over-share tenant under pressure: defer in a bounded queue,
  // re-checking as completions/detaches signal, then reject.
  const long defer = defer_ms_.load(std::memory_order_relaxed);
  const auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::milliseconds(defer);
  std::unique_lock<std::mutex> lk(admit_mu_);
  if (waiting_ >= queue_cap_.load(std::memory_order_relaxed)) {
    ++rejected_;
    last_retry_after_ms_ = RetryAfterMsLocked();
    if (retry_after_ms) *retry_after_ms = last_retry_after_ms_;
    return kErrAdmission;
  }
  ++waiting_;
  ++deferred_;
  for (;;) {
    if (draining_.load(std::memory_order_relaxed) ||
        (stop && stop->load(std::memory_order_relaxed)))
      break;
    // `pressure` reads store metrics (its own leaf locks) — legal
    // under admit_mu_ (nothing takes admit_mu_ under store locks),
    // and holding it keeps the slot accounting consistent.
    if (!pressure()) {
      --waiting_;
      ++admitted_;
      admit_cv_.notify_all();
      return kOk;
    }
    const auto now = std::chrono::steady_clock::now();
    if (now >= deadline) break;
    // Slice the wait so pressure decay (histogram windows move even
    // without completions) is noticed without a wakeup.
    auto slice = deadline - now;
    if (slice > std::chrono::milliseconds(5))
      slice = std::chrono::milliseconds(5);
    admit_cv_.wait_for(lk, slice);
  }
  --waiting_;
  ++rejected_;
  if (draining_.load(std::memory_order_relaxed)) ++drain_sheds_;
  last_retry_after_ms_ = RetryAfterMsLocked();
  if (retry_after_ms) *retry_after_ms = last_retry_after_ms_;
  admit_cv_.notify_all();
  return kErrAdmission;
}

void Gateway::OpBegin() {
  std::lock_guard<std::mutex> lock(admit_mu_);
  ++inflight_;
}

void Gateway::OpEnd() {
  std::lock_guard<std::mutex> lock(admit_mu_);
  if (inflight_ > 0) --inflight_;
  // Completions are the admission gate's wakeup edge: a deferred
  // request re-checks pressure as soon as load drains.
  admit_cv_.notify_all();
}

int Gateway::Drain(long deadline_ms, const std::atomic<bool>* stop) {
  draining_.store(true, std::memory_order_relaxed);
  const auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::milliseconds(
                            deadline_ms > 0 ? deadline_ms : 0);
  std::unique_lock<std::mutex> lk(admit_mu_);
  admit_cv_.notify_all();  // deferred waiters shed immediately
  while (inflight_ > 0) {
    if (stop && stop->load(std::memory_order_relaxed)) break;
    const auto now = std::chrono::steady_clock::now();
    if (now >= deadline) break;
    auto slice = deadline - now;
    if (slice > std::chrono::milliseconds(10))
      slice = std::chrono::milliseconds(10);
    admit_cv_.wait_for(lk, slice);
  }
  return inflight_ == 0 ? kOk : kErrTransport;
}

void Gateway::Stats(int64_t out[kGwStatSlots]) const {
  for (int i = 0; i < kGwStatSlots; ++i) out[i] = 0;
  out[0] = enabled_.load(std::memory_order_relaxed);
  out[10] = draining_.load(std::memory_order_relaxed) ? 1 : 0;
  {
    std::lock_guard<std::mutex> lock(lease_mu_);
    out[1] = static_cast<int64_t>(sessions_.size());
    out[2] = attaches_;
    out[3] = detaches_;
    out[4] = expired_;
    out[5] = renewals_;
  }
  {
    std::lock_guard<std::mutex> lock(admit_mu_);
    out[6] = admitted_;
    out[7] = deferred_;
    out[8] = rejected_;
    out[9] = drain_sheds_;
    out[11] = inflight_;
    out[12] = waiting_;
    out[13] = last_retry_after_ms_;
  }
}

}  // namespace gw
}  // namespace dds
